"""
Linear estimator kernels: logistic regression, linear SVC, SGD linear
models, ridge / ridge classifier, OLS.

These supply the compute the reference borrowed from sklearn's
liblinear/lbfgs C solvers (used as the base estimator in nearly every
sk-dist example, e.g. ``/root/reference/examples/search/basic_usage.py:99``).
Each estimator is built around pure, jit/vmap-able kernels:

- ``_build_fit_kernel(static)`` → ``kernel(X, y, sample_weight, hyper)``
  returning fitted parameters. ``hyper`` values are *traced* scalars, so
  a grid of hyperparameter candidates vmaps into ONE XLA program; the
  distributed search stacks (candidate × fold) tasks on that axis and
  shards it over the TPU mesh.
- fold selection is by **sample weight masking**, never row slicing —
  static shapes are what keep XLA happy (SURVEY §7.3 item 1).

Objectives match sklearn's parameterisations where sklearn defines them:
LogisticRegression minimises ``Σ s_i·ce_i + 0.5/C·‖w‖²`` (no intercept
penalty), LinearSVC minimises ``0.5‖w‖² + C·Σ s_i·max(0, 1-y·f)²``
(squared hinge; unlike liblinear we do not penalise the intercept).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin
from ..sparse import (
    LinearOperator,
    PackedX,
    matvec_any,
    pack_for_fit,
    resolve_matvec_mode,
    sparse_to_dense_f32,
    would_pack,
)
from .solvers import (
    carry_iterate,
    lbfgs_carry_init,
    lbfgs_minimize,
    lbfgs_resume,
    sgd_carry_init,
    sgd_minimize,
    sgd_resume,
)

__all__ = [
    "LogisticRegression",
    "LinearSVC",
    "SGDClassifier",
    "Ridge",
    "RidgeClassifier",
    "LinearRegression",
]


# --------------------------------------------------------------------------
# data plumbing
# --------------------------------------------------------------------------

def as_dense_f32(X):
    """Convert input to a dense float32 ndarray (TPU-resident layout).

    Sparse input is densified through ``sparse.sparse_to_dense_f32``
    (budget guardrail, native multithreaded densifier at device-feeding
    sizes, 1-D ``csr_array`` column-vector handling). Callers on the
    FIT path should prefer :func:`prepare_fit_X`, which keeps packable
    sparse input packed (``skdist_tpu.sparse``) instead of densifying.
    """
    if hasattr(X, "toarray"):  # scipy sparse
        return sparse_to_dense_f32(X)
    elif hasattr(X, "values") and not isinstance(X, np.ndarray):  # pandas
        X = X.values
    X = np.asarray(X)
    if X.ndim == 1:
        X = X.reshape(-1, 1)
    return np.ascontiguousarray(X, dtype=np.float32)


def prepare_fit_X(X, est=None):
    """Fit-plane input routing: a :class:`~skdist_tpu.sparse.PackedX`
    when the packed-CSR sparse plane wins for this input AND the
    estimator family consumes it (``_supports_packed_X`` — the linear
    families), else a dense float32 ndarray. The predict-side entry
    points route through this too, so a sparse-fit model scores sparse
    input without ever materialising the dense matrix."""
    cls = (
        est if isinstance(est, type)
        else (type(est) if est is not None else None)
    )
    if cls is None or getattr(cls, "_supports_packed_X", False):
        packed = pack_for_fit(X)
        if packed is not None:
            return packed
    return as_dense_f32(X)


def fit_would_pack(X, est=None):
    """Whether :func:`prepare_fit_X` would return a ``PackedX`` for
    this (input, estimator) pair — the same routing, decided from
    shape/``indptr`` alone with no conversion or packing. Callers use
    it to order bails (e.g. the host-engine gate) BEFORE paying
    ``prepare_fit_X``'s dense f32 copy for input that will not pack."""
    cls = (
        est if isinstance(est, type)
        else (type(est) if est is not None else None)
    )
    if cls is not None and not getattr(cls, "_supports_packed_X", False):
        return False
    return would_pack(X)


def host_stage(x):
    """Stage an array for backend placement: host arrays stay host,
    device arrays stay put.

    ``_prep_fit_data`` used to ``jnp.asarray`` every leaf, which
    performed an eager uncommitted default-device transfer that the
    backend's ``batched_map`` immediately re-placed with a sharded
    ``device_put`` — and which made the reuse-broadcast cache inert
    (it keys on HOST array identity). Staying host defers the single
    transfer to the placement layer, where sharding and the opt-in
    reuse cache live.
    """
    if isinstance(x, PackedX):
        return PackedX(host_stage(x.idx), host_stage(x.val), x.n_cols)
    if hasattr(x, "sharding"):  # already a jax array: leave it be
        return x
    return np.asarray(x)


def encode_labels(y):
    """y → (int32 indices, classes array)."""
    y = np.asarray(y)
    if y.ndim == 2 and y.shape[1] == 1:
        y = y.ravel()
    classes, y_idx = np.unique(y, return_inverse=True)
    return y_idx.astype(np.int32), classes


def prepare_sample_weight(sample_weight, n):
    """Normalise user weights to a (n,) f32 vector.

    Accepts scalars (broadcast), (n,) vectors, and (n, 1) columns
    (flattened — a 2-D column would otherwise broadcast against the
    (n,) per-sample loss into an (n, n) matrix and silently corrupt
    the fit). Anything else is rejected loudly.
    """
    if sample_weight is None:
        return np.ones(n, dtype=np.float32)
    sw = np.asarray(sample_weight, dtype=np.float32)
    if sw.ndim == 0:
        return np.full(n, float(sw), dtype=np.float32)
    if sw.ndim == 2 and sw.shape[1] == 1:
        sw = sw.ravel()
    if sw.shape != (n,):
        raise ValueError(
            f"sample_weight has shape {np.shape(sample_weight)}; expected "
            f"({n},), ({n}, 1) or a scalar"
        )
    return sw


def class_weight_vector(class_weight, classes):
    """Per-class multiplier array, or None. 'balanced' resolves on device
    from effective (masked) counts inside the kernel."""
    if class_weight is None or class_weight == "balanced":
        return None
    arr = np.ones(len(classes), dtype=np.float32)
    for i, c in enumerate(classes):
        key = c.item() if hasattr(c, "item") else c
        if c in class_weight:
            arr[i] = class_weight[c]
        elif key in class_weight:
            arr[i] = class_weight[key]
        # classes absent from the dict keep weight 1 (sklearn semantics)
    return arr


def _apply_class_weight(sw, y_idx, n_classes, class_weight, cw_arr):
    """Apply class weighting on device. 'balanced' uses the weighted
    class counts of the *current* (possibly fold-masked) sample weights,
    matching sklearn's balanced heuristic n/(k·count_c)."""
    if class_weight is None:
        return sw
    onehot = jax.nn.one_hot(y_idx, n_classes, dtype=sw.dtype)
    if class_weight == "balanced":
        counts = onehot.T @ sw  # (k,)
        total = jnp.sum(sw)
        per_class = total / (n_classes * jnp.maximum(counts, 1e-12))
        per_class = jnp.where(counts > 0, per_class, 0.0)
    else:
        per_class = jnp.asarray(cw_arr)
    return sw * (onehot @ per_class)


# --------------------------------------------------------------------------
# shared linear-model machinery
# --------------------------------------------------------------------------

#: reserved keys of the _prep_fit_data data dict; everything else is
#: per-estimator fit context forwarded to kernels as ``aux``
RESERVED_DATA_KEYS = ("X", "y", "sw")


def hyper_float(value):
    """A ``_hyper_names`` value as float32. sklearn's ``tol=None``
    ("no early stopping") maps to ``-inf`` so the traced threshold
    comparison can never trigger — no other hyper accepts None."""
    return np.float32(-np.inf if value is None else value)


def extract_aux(data):
    return {k: v for k, v in data.items() if k not in RESERVED_DATA_KEYS}


def exact_matmuls(fn):
    """Trace ``fn`` under ``jax.default_matmul_precision('highest')``.

    TPU's default f32 matmul runs reduced-precision MXU passes; for the
    solver kernels that breaks the ≤1e-5 batched-vs-generic cv_results_
    parity contract (measured: 9.7e-4 default vs 1.5e-8 highest on the
    20news-shaped headline workload) — and measured *faster* end-to-end
    (21.3 vs 14.4 fits/sec), since L-BFGS converges in fewer, cleaner
    steps. Opt-in reduced precision stays available via
    ``matmul_dtype='bfloat16'``, whose dot_generals pin their own
    precision explicitly.

    Estimator classes opt out with ``_exact_matmuls = False`` (the tree
    kernels do: their one-hot/count matmul operands are exact in the
    reduced passes, so 'highest' would cost extra MXU passes for zero
    accuracy — every consumer site honours the flag so a tree compiles
    identically standalone, under a grid search, and inside a forest).
    """
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapped


def maybe_exact_matmuls(cls, fn):
    """Apply :func:`exact_matmuls` unless ``cls`` opts out via
    ``_exact_matmuls = False`` — the single decision point for every
    kernel consumer (get_kernel, the cv kernel, the multiclass batched
    paths), so the opt-out semantics can't drift between sites."""
    return exact_matmuls(fn) if getattr(cls, "_exact_matmuls", True) else fn


def _meta_signature(meta):
    cw = meta.get("cw_arr")
    return (
        meta["n_features"],
        meta.get("n_classes"),
        tuple(cw.tolist()) if cw is not None else None,
        meta.get("y_ndim"),
        # the sparse plane is compile-shaping: a packed-X kernel and a
        # dense-X kernel of the same family must never share a cache
        # entry, and neither must two packed matvec modes
        meta.get("x_format"),
        meta.get("x_matvec"),
    )


def _annotate_x_meta(meta, X):
    """Record the fit-data representation in ``meta`` — consumed by the
    kernel builders (packed vs dense problems) and by
    :func:`_meta_signature` (structural compile keys)."""
    if isinstance(X, PackedX):
        meta["x_format"] = "packed"
        meta["x_matvec"] = resolve_matvec_mode()
    return meta


def _annotate_stream_meta(meta, dataset):
    """The ChunkedDataset analogue of :func:`_annotate_x_meta`: a
    packed dataset's blocks run the packed kernels, and the
    representation participates in the structural compile keys exactly
    as on the resident path."""
    if getattr(dataset, "x_format", "dense") == "packed":
        meta["x_format"] = "packed"
        meta["x_matvec"] = resolve_matvec_mode()
    return meta


def kernel_mode_of(meta):
    """The kernel variant a fit with this ``meta`` runs — ``"dense"``
    or ``"packed_<matvec mode>"`` for the matvec families, or the
    family tag a non-linear family stamps in ``meta["kernel_family"]``
    (the GBDT histogram trees stamp ``"hist_tree"``). The batched
    dispatch sites stamp it into
    ``backend.last_round_stats["kernel_mode"]`` so round observability
    (and the chip-leg bench captures) can attribute walls to the
    kernel that actually ran."""
    family = meta.get("kernel_family")
    if family is not None:
        return family
    if meta.get("x_format") == "packed":
        return "packed_" + meta.get("x_matvec", "gather")
    return "dense"


def annotate_round_kernel_mode(backend, meta):
    """Stamp :func:`kernel_mode_of` onto the backend's most recent
    round stats (no-op when the backend has none), and bill the
    registry's per-kernel-mode dispatch counter — the stamp happens
    AFTER the dispatch published its RoundStats, so the registry leg
    records it here."""
    stats = getattr(backend, "last_round_stats", None)
    if isinstance(stats, dict):
        mode = stats["kernel_mode"] = kernel_mode_of(meta)
        from ..obs import metrics as obs_metrics

        obs_metrics.counter("rounds.kernel_mode").inc(
            1, kernel_mode=str(mode)
        )


def _linear_op(X, fit_intercept, meta, matmul_dtype=None):
    """The one construction point of the fit problems' matvec
    interface (``sparse.LinearOperator``): dense X reproduces the
    historical expressions verbatim; packed X routes through the
    gather/scatter kernels in the mode ``meta`` resolved at prep
    time."""
    return LinearOperator(
        X, fit_intercept, matmul_dtype=matmul_dtype,
        mode=meta.get("x_matvec", "gather"),
    )


def get_kernel(cls, which, meta, static):
    """Fetch a (possibly jitted) kernel from the process-wide cache.

    Kernel builders return fresh closures; caching on the *structural*
    key (class qualname, static config, meta signature — see
    ``parallel.compile_cache.structural_key``) keeps jax.jit's own
    cache hot across estimator instances — without this every `.fit()`
    would recompile. The same memo records hit/miss counters for
    benchmark/test observability.
    """
    from ..parallel import compile_cache

    sig = compile_cache.structural_key(
        f"kernel:{which}", cls, static, _meta_signature(meta)
    )

    def build():
        fn = maybe_exact_matmuls(
            cls, getattr(cls, f"_build_{which}_kernel")(meta, static)
        )
        if which == "fit":
            fn = jax.jit(fn)
        return fn

    return compile_cache.kernel_memo(sig, build)


class _LinearModelBase(BaseEstimator):
    """Common fitted-state handling + the batched-fit contract.

    Batched-fit contract (consumed by ``distribute.search`` et al.):

    - ``_hyper_names``: constructor params that become traced scalars on
      the task axis (safe to vary within one compiled program)
    - ``_static_names``: params that change the compiled program (loop
      bounds, booleans, strings); candidates differing here are bucketed
      into separate compilations by the scheduler
    - ``_prep_fit_data(X, y, sample_weight)`` → (device pytree, meta)
    - ``_build_fit_kernel(meta, static)`` → pure fit kernel
    - ``_build_decision_kernel(meta, static)`` → params, X → raw scores
    """

    _hyper_names = ()
    _static_names = ()

    #: the linear families consume packed-CSR X (skdist_tpu.sparse)
    #: through the fit problems' matvec interface; families without the
    #: flag always receive dense input from :func:`prepare_fit_X`
    _supports_packed_X = True

    #: streamed-fit family kind consumed by ``models/streaming.py``:
    #: "lbfgs" (block-accumulated value/grad), "sgd" (block-stream
    #: epochs), "gram" (block-accumulated normal equations); None =
    #: family has no out-of-core fit
    _stream_fit_kind = None

    # ---- host-facing API -------------------------------------------------
    def fit(self, X, y=None, sample_weight=None, coef_init=None,
            intercept_init=None):
        """Fit. ``coef_init``/``intercept_init`` (sklearn shapes — a
        parent fit's ``coef_``/``intercept_``) warm-start the
        iterative families' solver carry: the L-BFGS and SGD solves
        start from the seed instead of zeros, so a refit on drifted
        data converges in a fraction of the cold iterations (the
        catalog refresh loop's public seeding surface). Closed-form
        families (the ridge/OLS direct solve) accept the seeds and
        ignore them — a direct solve has no iterate to seed, and
        accepting keeps cohort refresh generic across families."""
        from ..data import is_chunked

        if is_chunked(X):
            # out-of-core path: blocks stream through the backend's
            # double-buffered pipeline; labels/weights ride the dataset
            # (or come explicitly) as O(n) host vectors
            from .streaming import stream_fit_estimator

            return stream_fit_estimator(
                self, X, y, sample_weight,
                coef_init=coef_init, intercept_init=intercept_init,
            )
        if y is None:
            raise TypeError(
                f"{type(self).__name__}.fit requires y (only a "
                "ChunkedDataset carries its own labels)"
            )
        # packed input has no host (f64 BLAS) form: under engine='auto'
        # the packed XLA path IS the sparse engine on every platform —
        # densifying a packable hashed-text input to reach scipy would
        # reintroduce the exact host-RAM blowup this plane removes. An
        # EXPLICIT engine='host' pin is still honoured: it densifies
        # (the budget guardrail speaks when that cannot work).
        if getattr(self, "engine", None) == "host":
            X = as_dense_f32(X)
        else:
            X = prepare_fit_X(X, type(self))
        warm = coef_init is not None or intercept_init is not None
        if not isinstance(X, PackedX) and self._resolve_host_engine():
            if warm:
                # the host engines already honour a flat `_warm_w0`
                # seed (the warm C-path runner's seam); scoped so a
                # later cold fit never inherits this one's seed
                self._warm_w0 = self._warm_w0_flat(
                    X.shape[1], self._warm_n_out(y),
                    coef_init, intercept_init,
                ).astype(np.float64)
                try:
                    return self._host_fit(X, y, sample_weight)
                finally:
                    del self._warm_w0
            return self._host_fit(X, y, sample_weight)
        data, meta = self._prep_fit_data(X, y, sample_weight)
        static = self._static_config(meta)
        hyper = {k: jnp.asarray(hyper_float(getattr(self, k)))
                 for k in self._hyper_names}
        kernel = get_kernel(type(self), "fit", meta, _freeze(static))
        if warm:
            k = meta.get("n_classes", 2)
            w0 = self._warm_w0_flat(
                meta["n_features"], 1 if k <= 2 else k,
                coef_init, intercept_init,
            )
            params = kernel(data["X"], data["y"], data["sw"], hyper,
                            {"w0": jnp.asarray(w0)})
        else:
            params = kernel(data["X"], data["y"], data["sw"], hyper)
        self._set_fitted(params, meta)
        return self

    def _warm_n_out(self, y):
        """Solver output columns for warm-seed shaping, before meta
        exists: classifiers fold binary to one column (the families'
        flat layout), regressors are single-output."""
        if isinstance(self, ClassifierMixin):
            k = int(np.unique(np.asarray(y)).size)
            return 1 if k <= 2 else k
        return 1

    def _warm_w0_flat(self, d, n_out, coef_init, intercept_init):
        """Map sklearn-shaped warm-start seeds (a parent fit's
        ``coef_``/``intercept_``) onto the family's flat solver
        layout: ``W`` is ``(p, n_out)`` with rows ``[:d]`` the
        coefficients and row ``d`` the intercept (when fitted),
        flattened to ``(p,)`` single-output / ``(p*n_out,)``
        multiclass — exactly the layout ``unpack`` reshapes and the
        host engines' ``x0`` consumes."""
        fit_intercept = self._fit_intercept_flag()
        d = int(d)
        n_out = int(n_out)
        p = d + (1 if fit_intercept else 0)
        W = np.zeros((p, n_out), np.float32)
        if coef_init is not None:
            coef = np.asarray(coef_init, np.float32)
            if n_out == 1:
                coef = coef.reshape(-1)
                if coef.shape[0] != d:
                    raise ValueError(
                        f"coef_init has {coef.shape[0]} features; the "
                        f"fit data has {d}"
                    )
                W[:d, 0] = coef
            elif coef.shape == (n_out, d):
                W[:d] = coef.T
            elif coef.shape == (d, n_out):
                W[:d] = coef
            else:
                raise ValueError(
                    f"coef_init shape {coef.shape} does not match "
                    f"({n_out}, {d}) (classes x features)"
                )
        if intercept_init is not None:
            b = np.asarray(intercept_init, np.float32).reshape(-1)
            if not fit_intercept:
                if np.any(b != 0):
                    raise ValueError(
                        "intercept_init is nonzero but "
                        "fit_intercept=False — this family fits no "
                        "intercept to seed"
                    )
            else:
                if b.shape[0] == 1 and n_out > 1:
                    b = np.repeat(b, n_out)
                if b.shape[0] != n_out:
                    raise ValueError(
                        f"intercept_init has {b.shape[0]} entries; "
                        f"expected {n_out}"
                    )
                W[d] = b
        return W.reshape(-1) if n_out > 1 else W[:, 0]

    def _resolve_host_engine(self):
        """True when this host-side fit should run the f64 BLAS engine
        (``models/host_linear.py``) instead of the XLA kernel.

        Estimators without a host engine always return False. With
        one: ``engine='xla'`` pins the compiled path (bit-identical to
        the mesh program — the agreement tests run under this pin),
        ``'host'`` forces the host engine, and ``'auto'`` picks host
        exactly when the default platform is a CPU — the situation the
        reference served with plain sklearn (its sc=None path) and
        where XLA-CPU prices are the wrong trade (round-4 VERDICT
        weak #6)."""
        if self._host_fit is None:
            return False
        engine = getattr(self, "engine", "xla")
        if engine not in ("auto", "host", "xla"):
            raise ValueError(
                f"engine must be 'auto', 'host' or 'xla'; got {engine!r}"
            )
        if engine == "xla":
            return False
        if engine == "host":
            return True
        if getattr(self, "matmul_dtype", None) == "bfloat16":
            return False  # explicit accelerator-precision opt-in
        import jax

        from .host_linear import host_engine_available

        return jax.default_backend() == "cpu" and host_engine_available()

    _host_fit = None  # subclasses with a host engine override

    def __getstate__(self):
        """Fitted artifacts pickle WITHOUT the warm-start scratch: the
        f64 optimum (`_w_opt64`) exists only to seed the next fit in a
        C path during a live search, and would otherwise triple a big
        model's pickle next to its f32 coefficients."""
        state = self.__dict__.copy()
        state.pop("_w_opt64", None)
        state.pop("_warm_w0", None)
        return state

    def _static_config(self, meta):
        return {k: getattr(self, k) for k in self._static_names}

    def _set_fitted(self, params, meta):
        self._params = jax.device_get(params)
        self._meta = meta
        self.n_features_in_ = meta["n_features"]
        if "classes" in meta:
            self.classes_ = meta["classes"]
        if "n_iter" in self._params:
            self.n_iter_ = np.asarray(self._params["n_iter"])

    def _check_fitted(self):
        if not hasattr(self, "_params"):
            raise AttributeError(
                f"This {type(self).__name__} instance is not fitted yet."
            )

    def decision_function(self, X):
        self._check_fitted()
        from ..data import is_chunked

        if is_chunked(X):
            raise TypeError(
                "decision_function does not take a ChunkedDataset; use "
                "skdist_tpu.distribute.batch_predict(model, dataset) "
                "(or predict/predict_proba, which route there) to "
                "stream inference block by block"
            )
        # sparse predict input stays packed when packing wins — the
        # decision kernels are representation-polymorphic (matvec_any)
        X = prepare_fit_X(X, type(self))
        static = _freeze(self._static_config(self._meta))
        kernel = get_kernel(type(self), "decision", self._meta, static)
        out = np.asarray(kernel(_to_jnp(self._params), _to_jnp(X)))
        return out

    @property
    def coef_(self):
        self._check_fitted()
        if "W" not in self._params:
            raise AttributeError(
                f"{type(self).__name__} has no linear coefficients"
            )
        W = np.asarray(self._params["W"])  # (d[+1], k) or (d[+1],)
        d = self.n_features_in_
        w = W[:d]
        if w.ndim == 1:
            return w.reshape(1, -1) if self._sklearn_2d_coef() else w
        return w.T

    @property
    def intercept_(self):
        self._check_fitted()
        if "W" not in self._params:
            raise AttributeError(
                f"{type(self).__name__} has no linear coefficients"
            )
        W = np.asarray(self._params["W"])
        d = self.n_features_in_
        if not self._fit_intercept_flag():
            k = 1 if W.ndim == 1 else W.shape[1]
            return np.zeros(k, dtype=W.dtype)
        b = W[d]
        return np.atleast_1d(b)

    def _fit_intercept_flag(self):
        return getattr(self, "fit_intercept", True)

    def _sklearn_2d_coef(self):
        return isinstance(self, ClassifierMixin)


def _freeze(d):
    """dict → hashable tuple (dict/list values frozen recursively so
    e.g. class_weight dicts can key the kernel cache)."""

    def fr(v):
        if isinstance(v, dict):
            return tuple(sorted((k, fr(x)) for k, x in v.items()))
        if isinstance(v, (list, tuple)):
            return tuple(fr(x) for x in v)
        return v

    return tuple(sorted((k, fr(v)) for k, v in d.items()))


def _to_jnp(tree):
    return jax.tree_util.tree_map(jnp.asarray, tree)


def _split_Wb(W, d, fit_intercept, n_out):
    """W (p,) or (p,k) → (weights, bias)."""
    if W.ndim == 1:
        w, b = W[:d], (W[d] if fit_intercept else jnp.zeros((), W.dtype))
    else:
        w = W[:d]
        b = W[d] if fit_intercept else jnp.zeros((W.shape[1],), W.dtype)
    return w, b


class _LinearClassifierBase(_LinearModelBase, ClassifierMixin):
    def _prep_stream_fit(self, dataset, y, sample_weight=None):
        """Streamed-fit prep: global label encoding + meta from O(n)
        host vectors and the dataset's shape — no X materialisation.
        Returns ``(y_idx (n,), sw (n,), meta)``; the streaming driver
        slices both per block."""
        if y is None:
            raise ValueError(
                f"{type(self).__name__} needs labels: the ChunkedDataset "
                "carries none and no y was passed"
            )
        y_idx, classes = encode_labels(y)
        sw = prepare_sample_weight(sample_weight, dataset.n_rows)
        if getattr(self, "class_weight", None) == "balanced":
            raise ValueError(
                "class_weight='balanced' needs a global pass over the "
                "masked weights and is not supported on the streamed "
                "fit path yet; pass an explicit class_weight dict"
            )
        meta = _annotate_stream_meta({
            "n_features": dataset.n_features,
            "classes": classes,
            "n_classes": len(classes),
            "cw_arr": class_weight_vector(
                getattr(self, "class_weight", None), classes
            ),
        }, dataset)
        return y_idx, sw, meta

    def _prep_fit_data(self, X, y, sample_weight=None):
        y_idx, classes = encode_labels(y)
        sw = prepare_sample_weight(sample_weight, X.shape[0])
        meta = _annotate_x_meta({
            "n_features": X.shape[1],
            "classes": classes,
            "n_classes": len(classes),
            "cw_arr": class_weight_vector(getattr(self, "class_weight", None), classes),
        }, X)
        data = {
            "X": host_stage(X),
            "y": host_stage(y_idx),
            "sw": host_stage(sw),
        }
        return data, meta

    def predict(self, X):
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict")
        scores = self.decision_function(X)
        if scores.ndim == 1:
            idx = (scores > 0).astype(np.int64)
        else:
            idx = np.argmax(scores, axis=1)
        return self.classes_[idx]


class _LbfgsFitMixin:
    """Fit kernels for the L-BFGS linear family, derived from one
    ``_build_fit_problem(meta, static)`` definition of the objective.

    ``_build_fit_problem`` returns ``problem(X, y_idx, sw, hyper) ->
    (loss, w0, unpack)`` where ``unpack(w, n_iter)`` shapes the fitted
    params dict. The plain fit kernel and the iteration-sliced variant
    (``_build_fit_slice_kernels`` — the convergence-compacted
    scheduler's contract) are both generated from it, so the two
    execution forms minimise the *same traced objective* and the sliced
    run is bitwise identical to the unsliced solve (see
    ``models/solvers.py``)."""

    #: batched-path marker consulted by the scheduler gates
    _supports_sliced_fit = True

    #: out-of-core fit form: block-accumulated value/grad through the
    #: streamed L-BFGS driver (models/streaming.py)
    _stream_fit_kind = "lbfgs"

    @classmethod
    def _flat_w_width(cls, meta, static):
        """Flat weight-vector width of this family's solve — what the
        streamed driver allocates per task without tracing a kernel."""
        st = dict(static)
        p = meta["n_features"] + (1 if st["fit_intercept"] else 0)
        k = meta.get("n_classes", 2)
        return p if k <= 2 else p * k

    @classmethod
    def _batched_task_cost(cls, hyper):
        """Per-task convergence-cost heuristic for round packing
        (``hyper``: dict of per-task f32 arrays). L-BFGS family: weak
        regularisation (large C) and tight tolerance both mean more
        iterations — log-additive so neither axis drowns the other;
        ``tol <= 0`` (the tol=None → -inf mapping) never converges and
        sorts last."""
        C = np.asarray(hyper.get("C", 1.0), dtype=np.float64)
        tol = np.asarray(hyper.get("tol", 1e-4), dtype=np.float64)
        # log only on the positive mask: tol=-inf (the tol=None
        # mapping) must select -inf via where, not evaluate log(-inf)
        cost = np.log(np.maximum(C, 1e-30)) - np.where(
            tol > 0, np.log(np.where(tol > 0, tol, 1.0)), -np.inf
        )
        return np.broadcast_to(cost, np.broadcast_shapes(C.shape, tol.shape))

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        problem = cls._build_fit_problem(meta, static)
        st = dict(static)
        max_iter, hist = st["max_iter"], st["history"]

        def kernel(X, y_idx, sw, hyper, aux=None):
            loss, w0, unpack = problem(X, y_idx, sw, hyper)
            if aux is not None and "w0" in aux:
                # warm start: the solve begins at the caller's seed
                # (a parent fit's coefficients in the flat layout)
                w0 = jnp.asarray(aux["w0"], w0.dtype).reshape(w0.shape)
            w, n_iter = lbfgs_minimize(loss, w0, max_iter=max_iter,
                                       tol=hyper["tol"], history=hist)
            return unpack(w, n_iter)

        return kernel

    @classmethod
    def _build_fit_slice_kernels(cls, meta, static, n_slice):
        """Iteration-sliced fit: ``init`` starts the solve and runs the
        first ``n_slice`` iterations, ``step`` advances a carry by
        another slice, ``finalize`` shapes the fitted params from the
        (w, it) carry leaves. The carry is the solver's dict pytree —
        its ``done`` leaf is the flags-only gather the backend's
        compaction loop reads."""
        problem = cls._build_fit_problem(meta, static)
        st = dict(static)
        max_iter, hist = st["max_iter"], st["history"]
        n_slice = int(n_slice)

        def init(X, y_idx, sw, hyper, aux=None):
            loss, w0, _ = problem(X, y_idx, sw, hyper)
            carry = lbfgs_carry_init(loss, w0, max_iter=max_iter,
                                     tol=hyper["tol"], history=hist)
            return lbfgs_resume(loss, carry, n_slice, max_iter=max_iter,
                                tol=hyper["tol"], history=hist)

        def step(X, y_idx, sw, hyper, carry, aux=None):
            loss, _, _ = problem(X, y_idx, sw, hyper)
            return lbfgs_resume(loss, carry, n_slice, max_iter=max_iter,
                                tol=hyper["tol"], history=hist)

        def finalize(X, y_idx, sw, hyper, carry, aux=None):
            _, _, unpack = problem(X, y_idx, sw, hyper)
            return unpack(carry_iterate(carry), carry["it"])

        return {
            "init": init, "step": step, "finalize": finalize,
            # finalize touches only these carry leaves: retired lanes'
            # S/Y/rho history never needs to leave the device
            "finalize_keys": ("w", "it"),
            # score-from-carry: the current iterate is a valid model at
            # every slice boundary (solvers.carry_iterate), so the ASHA
            # rung evaluator shapes params from a LIVE carry with the
            # same unpack the finalize uses — scoring never perturbs
            # the trajectory, it only reads it
            "score_params": finalize,
        }


# --------------------------------------------------------------------------
# LogisticRegression
# --------------------------------------------------------------------------

class LogisticRegression(_LbfgsFitMixin, _LinearClassifierBase):
    """L2 multinomial / binary logistic regression via jittable L-BFGS.

    sklearn-compatible surface; objective matches sklearn
    (``Σ s·ce + 0.5/C·‖w‖²``, intercept unpenalised) so coefficient and
    score parity with the reference stack holds to solver tolerance.
    ``penalty=None`` drops the ridge term entirely (sklearn's C=inf
    convention; ``C`` is then ignored). ``C`` and ``tol`` are batchable
    hyperparameters — a CV grid over C compiles to a single vmapped
    XLA program; ``penalty`` is compile-shaping (candidates bucket).

    ``engine`` picks the execution engine: ``'auto'`` (default) runs
    host-side fits on CPU platforms through the f64 BLAS solver
    (``models/host_linear.py``) and device fits through this XLA
    kernel; ``'xla'``/``'host'`` pin one engine. Both minimise the
    same objective, but stop differently at the same ``tol``: the
    host engine matches sklearn's mean-scaled ``gtol`` (iteration
    counts track sklearn), while the XLA kernel's ``max|grad| <= tol``
    is on the weight-SUM-scaled objective — tighter in absolute terms
    on large n.

    ``matmul_dtype="bfloat16"`` runs the loss/gradient matmuls (the
    FLOP bulk of L-BFGS) with bf16 inputs and f32 accumulation
    (``preferred_element_type``); the L-BFGS state, reductions, and
    regulariser stay f32. Measured on the v5e headline workload
    (round 2): ~13% faster end-to-end, cv_results_ deviation up to
    ~5e-3 from exact f32. POLICY — stays opt-in: exact f32 is the
    default because 5e-3 is 500× the framework's 1e-5 parity budget
    and can reorder close candidates. Opt in for throughput-bound
    SCREENING (wide grids / feature-elimination sweeps where you only
    need the top region of the leaderboard, not 1e-3 score
    resolution), then refit finalists at default precision. Not for
    final model selection between close candidates.
    """

    _hyper_names = ("C", "tol")
    _static_names = (
        "max_iter", "fit_intercept", "class_weight", "history",
        "matmul_dtype", "engine", "penalty",
    )

    def __init__(self, C=1.0, tol=1e-4, max_iter=100, fit_intercept=True,
                 class_weight=None, penalty="l2", random_state=None,
                 history=10, matmul_dtype=None, engine="auto"):
        self.C = C
        self.tol = tol
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept
        self.class_weight = class_weight
        self.penalty = penalty
        self.random_state = random_state
        self.history = history
        self.matmul_dtype = matmul_dtype
        self.engine = engine
        if penalty not in ("l2", None, "none"):
            raise ValueError("LogisticRegression supports penalty='l2' (or None)")
        if matmul_dtype not in (None, "float32", "bfloat16"):
            raise ValueError("matmul_dtype must be None/'float32'/'bfloat16'")
        if engine not in ("auto", "host", "xla"):
            raise ValueError("engine must be 'auto', 'host' or 'xla'")

    #: the warm C-path runner (distribute/search.py) may chain fits
    _host_warm_startable = True

    def _host_fit(self, X, y, sample_weight=None):
        """Host f64 BLAS engine (scipy L-BFGS-B on the identical
        objective; ``models/host_linear.py``) — the engine 'auto'
        resolution picks for CPU-platform host fits, mirroring the
        reference's sc=None == sklearn local path.

        A caller-seeded ``_warm_w0`` (the warm C-path runner's previous
        optimum) initialises the solver when its shape matches this
        problem; the fitted instance exposes its own f64 optimum as
        ``_w_opt64`` for the next fit in the path."""
        from .host_linear import logreg_host_fit

        data, meta = self._prep_fit_data(X, y, sample_weight)
        k = meta["n_classes"]
        p = meta["n_features"] + (1 if self.fit_intercept else 0)
        n_w = p if k <= 2 else p * k
        w0 = getattr(self, "_warm_w0", None)
        if w0 is not None and np.shape(w0) != (n_w,):
            w0 = None
        # penalty=None maps to C=inf (inv_C=0), sklearn's convention;
        # re-validated because set_params bypasses __init__ — both
        # engines must reject an unsupported penalty identically
        if self.penalty not in ("l2", None, "none"):
            raise ValueError(
                "LogisticRegression supports penalty='l2' (or None)"
            )
        C_eff = (
            np.inf if self.penalty in (None, "none")
            else hyper_float(self.C)
        )
        params, w_opt = logreg_host_fit(
            np.asarray(data["X"]), np.asarray(data["y"]),
            np.asarray(data["sw"]),
            C=C_eff, tol=hyper_float(self.tol),
            max_iter=self.max_iter, fit_intercept=self.fit_intercept,
            n_classes=k, history=self.history,
            class_weight=self.class_weight, cw_arr=meta.get("cw_arr"),
            w0=w0,
        )
        self._set_fitted(params, meta)
        self._w_opt64 = w_opt
        return self

    @classmethod
    def _build_fit_problem(cls, meta, static):
        st = dict(static)
        k = meta["n_classes"]
        fit_intercept = st["fit_intercept"]
        class_weight, cw_arr = st["class_weight"], meta.get("cw_arr")
        binary = k <= 2

        md = st.get("matmul_dtype")
        if md not in (None, "float32", "bfloat16"):
            # re-validated here because set_params bypasses __init__
            raise ValueError("matmul_dtype must be None/'float32'/'bfloat16'")
        if st.get("engine", "auto") not in ("auto", "host", "xla"):
            # same guard: a typo'd engine set via set_params must not
            # silently route to the batched device path
            raise ValueError("engine must be 'auto', 'host' or 'xla'")
        penalty = st.get("penalty", "l2")
        if penalty not in ("l2", None, "none"):
            raise ValueError(
                "LogisticRegression supports penalty='l2' (or None)"
            )
        unpenalized = penalty in (None, "none")
        bf16 = md == "bfloat16"

        def problem(X, y_idx, sw, hyper, parts=False):
            C = hyper["C"]
            # one matvec interface over dense AND packed-CSR X: the
            # operator reproduces the historical dense expressions
            # verbatim (incl. the bf16 dot_general), and routes packed
            # input through the sparse plane's gather/scatter kernels
            # — autodiff of the gather matvec IS the scatter-add
            # X.T @ r, so the whole L-BFGS solve runs O(nnz) per
            # iteration with no second code path in the solver
            op = _linear_op(X, fit_intercept, meta,
                            matmul_dtype="bfloat16" if bf16 else None)
            p = op.p
            sw = _apply_class_weight(sw, y_idx, k, class_weight, cw_arr)
            d = meta["n_features"]
            matvec = op.matvec
            # the data term and regulariser are separable closures: the
            # resident loss composes them in the historical expression
            # order (numerics pinned), and the STREAMED fit evaluates
            # data_loss per block (the term is row-additive) plus
            # reg_loss once — `parts=True` is that second consumer
            if binary:
                ypm = (y_idx == (k - 1)).astype(op.dtype)  # {0,1}

                def data_loss(w):
                    z = matvec(w)
                    return jnp.sum(sw * (jax.nn.softplus(z) - ypm * z))

                def reg_loss(w):
                    if unpenalized:  # penalty=None: sklearn's C=inf
                        return jnp.float32(0.0)
                    return 0.5 / C * jnp.dot(w[:d], w[:d])

                def loss(w):
                    ce = data_loss(w)
                    if unpenalized:
                        return ce
                    return ce + reg_loss(w)

                w0 = jnp.zeros(p, op.dtype)

                def unpack(w, n_iter):
                    return {"W": w, "n_iter": n_iter}

                if parts:
                    return loss, w0, unpack, data_loss, reg_loss
                return loss, w0, unpack

            onehot = jax.nn.one_hot(y_idx, k, dtype=op.dtype)

            def data_loss(wflat):
                W = wflat.reshape(p, k)
                logits = matvec(W)
                lse = jax.nn.logsumexp(logits, axis=1)
                return jnp.sum(sw * (lse - jnp.sum(onehot * logits, axis=1)))

            def reg_loss(wflat):
                if unpenalized:  # penalty=None: sklearn's C=inf
                    return jnp.float32(0.0)
                W = wflat.reshape(p, k)
                return 0.5 / C * jnp.sum(W[:d] * W[:d])

            def loss(wflat):
                ce = data_loss(wflat)
                if unpenalized:
                    return ce
                return ce + reg_loss(wflat)

            w0 = jnp.zeros(p * k, op.dtype)

            def unpack(w, n_iter):
                return {"W": w.reshape(p, k), "n_iter": n_iter}

            if parts:
                return loss, w0, unpack, data_loss, reg_loss
            return loss, w0, unpack

        return problem

    @classmethod
    def _build_decision_kernel(cls, meta, static):
        st = dict(static)
        fit_intercept = st["fit_intercept"]
        d = meta["n_features"]

        @jax.jit
        def decision(params, X):
            # representation-polymorphic: X may be a dense block (the
            # predict side) OR the shared packed pair (the batched CV
            # finalize scoring a sparse fit) — matvec_any dispatches on
            # the pytree structure at trace time
            W = params["W"]
            w, b = _split_Wb(W, d, fit_intercept, meta["n_classes"])
            return matvec_any(X, w) + b

        return decision

    @classmethod
    def _build_proba_kernel(cls, meta, static):
        decision = cls._build_decision_kernel(meta, static)
        binary = meta["n_classes"] <= 2

        @jax.jit
        def proba(params, X):
            z = decision(params, X)
            if binary:
                p1 = jax.nn.sigmoid(z)
                return jnp.stack([1.0 - p1, p1], axis=1)
            return jax.nn.softmax(z, axis=1)

        return proba

    def predict_proba(self, X):
        self._check_fitted()
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict_proba")
        X = prepare_fit_X(X, type(self))
        static = _freeze(self._static_config(self._meta))
        kernel = get_kernel(type(self), "proba", self._meta, static)
        return np.asarray(kernel(_to_jnp(self._params), _to_jnp(X)))

    def predict_log_proba(self, X):
        return np.log(np.clip(self.predict_proba(X), 1e-15, None))


# --------------------------------------------------------------------------
# LinearSVC (squared hinge, OvR)
# --------------------------------------------------------------------------

class LinearSVC(_LbfgsFitMixin, _LinearClassifierBase):
    """L2-regularised squared-hinge linear SVM (primal, L-BFGS).

    Multiclass is one-vs-rest with all class columns solved jointly in a
    single flattened L-BFGS problem (the per-class objectives are
    separable, so the joint minimiser equals per-class minimisers while
    keeping one XLA program). Reference usage: base estimator for
    DistOneVsRestClassifier (BASELINE.json configs).
    """

    _hyper_names = ("C", "tol")
    _static_names = (
        "max_iter", "fit_intercept", "class_weight", "history", "engine",
        "loss",
    )

    def __init__(self, C=1.0, tol=1e-4, max_iter=1000, fit_intercept=True,
                 class_weight=None, loss="squared_hinge", random_state=None,
                 history=10, engine="auto"):
        self.C = C
        self.tol = tol
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept
        self.class_weight = class_weight
        self.loss = loss
        self.random_state = random_state
        self.history = history
        self.engine = engine
        if loss != "squared_hinge":
            raise ValueError("LinearSVC supports loss='squared_hinge'")
        if engine not in ("auto", "host", "xla"):
            raise ValueError("engine must be 'auto', 'host' or 'xla'")

    #: the warm C-path runner (distribute/search.py) may chain fits
    _host_warm_startable = True

    def _host_fit(self, X, y, sample_weight=None):
        """Host f64 BLAS engine (scipy L-BFGS-B on the identical
        squared-hinge objective; ``models/host_linear.py``)."""
        from .host_linear import svc_host_fit

        # re-validated because set_params bypasses __init__: a
        # set_params(loss='hinge') must fail loudly on BOTH engines
        # instead of silently fitting squared hinge (ADVICE r05 #3)
        if self.loss != "squared_hinge":
            raise ValueError("LinearSVC supports loss='squared_hinge'")
        data, meta = self._prep_fit_data(X, y, sample_weight)
        k = meta["n_classes"]
        p = meta["n_features"] + (1 if self.fit_intercept else 0)
        n_w = p if k <= 2 else p * k
        w0 = getattr(self, "_warm_w0", None)
        if w0 is not None and np.shape(w0) != (n_w,):
            w0 = None
        params, w_opt = svc_host_fit(
            np.asarray(data["X"]), np.asarray(data["y"]),
            np.asarray(data["sw"]),
            C=hyper_float(self.C), tol=hyper_float(self.tol),
            max_iter=self.max_iter, fit_intercept=self.fit_intercept,
            n_classes=k, history=self.history,
            class_weight=self.class_weight, cw_arr=meta.get("cw_arr"),
            w0=w0,
        )
        self._set_fitted(params, meta)
        self._w_opt64 = w_opt
        return self

    @classmethod
    def _build_fit_problem(cls, meta, static):
        st = dict(static)
        k = meta["n_classes"]
        d = meta["n_features"]
        fit_intercept = st["fit_intercept"]
        class_weight, cw_arr = st["class_weight"], meta.get("cw_arr")
        binary = k <= 2

        if st.get("engine", "auto") not in ("auto", "host", "xla"):
            # re-validated because set_params bypasses __init__ (same
            # guard convention as LogisticRegression's matmul_dtype)
            raise ValueError("engine must be 'auto', 'host' or 'xla'")
        if st.get("loss", "squared_hinge") != "squared_hinge":
            # same convention for loss: set_params(loss='hinge') must
            # not silently fit squared hinge (ADVICE r05 #3)
            raise ValueError("LinearSVC supports loss='squared_hinge'")

        def problem(X, y_idx, sw, hyper, parts=False):
            C = hyper["C"]
            # dense or packed-CSR X behind one matvec interface (see
            # LogisticRegression._build_fit_problem); data/reg split as
            # there — the squared-hinge sum is row-additive (streamed
            # per block), the ridge term is evaluated once
            op = _linear_op(X, fit_intercept, meta)
            p = op.p
            sw = _apply_class_weight(sw, y_idx, k, class_weight, cw_arr)
            if binary:
                ypm = jnp.where(y_idx == (k - 1), 1.0, -1.0).astype(op.dtype)

                def data_loss(w):
                    margin = jnp.maximum(0.0, 1.0 - ypm * op.matvec(w))
                    return C * jnp.sum(sw * margin**2)

                def reg_loss(w):
                    return 0.5 * jnp.dot(w[:d], w[:d])

                def loss(w):
                    return reg_loss(w) + data_loss(w)

                w0 = jnp.zeros(p, op.dtype)

                def unpack(w, n_iter):
                    return {"W": w, "n_iter": n_iter}

                if parts:
                    return loss, w0, unpack, data_loss, reg_loss
                return loss, w0, unpack

            Ypm = jnp.where(jax.nn.one_hot(y_idx, k) > 0, 1.0, -1.0).astype(op.dtype)

            def data_loss(wflat):
                W = wflat.reshape(p, k)
                margins = jnp.maximum(0.0, 1.0 - Ypm * op.matvec(W))
                return C * jnp.sum(sw[:, None] * margins**2)

            def reg_loss(wflat):
                W = wflat.reshape(p, k)
                return 0.5 * jnp.sum(W[:d] * W[:d])

            def loss(wflat):
                return reg_loss(wflat) + data_loss(wflat)

            w0 = jnp.zeros(p * k, op.dtype)

            def unpack(w, n_iter):
                return {"W": w.reshape(p, k), "n_iter": n_iter}

            if parts:
                return loss, w0, unpack, data_loss, reg_loss
            return loss, w0, unpack

        return problem

    _build_decision_kernel = LogisticRegression._build_decision_kernel


# --------------------------------------------------------------------------
# SGDClassifier
# --------------------------------------------------------------------------

class SGDClassifier(_LinearClassifierBase):
    """Mini-batch SGD linear classifier (hinge / log_loss / squared_hinge).

    TPU-first redesign of sklearn's sample-at-a-time SGD: fixed-shape
    mini-batches stepped inside ``lax.scan`` so an entire randomized
    search over ``alpha``/``eta0``/``l1_ratio`` vmaps into one program
    (BASELINE config: DistRandomizedSearchCV(SGDClassifier, covtype)).

    Early stopping honours ``tol`` with sklearn's no-validation rule:
    the mean training loss of each epoch must beat ``best - tol``
    within ``n_iter_no_change`` (=5) epochs or the task stops —
    implemented shape-statically (stopped vmap lanes freeze their
    weights while the scan runs on), so a whole randomized search still
    compiles to one program; ``n_iter_`` reports the real per-task
    epoch count. ``tol=None`` maps to ``-inf`` and reproduces the
    fixed-``max_iter`` run. One deliberate divergence: the tracked
    epoch loss is evaluated on each batch *after* its gradient step
    (sklearn accumulates the pre-update loss during the step), so
    ``n_iter_`` can differ from sklearn by an epoch or two at the same
    ``tol`` — the post-update loss is what one fused scan step can
    compute without a second forward pass per batch.

    L1 / elastic-net apply sklearn's truncated-gradient cumulative
    penalty (Tsuruoka et al.) as a stateful post-step — weights are
    clipped toward zero by their accrued-penalty deficit and genuinely
    reach exact zeros, unlike a subgradient step. The operation is
    elementwise, so a vmapped hyper search still compiles to one
    program.
    """

    _hyper_names = ("alpha", "eta0", "l1_ratio", "tol")
    _static_names = (
        "max_iter", "fit_intercept", "class_weight", "loss", "penalty",
        "learning_rate", "batch_size", "random_state",
        "n_iter_no_change", "shuffle",
    )

    def __init__(self, loss="hinge", penalty="l2", alpha=1e-4, l1_ratio=0.15,
                 max_iter=20, tol=1e-3, fit_intercept=True, eta0=0.01,
                 learning_rate="optimal", class_weight=None, random_state=0,
                 batch_size=64, n_iter_no_change=5, shuffle=True):
        self.loss = loss
        self.penalty = penalty
        self.alpha = alpha
        self.l1_ratio = l1_ratio
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.eta0 = eta0
        self.learning_rate = learning_rate
        self.class_weight = class_weight
        self.random_state = random_state
        self.batch_size = batch_size
        self.n_iter_no_change = n_iter_no_change
        # sklearn's SGD exposes shuffle too; shuffle=False is also what
        # makes a block-streamed fit bitwise-comparable to the resident
        # scan (consecutive batches don't cross row blocks)
        self.shuffle = shuffle

    _supports_sliced_fit = True

    #: out-of-core fit form: epochs as block streams (models/streaming)
    _stream_fit_kind = "sgd"

    @classmethod
    def _flat_w_width(cls, meta, static):
        st = dict(static)
        p = meta["n_features"] + (1 if st["fit_intercept"] else 0)
        k = meta.get("n_classes", 2)
        return p if k <= 2 else p * k

    @classmethod
    def _batched_task_cost(cls, hyper):
        """Round-packing cost heuristic: weak regularisation (small
        ``alpha``) and tight ``tol`` both mean more epochs before the
        no-improvement rule fires; ``tol <= 0`` (tol=None → -inf) never
        stops early and sorts last."""
        alpha = np.asarray(hyper.get("alpha", 1e-4), dtype=np.float64)
        tol = np.asarray(hyper.get("tol", 1e-3), dtype=np.float64)
        # log only on the positive mask (see _LbfgsFitMixin)
        cost = -np.log(np.maximum(alpha, 1e-30)) - np.where(
            tol > 0, np.log(np.where(tol > 0, tol, 1.0)), -np.inf
        )
        return np.broadcast_to(
            cost, np.broadcast_shapes(alpha.shape, tol.shape)
        )

    @classmethod
    def _build_fit_problem(cls, meta, static):
        """Everything the SGD solve needs, built once per (meta,
        static): ``problem(X, y_idx, sw, hyper)`` returns a dict with
        the gradient/loss/schedule closures, the initial weights and
        post-step state, and ``unpack`` — consumed identically by the
        plain fit kernel (``sgd_minimize``) and the iteration-sliced
        variant (``sgd_carry_init``/``sgd_resume``)."""
        st = dict(static)
        k = meta["n_classes"]
        d = meta["n_features"]
        fit_intercept = st["fit_intercept"]
        loss_name, penalty = st["loss"], st["penalty"]
        lr_kind = st["learning_rate"]
        max_iter, batch_size = st["max_iter"], st["batch_size"]
        n_iter_no_change = int(st["n_iter_no_change"])
        if n_iter_no_change < 1:
            # sklearn raises for this too; silently freezing after the
            # first epoch (bad_new=0 >= 0) would under-train the model
            raise ValueError(
                f"n_iter_no_change must be >= 1; got {n_iter_no_change}"
            )
        class_weight, cw_arr = st["class_weight"], meta.get("cw_arr")
        n_out = 1 if k <= 2 else k

        def pointwise_grad_factory(alpha):
            if loss_name == "log_loss":
                def dloss(z, ypm):  # dL/dz with y in {-1,1}
                    return -ypm * jax.nn.sigmoid(-ypm * z)
            elif loss_name == "hinge":
                def dloss(z, ypm):
                    return jnp.where(ypm * z < 1.0, -ypm, 0.0)
            elif loss_name == "squared_hinge":
                def dloss(z, ypm):
                    return jnp.where(ypm * z < 1.0, -2.0 * ypm * (1.0 - ypm * z), 0.0)
            else:
                raise ValueError(f"unsupported loss {loss_name!r}")
            return dloss

        seed = st["random_state"] or 0

        def problem(X, y_idx, sw, hyper):
            alpha = hyper["alpha"]
            eta0 = hyper["eta0"]
            l1_ratio = hyper["l1_ratio"]
            # dense or packed-CSR X behind one matvec interface; the
            # mini-batch forms gather the batch's packed rows, so each
            # SGD step is O(batch nnz) instead of O(batch·d)
            op = _linear_op(X, fit_intercept, meta)
            n = op.n
            p = op.p
            sw_full = _apply_class_weight(sw, y_idx, k, class_weight, cw_arr)
            if n_out == 1:
                Ypm = jnp.where(y_idx == (k - 1), 1.0, -1.0).astype(op.dtype)[:, None]
            else:
                Ypm = jnp.where(jax.nn.one_hot(y_idx, k) > 0, 1.0, -1.0).astype(op.dtype)
            dloss = pointwise_grad_factory(alpha)

            if loss_name == "log_loss":
                def ploss(z, ypm):
                    return jax.nn.softplus(-ypm * z)
            elif loss_name == "hinge":
                def ploss(z, ypm):
                    return jnp.maximum(0.0, 1.0 - ypm * z)
            else:  # squared_hinge
                def ploss(z, ypm):
                    return jnp.maximum(0.0, 1.0 - ypm * z) ** 2

            def loss_fn(Wf, idx):
                # weighted mean DATA loss of one batch (penalty terms
                # excluded, matching the loss sklearn's no-validation
                # early stopping tracks); joint multiclass sums the
                # separable per-column binary losses
                W = Wf.reshape(p, n_out)
                wb = sw_full[idx]
                per = ploss(op.row_matvec(idx, W), Ypm[idx]).sum(axis=1) * wb
                return jnp.sum(per) / jnp.maximum(jnp.sum(wb), 1e-12)

            def grad_fn(Wf, idx):
                W = Wf.reshape(p, n_out)
                yb = Ypm[idx]
                wb = sw_full[idx][:, None]
                z = op.row_matvec(idx, W)
                g_z = dloss(z, yb) * wb
                g = op.row_rmatvec(idx, g_z) / jnp.maximum(
                    jnp.sum(sw_full[idx]), 1e-12
                )
                if penalty in ("l2", "elasticnet"):
                    l2_mul = 1.0 if penalty == "l2" else (1.0 - l1_ratio)
                    g = g.at[:d].add(alpha * l2_mul * W[:d])
                return g.reshape(-1)

            if lr_kind == "optimal":
                # batch-adapted variant of Bottou's 'optimal' schedule:
                # sklearn's eta0 = typw suits per-SAMPLE updates; with
                # batch-MEAN gradients that initial step overshoots, so
                # the step starts at ~1 — and the 1/(alpha·t) decay
                # runs in SAMPLE time (alpha·batch_size per batch
                # step), keeping the per-sample schedule's time
                # constant. Decaying in batch-step time was ~batch×
                # too slow: the lr sat near 1 for hundreds of epochs,
                # iterates oscillated (measured: epoch losses bouncing
                # 0.8–2.7 on a problem whose optimum is 0.64), and the
                # epoch-loss series was too noisy for tol-based early
                # stopping to read.
                def lr_fn(t):
                    return 1.0 / (1.0 + alpha * batch_size * (t + 1.0))
            elif lr_kind == "invscaling":
                def lr_fn(t):
                    return eta0 / (t + 1.0) ** 0.5
            else:  # constant
                def lr_fn(t):
                    return eta0 * jnp.ones_like(t, jnp.float32)

            W0 = jnp.zeros(p * n_out, op.dtype)

            if penalty in ("l1", "elasticnet"):
                l1_mul = 1.0 if penalty == "l1" else l1_ratio

                # truncated-gradient L1 (Tsuruoka et al.'s cumulative
                # penalty — what sklearn's SGD applies): u tracks the
                # total penalty rate accrued, q what each weight has
                # actually absorbed; weights are clipped toward zero by
                # the deficit and STAY exactly zero once truncated.
                # Elementwise, so the whole search still vmaps; the l2
                # leg of elastic-net stays in grad_fn.
                def post_step(Wf, state, lr):
                    u, q = state
                    u = u + lr * alpha * l1_mul
                    W = Wf.reshape(p, n_out)
                    Q = q.reshape(p, n_out)
                    z = W[:d]  # intercept rows are not penalised
                    # exactly-zero weights stay put (sklearn's branch
                    # structure; the blind else-branch could push them
                    # negative when q > u)
                    w_trunc = jnp.where(
                        z > 0,
                        jnp.maximum(0.0, z - (u + Q[:d])),
                        jnp.where(
                            z < 0,
                            jnp.minimum(0.0, z + (u - Q[:d])),
                            z,
                        ),
                    )
                    Q = Q.at[:d].add(w_trunc - z)
                    W = W.at[:d].set(w_trunc)
                    return W.reshape(-1), (u, Q.reshape(-1))

                post_state = (jnp.float32(0.0), jnp.zeros_like(W0))
            else:
                post_step, post_state = None, ()

            def unpack(W, n_epochs):
                W = W.reshape(p, n_out)
                if n_out == 1:
                    W = W[:, 0]
                return {"W": W, "n_iter": n_epochs}

            return {
                "grad_fn": grad_fn, "loss_fn": loss_fn, "lr_fn": lr_fn,
                "post_step": post_step, "post_state": post_state,
                "W0": W0, "n": n, "key": jax.random.PRNGKey(seed),
                "unpack": unpack,
            }

        return problem

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        problem = cls._build_fit_problem(meta, static)
        st = dict(static)
        max_iter, batch_size = st["max_iter"], st["batch_size"]
        n_iter_no_change = int(st["n_iter_no_change"])

        shuffle = bool(st.get("shuffle", True))

        def kernel(X, y_idx, sw, hyper, aux=None):
            pb = problem(X, y_idx, sw, hyper)
            W0 = pb["W0"]
            if aux is not None and "w0" in aux:
                # warm start: epochs begin at the caller's seed
                W0 = jnp.asarray(aux["w0"], W0.dtype).reshape(W0.shape)
            W, n_epochs = sgd_minimize(
                pb["grad_fn"], W0, pb["n"], pb["key"], max_iter,
                batch_size, pb["lr_fn"], shuffle=shuffle,
                loss_fn=pb["loss_fn"],
                tol=hyper["tol"], n_iter_no_change=n_iter_no_change,
                post_step=pb["post_step"], post_state=pb["post_state"],
            )
            return pb["unpack"](W, n_epochs)

        return kernel

    @classmethod
    def _build_fit_slice_kernels(cls, meta, static, n_slice):
        """Epoch-sliced SGD fit (the convergence-compacted scheduler's
        contract; slice unit = one epoch): same closures, carries
        advanced by ``sgd_resume`` — bitwise identical to the unsliced
        scan (stopped lanes and overhanging tails freeze in place)."""
        problem = cls._build_fit_problem(meta, static)
        st = dict(static)
        max_iter, batch_size = st["max_iter"], st["batch_size"]
        n_iter_no_change = int(st["n_iter_no_change"])
        n_slice = int(n_slice)

        shuffle = bool(st.get("shuffle", True))

        def resume(pb, carry, hyper):
            return sgd_resume(
                pb["grad_fn"], carry, n_slice, pb["n"], pb["key"],
                max_iter, batch_size, pb["lr_fn"], shuffle=shuffle,
                loss_fn=pb["loss_fn"],
                tol=hyper["tol"], n_iter_no_change=n_iter_no_change,
                post_step=pb["post_step"],
            )

        def init(X, y_idx, sw, hyper, aux=None):
            pb = problem(X, y_idx, sw, hyper)
            carry = sgd_carry_init(pb["W0"], pb["post_state"])
            return resume(pb, carry, hyper)

        def step(X, y_idx, sw, hyper, carry, aux=None):
            pb = problem(X, y_idx, sw, hyper)
            return resume(pb, carry, hyper)

        def finalize(X, y_idx, sw, hyper, carry, aux=None):
            pb = problem(X, y_idx, sw, hyper)
            return pb["unpack"](carry_iterate(carry), carry["n_done"])

        return {
            "init": init, "step": step, "finalize": finalize,
            "finalize_keys": ("w", "n_done"),
            # live-carry params for the ASHA rung evaluator (epoch
            # boundaries leave frozen/stopped lanes' weights intact, so
            # the iterate is always a scoreable model)
            "score_params": finalize,
        }

    _build_decision_kernel = LogisticRegression._build_decision_kernel

    _build_proba_kernel = LogisticRegression._build_proba_kernel

    def predict_proba(self, X):
        if self.loss != "log_loss":
            raise AttributeError(
                "predict_proba is only available with loss='log_loss'"
            )
        self._check_fitted()
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict_proba")
        X = prepare_fit_X(X, type(self))
        static = _freeze(self._static_config(self._meta))
        kernel = get_kernel(type(self), "proba", self._meta, static)
        return np.asarray(kernel(_to_jnp(self._params), _to_jnp(X)))


# --------------------------------------------------------------------------
# Ridge family (closed form — one cholesky solve per task, MXU-friendly)
# --------------------------------------------------------------------------

class _RidgeKernelMixin:
    @staticmethod
    def _solve(op, T, sw, alpha, d):
        """Weighted ridge: solve (XᵀSX + αI₀)W = XᵀST; intercept column
        unpenalised (I₀ has zero at the bias position). ``op`` is the
        matvec interface (``_linear_op``): dense X keeps the MXU gram
        matmul verbatim; packed X builds the gram by the m² scatter
        (O(nnz·m) instead of O(n·d²))."""
        G, b = op.weighted_gram_rhs(sw, T)  # (p, p), (p, k)
        p = G.shape[0]
        reg = jnp.concatenate([jnp.full((d,), alpha), jnp.zeros(p - d)])
        G = G + jnp.diag(reg)
        # jitter for singular grams (e.g. alpha=0 OLS)
        G = G + 1e-8 * jnp.eye(p, dtype=G.dtype)
        W = jax.scipy.linalg.solve(G, b, assume_a="pos")
        return W


class Ridge(_LinearModelBase, RegressorMixin, _RidgeKernelMixin):
    """Closed-form weighted ridge regression. ``alpha`` is batchable, so
    a CV sweep over alphas × folds is one vmapped solve."""

    _hyper_names = ("alpha",)
    _static_names = ("fit_intercept",)

    #: out-of-core fit form: block-accumulated normal equations — the
    #: gram/rhs sums stream, one solve finishes (models/streaming.py)
    _stream_fit_kind = "gram"

    def __init__(self, alpha=1.0, fit_intercept=True):
        self.alpha = alpha
        self.fit_intercept = fit_intercept

    def _prep_stream_fit(self, dataset, y, sample_weight=None):
        if y is None:
            raise ValueError(
                f"{type(self).__name__} needs targets: the "
                "ChunkedDataset carries none and no y was passed"
            )
        y = np.asarray(y, dtype=np.float32)
        sw = prepare_sample_weight(sample_weight, dataset.n_rows)
        meta = _annotate_stream_meta(
            {"n_features": dataset.n_features, "y_ndim": y.ndim}, dataset
        )
        return y, sw, meta

    def _prep_fit_data(self, X, y, sample_weight=None):
        y = np.asarray(y, dtype=np.float32)
        sw = prepare_sample_weight(sample_weight, X.shape[0])
        meta = _annotate_x_meta(
            {"n_features": X.shape[1], "y_ndim": y.ndim}, X
        )
        data = {"X": host_stage(X), "y": host_stage(y), "sw": host_stage(sw)}
        return data, meta

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        st = dict(static)
        fit_intercept = st["fit_intercept"]
        d = meta["n_features"]

        def kernel(X, y, sw, hyper, aux=None):
            alpha = hyper["alpha"]
            op = _linear_op(X, fit_intercept, meta)
            T = y.reshape(y.shape[0], -1)
            W = cls._solve(op, T, sw, alpha, d)
            if meta.get("y_ndim", 1) == 1:
                W = W[:, 0]
            return {"W": W}

        return kernel

    @classmethod
    def _build_decision_kernel(cls, meta, static):
        st = dict(static)
        fit_intercept = st["fit_intercept"]
        d = meta["n_features"]

        @jax.jit
        def decision(params, X):
            W = params["W"]
            w, b = _split_Wb(W, d, fit_intercept, 1)
            return matvec_any(X, w) + b

        return decision

    def predict(self, X):
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict")
        return self.decision_function(X)

    def _sklearn_2d_coef(self):
        return False


class LinearRegression(Ridge):
    """OLS as ridge with alpha=0 (tiny jitter for rank safety)."""

    _hyper_names = ()
    _static_names = ("fit_intercept",)

    def __init__(self, fit_intercept=True):
        self.fit_intercept = fit_intercept
        self.alpha = 0.0

    def fit(self, X, y=None, sample_weight=None, coef_init=None,
            intercept_init=None):
        self.alpha = 0.0
        return super().fit(X, y, sample_weight, coef_init=coef_init,
                           intercept_init=intercept_init)

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        inner = Ridge._build_fit_kernel.__func__(cls, meta, static)

        def kernel(X, y, sw, hyper, aux=None):
            hyper = dict(hyper)
            hyper.setdefault("alpha", jnp.float32(0.0))
            return inner(X, y, sw, hyper)

        return kernel


class RidgeClassifier(_LinearClassifierBase, _RidgeKernelMixin):
    """Ridge on ±1 targets; predict via argmax/sign of the decision."""

    _hyper_names = ("alpha",)
    _static_names = ("fit_intercept", "class_weight")

    _stream_fit_kind = "gram"

    def __init__(self, alpha=1.0, fit_intercept=True, class_weight=None):
        self.alpha = alpha
        self.fit_intercept = fit_intercept
        self.class_weight = class_weight

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        st = dict(static)
        fit_intercept = st["fit_intercept"]
        class_weight, cw_arr = st["class_weight"], meta.get("cw_arr")
        d = meta["n_features"]
        k = meta["n_classes"]

        def kernel(X, y_idx, sw, hyper, aux=None):
            alpha = hyper["alpha"]
            op = _linear_op(X, fit_intercept, meta)
            sw = _apply_class_weight(sw, y_idx, k, class_weight, cw_arr)
            if k <= 2:
                T = jnp.where(y_idx == (k - 1), 1.0, -1.0).astype(op.dtype)[:, None]
            else:
                T = jnp.where(jax.nn.one_hot(y_idx, k) > 0, 1.0, -1.0).astype(op.dtype)
            W = cls._solve(op, T, sw, alpha, d)
            if k <= 2:
                W = W[:, 0]
            return {"W": W}

        return kernel

    _build_decision_kernel = LogisticRegression._build_decision_kernel
