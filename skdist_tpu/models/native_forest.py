"""
Host (CPU) forest engine over the native per-level histogram kernels.

``models/tree.py`` grows trees as one XLA program — the right design
for the TPU, where the histogram is an MXU matmul / Pallas contraction.
On CPU the same program bottoms out in XLA's scatter-add, which
executes effectively serially: the committed calibration
(``models/hist_calib.json``) measured the best scatter variant at
20.1 s warm / 60.7 s cold per 100 trees on 20k x 54 x 7 — against
sklearn's 7.5 s. This module is the CPU counterpart of the device
kernel: the SAME breadth-first histogram algorithm (identical gain
formulas, validity rules, routing, and leaf statistics — see
``build_tree_kernel``), but with the per-level histogram AND the split
search executed by the multithreaded C kernels (``native/hist_tree.c``)
over a CHUNK of trees at once, and only the cheap glue (per-level
routing, record-keeping, PRNG draws) in numpy. Features no node
sampled this level (``max_features``) are skipped in both kernels —
work the dense XLA formulation must spend. No XLA compilation happens
at all, so cold fit == warm fit.

The reference delegated this exact role to sklearn's Cython builder
(reference ``skdist/distribute/ensemble.py:106-108``); here it is the
``hist_mode="native"`` engine that ``resolve_hist_config`` selects on
platforms whose calibration names it (the CPU sweep does).

Engine-vs-engine caveat: PRNG streams differ (jax.random on device,
numpy RandomState here) and the C split search accumulates in f64
where XLA uses f32, so a native forest and a device forest with the
same ``random_state`` are statistically equivalent but not
tree-for-tree identical — the same contract as sklearn vs LightGBM.
Bootstrap draws are the EXCEPTION: they reproduce the device path's
``_bootstrap_counts`` (jax PRNG) exactly, because OOB scoring
regenerates masks from stored seeds through that one function.

Known future optimisation, deliberately NOT taken: LightGBM's
sibling-subtraction trick (histogram only the smaller child, derive
the larger by parent-minus-smaller) would cut the accumulation's
sample work roughly in half, but it conflicts with the per-level
sampled-feature skipping (the parent must have histogrammed every
feature any DESCENDANT level samples, which degenerates to all
features) and makes weighted-channel histograms inexact under f32
subtraction, breaking the tested exact structural parity with the
device kernel. At the current measured margin over sklearn the
complexity is not worth either cost.
"""

import numpy as np

_NEG = -1e30


def native_forest_supported(n_bins):
    """The C kernel keys bins as uint8."""
    from ..native import hist_tree_available

    return n_bins <= 256 and hist_tree_available()


def native_supported_or_raise(n_bins, explicit):
    """True when the C engine can serve this fit, False when ``auto``
    should fall back to an XLA engine — and a precise error for an
    EXPLICIT ``hist_mode='native'`` that cannot be honored on this
    host (shared by the tree and forest dispatch sites so the
    diagnosis never drifts between them)."""
    if native_forest_supported(n_bins):
        return True
    if explicit:
        raise ValueError(
            "hist_mode='native' requested but the C histogram kernel "
            "is unavailable (no working compiler?) or n_bins "
            f"({n_bins}) > 256"
        )
    return False


def grow_single_tree_native(Xb, y, sw, seed, **config):
    """One tree via the host engine (a T=1 forest): the single-tree
    estimators' dispatch (``tree.py::_BaseTree.fit``) — no XLA compile
    at all, so a cold one-tree fit is milliseconds. Returns the
    unstacked param dict (without the forest-only ``seed`` entry)."""
    trees = grow_forest_native(
        Xb, y, np.asarray(sw, np.float32)[None, :],
        np.asarray([seed], np.int32), **config,
    )
    return {k: np.asarray(v[0]) for k, v in trees.items() if k != "seed"}


def _level_rng(seed, level):
    # deterministic per (tree, level); any well-mixed map works — this
    # only needs independence across levels, not device-path parity
    return np.random.RandomState(
        (int(seed) * 2654435761 + level * 40503 + 7) % (2**31 - 1)
    )


def _best_splits_numpy(hist, fmask, urand, K, classification, msl):
    """Numpy scoring fallback, math-matched to the device kernel's
    ``node_scores`` (f32, same masking/tie-break order). Returns
    ``(gain, f, t, cnt_l, cnt_r)`` each (Tb, nl), like the C kernel."""
    Tb, d, nl, B, C = hist.shape
    cum = np.cumsum(hist, axis=3)
    tot = cum[:, :, :, -1, :]  # (Tb, d, nl, C)
    cnt_l = cum[..., -1]
    cnt_r = tot[..., None, -1] - cnt_l
    if classification:
        Lk = cum[..., :K]
        wl = Lk.sum(-1)
        sl = np.einsum("...c,...c->...", Lk, Lk) / np.maximum(wl, 1e-12)
        totk = tot[..., :K]
        Rk = totk[:, :, :, None, :] - Lk
        wr = Rk.sum(-1)
        sr = np.einsum("...c,...c->...", Rk, Rk) / np.maximum(wr, 1e-12)
        wt = totk.sum(-1)
        st = np.einsum("...c,...c->...", totk, totk) / np.maximum(wt, 1e-12)
        gain = sl + sr - st[..., None]
    else:
        w_l, wy_l, wy2_l = cum[..., 0], cum[..., 1], cum[..., 2]
        w_t = tot[..., 0, None]
        wy_t = tot[..., 1, None]
        wy2_t = tot[..., 2, None]
        sse_l = wy2_l - wy_l**2 / np.maximum(w_l, 1e-12)
        w_r = w_t - w_l
        wy_r = wy_t - wy_l
        sse_r = (wy2_t - wy2_l) - wy_r**2 / np.maximum(w_r, 1e-12)
        sse_t = wy2_t - wy_t**2 / np.maximum(w_t, 1e-12)
        gain = sse_t - (sse_l + sse_r)

    ok = (cnt_l >= msl) & (cnt_r >= msl)
    gain = np.where(ok, gain, _NEG)
    if fmask is not None:
        gain = np.where(fmask[..., None].astype(bool), gain, _NEG)
    if urand is not None:
        occ = hist[..., -1] > 0  # (Tb, d, nl, B)
        lo = np.argmax(occ, axis=3)
        hi = B - 1 - np.argmax(occ[:, :, :, ::-1], axis=3)
        t_rand = lo + np.floor(urand * np.maximum(hi - lo, 1)).astype(
            np.int32
        )
        t_rand = np.clip(t_rand, 0, B - 2)
        sel = np.arange(B)[None, None, None, :] == t_rand[..., None]
        gain = np.where(sel, gain, _NEG)

    gain_fb = gain.transpose(0, 2, 1, 3).reshape(Tb, nl, d * B)
    best_flat = np.argmax(gain_fb, axis=2)[..., None]
    best_gain = np.take_along_axis(gain_fb, best_flat, axis=2)[..., 0]
    bf = (best_flat[..., 0] // B).astype(np.int32)
    bt = (best_flat[..., 0] % B).astype(np.int32)

    def pick(a):
        afb = a.transpose(0, 2, 1, 3).reshape(Tb, nl, d * B)
        return np.take_along_axis(afb, best_flat, axis=2)[..., 0]

    return best_gain, bf, bt, pick(cnt_l), pick(cnt_r)


def _leaf_stats(node_id, W, cls, yv, n_nodes, C, n_threads):
    """Final (Tb, N, C) channel sums via the histogram kernel, seen as
    a single-feature, single-bin level over all N nodes."""
    from ..native import hist_level

    Tb, n = node_id.shape
    dummy = np.zeros((1, n), np.uint8)
    stats = np.empty((Tb, 1, n_nodes, 1, C), np.float32)
    hist_level(stats, dummy, node_id, W, cls=cls, yv=yv,
               n_threads=n_threads)
    return stats.reshape(Tb, n_nodes, C)


def grow_forest_native(Xb, y, W, seeds, *, n_bins, max_depth, max_features,
                       min_samples_split, min_samples_leaf,
                       min_impurity_decrease, extra, classification,
                       n_classes, n_threads=None, budget_bytes=512 << 20):
    """Grow ``len(seeds)`` trees; returns the same stacked pytree the
    device path yields: ``{feat (T,N) i32, thr (T,N) i32, is_split
    (T,N) bool, leaf (T,N,K), gain (T,N) f32, seed (T,) i32}``.

    ``Xb`` (n, d) binned features (any int dtype, values < n_bins),
    ``y`` int32 class indices or f32 targets, ``W`` the (T, n) f32
    combined weights (sample_weight x bootstrap counts) — either the
    array itself or a ``(t0, t1) -> (t1-t0, n)`` callable built per
    tree-chunk, so a 500-tree x 1M-row fit never co-materialises all
    rows' weights — ``seeds`` (T,) int, used ONLY for
    feature-subsampling / random-threshold draws (the bootstrap is
    already inside ``W``).
    """
    from ..native import best_splits_native, hist_level

    n, d = Xb.shape
    T = len(seeds)
    D, B = int(max_depth), int(n_bins)
    K = int(n_classes) if classification else 1
    C = K + 1 if classification else 4
    N = 2 ** (D + 1) - 1
    msl, mss = int(min_samples_leaf), int(min_samples_split)
    mid = float(min_impurity_decrease)
    cls = np.ascontiguousarray(y, np.int32) if classification else None
    if cls is not None and cls.size:
        # the C kernel indexes histograms by class with no bounds
        # check (native/hist_tree.c hist_level) — raw labels or an
        # understated n_classes would corrupt heap memory, so the
        # range is validated host-side before the buffer is handed off
        lo, hi = int(cls.min()), int(cls.max())
        if lo < 0 or hi >= K:
            raise ValueError(
                f"grow_forest_native expects encoded class indices in "
                f"[0, {K - 1}] (n_classes={K}); got range [{lo}, {hi}]"
            )
    yv = None if classification else np.ascontiguousarray(y, np.float32)
    if n:
        # same defense for bin values: the C kernel's histogram index
        # (node*B + bin)*C has no bounds check either, and the uint8
        # casts below would silently truncate wider values
        bmin, bmax = int(np.min(Xb)), int(np.max(Xb))
        if bmin < 0 or bmax >= B:
            raise ValueError(
                f"grow_forest_native expects binned features in "
                f"[0, {B - 1}] (n_bins={B}); got range [{bmin}, {bmax}]"
            )
    XbT = np.ascontiguousarray(np.asarray(Xb).T, np.uint8)
    Xb = np.ascontiguousarray(Xb, np.uint8)
    if not callable(W):
        W = np.ascontiguousarray(W, np.float32)
    if n_threads is None:
        import os

        n_threads = min(16, os.cpu_count() or 1)

    # chunk trees so one level's histogram stays inside the budget
    # (the C path holds just the histogram; ~4x headroom covers the
    # numpy fallback's cumsum and gain temporaries)
    per_tree = d * (2 ** (D - 1)) * B * C * 4 * 4
    Tb_max = max(1, int(budget_bytes // max(per_tree, 1)))

    feat = np.full((T, N), -1, np.int32)
    thr = np.zeros((T, N), np.int32)
    is_split = np.zeros((T, N), bool)
    gain_rec = np.zeros((T, N), np.float32)
    leaf = np.zeros((T, N, K), np.float32)
    need_fmask = max_features < d

    rows = np.arange(n)
    for t0 in range(0, T, Tb_max):
        t1 = min(t0 + Tb_max, T)
        Tb = t1 - t0
        Wc = W(t0, t1) if callable(W) else W[t0:t1]
        Wc = np.ascontiguousarray(Wc, np.float32)
        w_root = Wc.sum(axis=1)  # (Tb,)
        node_id = np.zeros((Tb, n), np.int32)

        for level in range(D):
            start = 2**level - 1
            nl = 2**level
            rel = node_id - start
            at_level = (rel >= 0) & (rel < nl)
            node_rel = np.where(at_level, rel, -1).astype(np.int32)

            # per-(tree, level) draws: feature-subsample mask first,
            # random thresholds second (the device kernel's lkey /
            # fold_in(lkey, 1) ordering), one stream per tree
            fmask = urand = None
            if need_fmask or extra:
                if need_fmask:
                    fmask = np.empty((Tb, d, nl), np.uint8)
                if extra:
                    urand = np.empty((Tb, d, nl), np.float32)
                for i in range(Tb):
                    rng = _level_rng(seeds[t0 + i], level)
                    if need_fmask:
                        r = rng.uniform(size=(nl, d))
                        kth = np.sort(r, axis=1)[:, max_features - 1]
                        fmask[i] = (r <= kth[:, None]).T
                    if extra:
                        urand[i] = rng.uniform(size=(d, nl))
            act = (
                None if fmask is None
                else np.ascontiguousarray(fmask.any(axis=2).astype(np.uint8))
            )

            hist = np.empty((Tb, d, nl, B, C), np.float32)
            hist_level(hist, XbT, node_rel, Wc, cls=cls, yv=yv, act=act,
                       n_threads=n_threads)

            # unweighted node occupancy for the min_samples_split rule
            # (kept out of the histogram so ``act``-skipped feature
            # slabs are never read)
            node_cnt = np.zeros((Tb, nl), np.float32)
            for i in range(Tb):
                m = at_level[i] & (Wc[i] > 0)
                node_cnt[i] = np.bincount(
                    node_rel[i][m], minlength=nl
                ).astype(np.float32)

            res = best_splits_native(
                hist, fmask, urand, K, classification, msl, n_threads
            )
            if res is None:
                res = _best_splits_numpy(
                    hist, fmask, urand, K, classification, msl
                )
            best_gain, best_f, best_t = res[0], res[1], res[2]

            decrease = best_gain / np.maximum(w_root[:, None], 1e-12)
            do_split = (
                (best_gain > 1e-12)
                & (decrease >= mid)
                & (node_cnt >= mss)
            )

            sl_idx = slice(start, start + nl)
            feat[t0:t1, sl_idx] = np.where(do_split, best_f, -1)
            thr[t0:t1, sl_idx] = best_t
            is_split[t0:t1, sl_idx] = do_split
            gain_rec[t0:t1, sl_idx] = np.where(do_split, best_gain, 0.0)

            relc = np.clip(rel, 0, nl - 1)
            f_s = np.take_along_axis(best_f, relc, axis=1)
            t_s = np.take_along_axis(best_t, relc, axis=1)
            split_s = np.take_along_axis(do_split, relc, axis=1) & at_level
            bin_s = Xb[rows[None, :], f_s]
            child = 2 * node_id + 1 + (bin_s > t_s)
            node_id = np.where(split_s, child, node_id).astype(np.int32)

        stats = _leaf_stats(node_id, Wc, cls, yv, N, C, n_threads)
        if classification:
            wsum = stats[:, :, :K].sum(axis=2, keepdims=True)
            lv = stats[:, :, :K] / np.maximum(wsum, 1e-12)
            leaf[t0:t1] = np.where(wsum > 0, lv, 1.0 / K)
        else:
            leaf[t0:t1] = (
                stats[:, :, 1] / np.maximum(stats[:, :, 0], 1e-12)
            )[..., None]

    return {
        "feat": feat, "thr": thr, "is_split": is_split,
        "leaf": leaf, "gain": gain_rec,
        "seed": np.asarray(seeds, np.int32),
    }
