"""
Naive Bayes kernels: Gaussian and Multinomial.

Closed-form fits (per-class weighted moments / counts — a couple of
matmuls), which makes them the cheapest members of the batched-fit
contract: a CV sweep is one vmapped program of segment reductions.
The reference exercised sklearn's GaussianNB through
DistMultiModelSearch (reference test_search.py multimodel test) and
text models through the Encoderizer pipelines.

Numerical notes: Gaussian moments are computed on globally-centred
data (bounding magnitudes by the inter-class spread) so the
E[x²]−mean² form doesn't catastrophically cancel in float32; the
Gaussian decision is expressed as three matmuls, never materialising
an (n, k, d) intermediate.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .linear import (
    LogisticRegression,
    _LinearClassifierBase,
)

__all__ = ["GaussianNB", "MultinomialNB"]


class GaussianNB(_LinearClassifierBase):
    """Gaussian naive Bayes with weighted per-class moments.

    ``var_smoothing`` (sklearn semantics: added variance =
    var_smoothing · max feature variance) is a batchable hyper.
    """

    _hyper_names = ("var_smoothing",)
    _static_names = ()

    def __init__(self, var_smoothing=1e-9):
        self.var_smoothing = var_smoothing

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        k = meta["n_classes"]

        def kernel(X, y_idx, sw, hyper, aux=None):
            vs = hyper["var_smoothing"]
            tot_w = jnp.maximum(jnp.sum(sw), 1e-12)
            gmean = jnp.sum(sw[:, None] * X, axis=0) / tot_w
            Xc = X - gmean  # centred: bounds moment magnitudes
            oh = jax.nn.one_hot(y_idx, k, dtype=X.dtype) * sw[:, None]
            cw = jnp.sum(oh, axis=0)  # (k,)
            means_c = (oh.T @ Xc) / jnp.maximum(cw[:, None], 1e-12)
            sq = oh.T @ (Xc * Xc)
            var = sq / jnp.maximum(cw[:, None], 1e-12) - means_c**2
            gvar = jnp.sum(sw[:, None] * Xc * Xc, axis=0) / tot_w
            var = jnp.maximum(var, 0.0) + vs * jnp.max(gvar)
            priors = cw / tot_w
            return {
                "gmean": gmean,
                "means_c": means_c,
                "var": var,
                "log_prior": jnp.log(jnp.maximum(priors, 1e-12)),
            }

        return kernel

    @classmethod
    def _build_decision_kernel(cls, meta, static):
        @jax.jit
        def decision(params, X):
            m, var = params["means_c"], params["var"]
            Xc = X - params["gmean"]
            # -(1/2)[Σ log 2πσ² + Σ (x-m)²/σ²] as matmuls, no (n,k,d)
            const = -0.5 * (
                jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)
                + jnp.sum(m * m / var, axis=1)
            )  # (k,)
            lin = Xc @ (m / var).T  # (n, k)
            quad = -0.5 * ((Xc * Xc) @ (1.0 / var).T)  # (n, k)
            return quad + lin + const[None, :] + params["log_prior"][None, :]

        return decision

    @classmethod
    def _build_proba_kernel(cls, meta, static):
        decision = cls._build_decision_kernel(meta, static)

        @jax.jit
        def proba(params, X):
            return jax.nn.softmax(decision(params, X), axis=1)

        return proba

    predict_proba = LogisticRegression.predict_proba
    predict_log_proba = LogisticRegression.predict_log_proba


class MultinomialNB(_LinearClassifierBase):
    """Multinomial naive Bayes (count features, e.g. hashed text).

    ``alpha`` (Lidstone smoothing, clamped to ≥1e-10 like sklearn) is a
    batchable hyper. The decision is linear in X, so ``coef_`` /
    ``intercept_`` expose the per-class feature log-probabilities and
    log-priors.
    """

    _hyper_names = ("alpha",)
    _static_names = ()

    def __init__(self, alpha=1.0):
        self.alpha = alpha

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        k = meta["n_classes"]

        def kernel(X, y_idx, sw, hyper, aux=None):
            alpha = jnp.maximum(hyper["alpha"], 1e-10)
            oh = jax.nn.one_hot(y_idx, k, dtype=X.dtype) * sw[:, None]
            counts = oh.T @ X  # (k, d) per-class feature totals
            smoothed = counts + alpha
            log_p = jnp.log(smoothed) - jnp.log(
                jnp.sum(smoothed, axis=1, keepdims=True)
            )
            cw = jnp.sum(oh, axis=0)
            log_prior = jnp.log(
                jnp.maximum(cw / jnp.maximum(jnp.sum(sw), 1e-12), 1e-12)
            )
            # linear form: decision = X @ log_p.T + log_prior
            W = jnp.concatenate([log_p.T, log_prior[None, :]], axis=0)
            return {"W": W}

        return kernel

    def _prep_fit_data(self, X, y, sample_weight=None):
        if np.asarray(X).min() < 0:
            raise ValueError(
                "Negative values in data passed to MultinomialNB "
                "(input X must be non-negative counts)"
            )
        return super()._prep_fit_data(X, y, sample_weight)

    @classmethod
    def _build_decision_kernel(cls, meta, static):
        d = meta["n_features"]

        @jax.jit
        def decision(params, X):
            W = params["W"]
            return X @ W[:d] + W[d]

        return decision

    @classmethod
    def _build_proba_kernel(cls, meta, static):
        decision = cls._build_decision_kernel(meta, static)

        @jax.jit
        def proba(params, X):
            return jax.nn.softmax(decision(params, X), axis=1)

        return proba

    predict_proba = LogisticRegression.predict_proba
    predict_log_proba = LogisticRegression.predict_log_proba
