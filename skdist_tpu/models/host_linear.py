"""
Host (CPU) solver engine for the linear classifiers.

The reference's ``sc=None`` path ran sklearn directly (reference
``skdist/distribute/search.py:388-408``), so a CPU-only user paid
sklearn prices — fast BLAS f64 L-BFGS. Our XLA kernels are built for
the device fan-out (one vmapped program per grid); running that same
program on a host CPU pays XLA-CPU prices for small matmuls and
whole-grid worst-case iteration counts (round-4 VERDICT weak #6:
12.1 s vs sklearn's 1.3 s on the covtype-shaped local LR grid).

This module is the linear analogue of ``native_forest``: the SAME
objective the XLA kernel minimises (``Σ sw·ce + 0.5/C·‖W[:d]‖²``,
intercept unpenalised, identical class weighting), solved on host in
f64 by scipy's L-BFGS-B — the exact workhorse sklearn's
LogisticRegression wraps — with BLAS-rate gradient matmuls. Both
engines minimise the same convex objective, so they agree at the
optimum to solver tolerance; engine selection is an execution detail,
like ``hist_mode`` for forests. ``engine='xla'`` pins the compiled
path (and with it the bit-level local==device agreement property).
"""

import numpy as np

__all__ = ["logreg_host_fit", "svc_host_fit", "host_engine_available"]


def host_engine_available():
    try:
        from scipy.optimize import minimize  # noqa: F401

        return True
    except Exception:  # pragma: no cover - scipy ships with sklearn
        return False


def _class_weighted_sw(sw, y_idx, k, class_weight, cw_arr):
    """Numpy mirror of ``linear._apply_class_weight`` (same 'balanced'
    heuristic on the current weights)."""
    if class_weight is None:
        return sw
    counts = np.bincount(y_idx, weights=sw, minlength=k)
    if class_weight == "balanced":
        per_class = sw.sum() / (k * np.maximum(counts, 1e-12))
        per_class = np.where(counts > 0, per_class, 0.0)
    else:
        per_class = np.asarray(cw_arr, dtype=np.float64)
    return sw * per_class[y_idx]


def logreg_host_fit(X, y_idx, sw, *, C, tol, max_iter, fit_intercept,
                    n_classes, history, class_weight, cw_arr, w0=None):
    """Fit one logistic regression on host; returns the same params
    pytree the XLA fit kernel yields (``{"W", "n_iter"}``, f32) plus
    the f64 optimum for warm-starting the next fit along a C path —
    or None in its place when the solver stopped on ``max_iter``
    rather than ``tol``: an unconverged endpoint is init-dependent,
    and seeding the chain with it would make CV scores depend on
    which other C values share the grid (round-5 review).

    Objective identical to ``LogisticRegression._build_fit_kernel``:
    binary uses the single-column softplus form, multinomial the
    softmax CE, both with the intercept column excluded from the
    ridge term.
    """
    from scipy.optimize import minimize
    from scipy.special import expit

    X = np.asarray(X, dtype=np.float64)
    n, d = X.shape
    k = int(n_classes)
    sw = _class_weighted_sw(
        np.asarray(sw, dtype=np.float64), y_idx, k, class_weight, cw_arr
    )
    Xa = np.concatenate([X, np.ones((n, 1))], axis=1) if fit_intercept else X
    p = Xa.shape[1]
    inv_C = 1.0 / float(C)
    binary = k <= 2
    # The minimised function is the weight-MEAN-scaled objective (both
    # terms divided by Σsw — sklearn's own internal scaling), so
    # scipy's gtol=tol stops at the same effective precision sklearn's
    # LogisticRegression(tol=...) does: iteration counts match sklearn
    # instead of growing with n. Scaling does not move the optimum, so
    # engine parity with the (sum-scaled) XLA kernel holds at the
    # solution; only the stopping rule's absolute scale differs.
    scale = 1.0 / max(float(sw.sum()), 1e-12)

    if binary:
        ypm = (y_idx == (k - 1)).astype(np.float64)

        def fun(w):
            z = Xa @ w
            ce = float(np.dot(sw, np.logaddexp(0.0, z) - ypm * z))
            reg = 0.5 * inv_C * float(np.dot(w[:d], w[:d]))
            resid = sw * (expit(z) - ypm)
            g = Xa.T @ resid
            g[:d] += inv_C * w[:d]
            return scale * (ce + reg), scale * g

        x0 = np.zeros(p) if w0 is None else np.asarray(w0, np.float64)
        res = minimize(
            fun, x0, jac=True, method="L-BFGS-B",
            options={"maxiter": int(max_iter), "maxcor": int(history),
                     "gtol": float(tol), "ftol": 1e-12},
        )
        params = {"W": res.x.astype(np.float32),
                  "n_iter": np.int32(res.nit)}
        return params, (res.x if res.status == 0 else None)

    onehot_rows = np.arange(n)

    def fun(wflat):
        W = wflat.reshape(p, k)
        z = Xa @ W
        zmax = z.max(axis=1)
        ez = np.exp(z - zmax[:, None])
        sez = ez.sum(axis=1)
        lse = zmax + np.log(sez)
        ce = float(np.dot(sw, lse - z[onehot_rows, y_idx]))
        P = ez / sez[:, None]
        P[onehot_rows, y_idx] -= 1.0
        G = Xa.T @ (sw[:, None] * P)
        G[:d] += inv_C * W[:d]
        reg = 0.5 * inv_C * float(np.sum(W[:d] * W[:d]))
        return scale * (ce + reg), scale * G.ravel()

    x0 = np.zeros(p * k) if w0 is None else np.asarray(w0, np.float64)
    res = minimize(
        fun, x0, jac=True, method="L-BFGS-B",
        options={"maxiter": int(max_iter), "maxcor": int(history),
                 "gtol": float(tol), "ftol": 1e-12},
    )
    params = {"W": res.x.reshape(p, k).astype(np.float32),
              "n_iter": np.int32(res.nit)}
    return params, (res.x if res.status == 0 else None)


def svc_host_fit(X, y_idx, sw, *, C, tol, max_iter, fit_intercept,
                 n_classes, history, class_weight, cw_arr, w0=None):
    """Squared-hinge linear SVM on host (objective identical to
    ``LinearSVC._build_fit_kernel``: ``0.5·‖W[:d]‖² + C·Σ sw·max(0,
    1−y·z)²``, intercept unpenalised, one-vs-rest columns solved
    jointly). Same mean-scaling/stopping treatment as
    :func:`logreg_host_fit`."""
    from scipy.optimize import minimize

    X = np.asarray(X, dtype=np.float64)
    n, d = X.shape
    k = int(n_classes)
    sw = _class_weighted_sw(
        np.asarray(sw, dtype=np.float64), y_idx, k, class_weight, cw_arr
    )
    Xa = np.concatenate([X, np.ones((n, 1))], axis=1) if fit_intercept else X
    p = Xa.shape[1]
    Cf = float(C)
    scale = 1.0 / max(float(sw.sum()), 1e-12)
    binary = k <= 2

    if binary:
        ypm = np.where(y_idx == (k - 1), 1.0, -1.0)

        def fun(w):
            z = Xa @ w
            margin = np.maximum(0.0, 1.0 - ypm * z)
            val = 0.5 * float(np.dot(w[:d], w[:d])) \
                + Cf * float(np.dot(sw, margin * margin))
            g = -2.0 * Cf * (Xa.T @ (sw * margin * ypm))
            g[:d] += w[:d]
            return scale * val, scale * g

        x0 = np.zeros(p) if w0 is None else np.asarray(w0, np.float64)
        res = minimize(
            fun, x0, jac=True, method="L-BFGS-B",
            options={"maxiter": int(max_iter), "maxcor": int(history),
                     "gtol": float(tol), "ftol": 1e-12},
        )
        return ({"W": res.x.astype(np.float32),
                 "n_iter": np.int32(res.nit)},
                res.x if res.status == 0 else None)

    Ypm = np.full((n, k), -1.0)
    Ypm[np.arange(n), y_idx] = 1.0

    def fun(wflat):
        W = wflat.reshape(p, k)
        margin = np.maximum(0.0, 1.0 - Ypm * (Xa @ W))
        val = 0.5 * float(np.sum(W[:d] * W[:d])) \
            + Cf * float(np.dot(sw, (margin * margin).sum(axis=1)))
        G = -2.0 * Cf * (Xa.T @ (sw[:, None] * margin * Ypm))
        G[:d] += W[:d]
        return scale * val, scale * G.ravel()

    x0 = np.zeros(p * k) if w0 is None else np.asarray(w0, np.float64)
    res = minimize(
        fun, x0, jac=True, method="L-BFGS-B",
        options={"maxiter": int(max_iter), "maxcor": int(history),
                 "gtol": float(tol), "ftol": 1e-12},
    )
    return ({"W": res.x.reshape(p, k).astype(np.float32),
             "n_iter": np.int32(res.nit)},
            res.x if res.status == 0 else None)
