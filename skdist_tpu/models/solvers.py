"""
Jittable optimisers for the linear-model kernels.

A compact L-BFGS (two-loop recursion, Armijo backtracking) written
directly in ``lax`` control flow so it is safe under ``jit`` *and*
``vmap`` — the property that lets a whole hyperparameter grid of fits
run as one XLA program. This replaces the scipy/liblinear solvers the
reference reached through sklearn (e.g. LogisticRegression in
``/root/reference/examples/search/basic_usage.py:99``).

Design notes for TPU:
- fixed-size ring-buffer history (static ``history``), no dynamic shapes
- convergence handled with a ``done`` flag in the carry so converged
  vmap lanes freeze while others keep iterating (vmap of while_loop
  steps all lanes until every lane's predicate is false)
- all dot products are on flat f32 vectors; the heavy lifting (loss and
  gradient) is the caller's X @ W matmuls, which land on the MXU
"""

import jax
import jax.numpy as jnp
from jax import lax

_EPS = 1e-12


def lbfgs_minimize(fun, w0, max_iter=100, tol=1e-4, history=10, max_ls=20):
    """Minimise ``fun(w) -> scalar`` from ``w0`` (flat vector).

    Returns ``(w, n_iter)``. Convergence: ``max|grad| <= tol`` (the same
    criterion sklearn passes to scipy's lbfgs as ``gtol``).
    """
    value_and_grad = jax.value_and_grad(fun)
    p = w0.shape[0]
    m = history

    f0, g0 = value_and_grad(w0)

    def two_loop(g, S, Y, rho, k):
        n_corr = jnp.minimum(k, m)

        def bwd(i, carry):
            q, alphas = carry
            idx = (k - 1 - i) % m
            valid = i < n_corr
            alpha = rho[idx] * jnp.dot(S[idx], q)
            alpha = jnp.where(valid, alpha, 0.0)
            q = q - alpha * Y[idx]
            return q, alphas.at[idx].set(alpha)

        q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros(m, g.dtype)))
        last = (k - 1) % m
        sy = jnp.dot(S[last], Y[last])
        yy = jnp.dot(Y[last], Y[last])
        gamma = jnp.where(k > 0, sy / (yy + _EPS), 1.0)
        r = gamma * q

        def fwd(i, r):
            idx = (k - n_corr + i) % m
            valid = i < n_corr
            beta = rho[idx] * jnp.dot(Y[idx], r)
            upd = S[idx] * (alphas[idx] - beta)
            return r + jnp.where(valid, upd, 0.0)

        return -lax.fori_loop(0, m, fwd, r)

    def line_search(w, f, g, d):
        """Armijo backtracking; returns (step, f_new, accepted)."""
        gd = jnp.dot(g, d)

        def cond(carry):
            t, f_new, it = carry
            armijo = f_new <= f + 1e-4 * t * gd
            return jnp.logical_and(~armijo, it < max_ls)

        def body(carry):
            t, _, it = carry
            t = t * 0.5
            return t, fun(w + t * d), it + 1

        t0 = 1.0
        f1 = fun(w + t0 * d)
        t, f_new, _ = lax.while_loop(cond, body, (t0, f1, 0))
        ok = f_new <= f + 1e-4 * t * gd
        return t, f_new, ok

    def cond(state):
        _, _, _, _, _, _, _, it, done = state
        return jnp.logical_and(it < max_iter, ~done)

    def body(state):
        w, f, g, S, Y, rho, k, it, done = state
        d = two_loop(g, S, Y, rho, k)
        # safeguard: fall back to steepest descent if d isn't a descent dir
        descent = jnp.dot(g, d) < 0
        d = jnp.where(descent, d, -g)
        # a raw -g direction (first iteration, or the fallback above)
        # has arbitrary scale: on unscaled data |g| can be ~1e6, and
        # max_ls backtracking halvings from t=1 cannot reach a usable
        # step — the line search "stalls" and the solver would stop
        # after one iteration. Normalise those directions so the unit
        # backtracking grid covers them; curvature-scaled directions
        # (k > 0 via two_loop's gamma) are already well-sized.
        raw_scale = jnp.logical_or(~descent, k == 0)
        d = jnp.where(
            raw_scale, d / (jnp.linalg.norm(d) + _EPS), d
        )
        t, f_new, ok = line_search(w, f, g, d)
        w_new = w + t * d
        f_new2, g_new = value_and_grad(w_new)
        s = w_new - w
        yv = g_new - g
        sy = jnp.dot(s, yv)
        # curvature check: only store pairs with s·y > 0
        store = sy > 1e-10
        idx = k % m
        S = jnp.where(store, S.at[idx].set(s), S)
        Y = jnp.where(store, Y.at[idx].set(yv), Y)
        rho = jnp.where(store, rho.at[idx].set(1.0 / (sy + _EPS)), rho)
        k_new = k + jnp.where(store, 1, 0)
        converged = jnp.max(jnp.abs(g_new)) <= tol
        stalled = ~ok  # line search failed to find decrease
        return (w_new, f_new2, g_new, S, Y, rho, k_new, it + 1,
                converged | stalled)

    S = jnp.zeros((m, p), w0.dtype)
    Y = jnp.zeros((m, p), w0.dtype)
    rho = jnp.zeros(m, w0.dtype)
    done0 = jnp.max(jnp.abs(g0)) <= tol
    state = (w0, f0, g0, S, Y, rho, jnp.array(0), jnp.array(0), done0)
    w, _, _, _, _, _, _, it, _ = lax.while_loop(cond, body, state)
    return w, it


def sgd_minimize(grad_fn, w0, n_samples, key, max_epochs, batch_size,
                 learning_rate_fn, shuffle=True, loss_fn=None, tol=None,
                 n_iter_no_change=5, post_step=None, post_state=None):
    """Mini-batch SGD with per-step learning-rate schedule.

    ``grad_fn(w, idx) -> grad`` computes the (penalised) gradient on the
    sample index batch ``idx``. Fixed-shape batches: ``n_samples`` is
    padded up to a multiple of ``batch_size`` with wrap-around indices —
    acceptable for the stochastic setting and keeps shapes static.

    Early stopping (sklearn ``SGDClassifier``'s no-validation rule):
    when ``loss_fn(w, idx) -> weighted mean batch loss`` and ``tol`` (a
    traced scalar is fine — it may ride a vmapped hyper axis) are
    given, the mean per-batch training loss of each epoch is tracked;
    an epoch that fails to beat ``best_loss - tol`` counts against
    ``n_iter_no_change``, and once the count is reached the lane
    FREEZES — the scan still runs ``max_epochs`` iterations (static
    shape, vmap-batchable), but stopped lanes keep their weights, so
    ``tol`` semantics hold per task without dynamic trip counts. A
    ``tol`` of ``-inf`` (the mapping for sklearn's ``tol=None``) never
    triggers and reproduces the fixed-epoch behaviour.

    ``post_step(w, state, lr) -> (w, state)``: stateful per-update
    transform applied AFTER each gradient step, threaded through the
    scan from ``post_state`` (an arbitrary pytree; frozen lanes keep
    it). The truncated-gradient L1 penalty (Tsuruoka et al.'s
    cumulative penalty, what sklearn's SGD applies) lives here — it is
    a proximal-style elementwise operation with persistent (u, q)
    state, not a gradient term.

    Returns ``(w, n_epochs_run)``.
    """
    n_batches = -(-n_samples // batch_size)
    padded = n_batches * batch_size
    track = loss_fn is not None and tol is not None
    if post_step is None:
        post_state = ()

    def epoch(carry, ekey):
        w, pstate, step, best, bad, stopped, n_done = carry
        if shuffle:
            perm = jax.random.permutation(ekey, padded) % n_samples
        else:
            perm = jnp.arange(padded) % n_samples
        batches = perm.reshape(n_batches, batch_size)

        def one(carry, idx):
            w, pstate, step, acc = carry
            g = grad_fn(w, idx)
            lr = learning_rate_fn(step)
            w_new = w - lr * g
            if post_step is not None:
                w_new, pstate = post_step(w_new, pstate, lr)
            if track:
                acc = acc + loss_fn(w_new, idx)
            return (w_new, pstate, step + 1, acc), None

        (w_new, pstate_new, step_new, acc), _ = lax.scan(
            one, (w, pstate, step, jnp.float32(0.0)), batches
        )
        if not track:
            return (w_new, pstate_new, step_new, best, bad, stopped,
                    n_done + 1), None
        loss = acc / n_batches
        improved = loss < best - tol
        bad_new = jnp.where(improved, 0, bad + 1)
        newly_stopped = bad_new >= n_iter_no_change
        # frozen lanes keep everything; live lanes advance and may stop
        keep = stopped

        def pick(a, b):
            return jnp.where(keep, a, b)

        return (
            pick(w, w_new),
            jax.tree_util.tree_map(pick, pstate, pstate_new),
            pick(step, step_new),
            pick(best, jnp.minimum(best, loss)),
            pick(bad, bad_new),
            jnp.logical_or(keep, newly_stopped),
            pick(n_done, n_done + 1),
        ), None

    keys = jax.random.split(key, max_epochs)
    state0 = (w0, post_state, jnp.array(0), jnp.float32(jnp.inf),
              jnp.array(0), jnp.array(False), jnp.array(0))
    (w, _, _, _, _, _, n_done), _ = lax.scan(epoch, state0, keys)
    return w, n_done
