"""
Jittable optimisers for the linear-model kernels.

A compact L-BFGS (two-loop recursion, Armijo backtracking) written
directly in ``lax`` control flow so it is safe under ``jit`` *and*
``vmap`` — the property that lets a whole hyperparameter grid of fits
run as one XLA program. This replaces the scipy/liblinear solvers the
reference reached through sklearn (e.g. LogisticRegression in
``/root/reference/examples/search/basic_usage.py:99``).

Design notes for TPU:
- fixed-size ring-buffer history (static ``history``), no dynamic shapes
- convergence handled with a ``done`` flag in the carry so converged
  vmap lanes freeze while others keep iterating (vmap of while_loop
  steps all lanes until every lane's predicate is false)
- all dot products are on flat f32 vectors; the heavy lifting (loss and
  gradient) is the caller's X @ W matmuls, which land on the MXU

Resumable carry form (convergence-compacted scheduling): both solvers
also expose an explicit carry-in/carry-out API —
``lbfgs_carry_init`` / ``lbfgs_resume`` and ``sgd_carry_init`` /
``sgd_resume`` — so a solve can run in bounded iteration *slices* and
resume exactly where it left off. The carries are plain dict pytrees
(every leaf a fixed-shape array), so a vmapped batch of carries is a
batch of arrays the fan-out backend can gather, compact to the
still-running lanes, and re-dispatch. ``lbfgs_minimize`` /
``sgd_minimize`` are themselves implemented as init + one full-length
resume, which is what makes a sliced run *bitwise identical* to the
unsliced solve: both apply the same traced body the same number of
times to the same carried state — slicing only changes where the host
observes the carry. The carries are also *scoreable* mid-solve: the
current iterate (:func:`carry_iterate`) is a valid model at every slice
boundary, which is what lets the adaptive (ASHA) scheduler evaluate
live lanes on the validation fold without touching the trajectory.

Data-representation agnosticism: neither solver ever touches X — the
heavy contractions live in the caller's loss/grad closures, built over
the ``skdist_tpu.sparse.LinearOperator`` matvec interface. A packed-CSR
X (gather ``X @ w`` forward, whose autodiff VJP is the scatter-add
``X.T @ r``) therefore runs through BOTH solvers — and the iteration-
sliced carry forms, and the convergence-compacted scheduler above them
— without a single sparse-specific line here: per-iteration cost drops
from O(n·d) to O(nnz) purely through the closures.
"""

import jax
import jax.numpy as jnp
from jax import lax

_EPS = 1e-12

#: order of the L-BFGS carry leaves (the ISSUE-pinned pytree contract)
LBFGS_CARRY_KEYS = ("w", "f", "g", "S", "Y", "rho", "k", "it", "done")


def carry_iterate(carry):
    """Current weight iterate of a solver carry — the leaf the adaptive
    (ASHA) rung evaluator scores MID-SOLVE.

    Both solver families keep the live iterate under ``"w"`` and keep
    it valid at every slice boundary: L-BFGS writes ``w`` only after an
    accepted (or stalled-in-place) line-search step, and the SGD epoch
    body freezes stopped lanes' weights in place — so ``carry["w"]`` is
    always a usable model, never a half-updated scratch buffer. The
    score-from-carry kernels (``models/linear.py``
    ``_build_fit_slice_kernels[...]["score_params"]``) read it through
    this helper so the contract has one name."""
    return carry["w"]


def _lbfgs_body(fun, value_and_grad, max_iter, tol, history, max_ls):
    """One L-BFGS iteration on the tuple state
    ``(w, f, g, S, Y, rho, k, it, done)`` — shared verbatim by the
    unsliced solve and every resume slice, so their trajectories cannot
    diverge."""
    m = history

    def two_loop(g, S, Y, rho, k):
        n_corr = jnp.minimum(k, m)

        def bwd(i, carry):
            q, alphas = carry
            idx = (k - 1 - i) % m
            valid = i < n_corr
            alpha = rho[idx] * jnp.dot(S[idx], q)
            alpha = jnp.where(valid, alpha, 0.0)
            q = q - alpha * Y[idx]
            return q, alphas.at[idx].set(alpha)

        q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros(m, g.dtype)))
        last = (k - 1) % m
        sy = jnp.dot(S[last], Y[last])
        yy = jnp.dot(Y[last], Y[last])
        gamma = jnp.where(k > 0, sy / (yy + _EPS), 1.0)
        r = gamma * q

        def fwd(i, r):
            idx = (k - n_corr + i) % m
            valid = i < n_corr
            beta = rho[idx] * jnp.dot(Y[idx], r)
            upd = S[idx] * (alphas[idx] - beta)
            return r + jnp.where(valid, upd, 0.0)

        return -lax.fori_loop(0, m, fwd, r)

    def line_search(w, f, g, d):
        """Armijo backtracking; returns (step, f_new, accepted)."""
        gd = jnp.dot(g, d)

        def cond(carry):
            t, f_new, it = carry
            armijo = f_new <= f + 1e-4 * t * gd
            return jnp.logical_and(~armijo, it < max_ls)

        def body(carry):
            t, _, it = carry
            t = t * 0.5
            return t, fun(w + t * d), it + 1

        t0 = 1.0
        f1 = fun(w + t0 * d)
        t, f_new, _ = lax.while_loop(cond, body, (t0, f1, 0))
        ok = f_new <= f + 1e-4 * t * gd
        return t, f_new, ok

    def body(state):
        w, f, g, S, Y, rho, k, it, done = state
        d = two_loop(g, S, Y, rho, k)
        # safeguard: fall back to steepest descent if d isn't a descent dir
        descent = jnp.dot(g, d) < 0
        d = jnp.where(descent, d, -g)
        # a raw -g direction (first iteration, or the fallback above)
        # has arbitrary scale: on unscaled data |g| can be ~1e6, and
        # max_ls backtracking halvings from t=1 cannot reach a usable
        # step — the line search "stalls" and the solver would stop
        # after one iteration. Normalise those directions so the unit
        # backtracking grid covers them; curvature-scaled directions
        # (k > 0 via two_loop's gamma) are already well-sized.
        raw_scale = jnp.logical_or(~descent, k == 0)
        d = jnp.where(
            raw_scale, d / (jnp.linalg.norm(d) + _EPS), d
        )
        t, f_new, ok = line_search(w, f, g, d)
        w_new = w + t * d
        f_new2, g_new = value_and_grad(w_new)
        s = w_new - w
        yv = g_new - g
        sy = jnp.dot(s, yv)
        # curvature check: only store pairs with s·y > 0
        store = sy > 1e-10
        idx = k % m
        S = jnp.where(store, S.at[idx].set(s), S)
        Y = jnp.where(store, Y.at[idx].set(yv), Y)
        rho = jnp.where(store, rho.at[idx].set(1.0 / (sy + _EPS)), rho)
        k_new = k + jnp.where(store, 1, 0)
        converged = jnp.max(jnp.abs(g_new)) <= tol
        stalled = ~ok  # line search failed to find decrease
        # ``done`` also latches the iteration cap so the flag alone
        # answers "will more steps change this lane?" — what the
        # backend's flags-only compaction gather reads
        done_new = converged | stalled | (it + 1 >= max_iter)
        return (w_new, f_new2, g_new, S, Y, rho, k_new, it + 1, done_new)

    return body


def lbfgs_carry_init(fun, w0, max_iter=100, tol=1e-4, history=10):
    """Initial L-BFGS carry for ``fun(w) -> scalar`` from ``w0``.

    The carry is a dict pytree over :data:`LBFGS_CARRY_KEYS`; feed it to
    :func:`lbfgs_resume` to advance it. ``done`` is True when no further
    step can change the lane (converged at ``tol``, line-search stall,
    or ``max_iter`` reached)."""
    value_and_grad = jax.value_and_grad(fun)
    p = w0.shape[0]
    m = history
    f0, g0 = value_and_grad(w0)
    done0 = (jnp.max(jnp.abs(g0)) <= tol) | jnp.asarray(max_iter <= 0)
    return dict(zip(LBFGS_CARRY_KEYS, (
        w0, f0, g0,
        jnp.zeros((m, p), w0.dtype),
        jnp.zeros((m, p), w0.dtype),
        jnp.zeros(m, w0.dtype),
        jnp.array(0), jnp.array(0), done0,
    )))


def lbfgs_resume(fun, carry, n_steps, max_iter=100, tol=1e-4, history=10,
                 max_ls=20):
    """Advance an L-BFGS carry by at most ``n_steps`` iterations.

    Applies the exact iteration body of :func:`lbfgs_minimize` (they
    share one closure), stopping early when the lane converges/stalls
    or hits ``max_iter``. ``n_steps >= max_iter`` therefore runs the
    solve to completion in one call — which is precisely how
    ``lbfgs_minimize`` is implemented, making chained short resumes
    bitwise identical to the unsliced solve."""
    value_and_grad = jax.value_and_grad(fun)
    body = _lbfgs_body(fun, value_and_grad, max_iter, tol, history, max_ls)
    state = tuple(carry[k] for k in LBFGS_CARRY_KEYS)

    def cond_j(state_j):
        (_, _, _, _, _, _, _, it, done), j = state_j
        return (j < n_steps) & (it < max_iter) & ~done

    def body_j(state_j):
        state, j = state_j
        return body(state), j + 1

    state, _ = lax.while_loop(cond_j, body_j, (state, jnp.array(0)))
    return dict(zip(LBFGS_CARRY_KEYS, state))


def lbfgs_minimize(fun, w0, max_iter=100, tol=1e-4, history=10, max_ls=20):
    """Minimise ``fun(w) -> scalar`` from ``w0`` (flat vector).

    Returns ``(w, n_iter)``. Convergence: ``max|grad| <= tol`` (the same
    criterion sklearn passes to scipy's lbfgs as ``gtol``). Implemented
    as :func:`lbfgs_carry_init` + one full-length
    :func:`lbfgs_resume`, so iteration-sliced runs share its exact
    trajectory."""
    carry = lbfgs_carry_init(fun, w0, max_iter=max_iter, tol=tol,
                             history=history)
    carry = lbfgs_resume(fun, carry, max_iter, max_iter=max_iter, tol=tol,
                         history=history, max_ls=max_ls)
    return carry["w"], carry["it"]


# ---------------------------------------------------------------------------
# SGD
# ---------------------------------------------------------------------------

#: order of the SGD carry leaves (``pstate`` is the post_step pytree)
SGD_CARRY_KEYS = ("w", "pstate", "step", "best", "bad", "n_done", "it",
                  "done")


def sgd_batch_scan(grad_fn, learning_rate_fn, post_step, loss_fn, track,
                   carry4, batches):
    """Advance the ``(w, pstate, step, acc)`` quadruple over a fixed
    stack of sample-index ``batches`` — the inner mini-batch loop,
    extracted so the resident epoch body (:func:`_sgd_epoch_body`) and
    the streamed block kernels (``models/streaming.py``; an epoch there
    is a SEQUENCE of these scans, one per row block) apply the exact
    same traced update — identical op sequence, so a block-streamed
    epoch that visits the same rows in the same order is bitwise
    identical to the resident scan."""

    def one(carry, idx):
        w, pstate, step, acc = carry
        g = grad_fn(w, idx)
        lr = learning_rate_fn(step)
        w_new = w - lr * g
        if post_step is not None:
            w_new, pstate = post_step(w_new, pstate, lr)
        if track:
            acc = acc + loss_fn(w_new, idx)
        return (w_new, pstate, step + 1, acc), None

    return lax.scan(one, carry4, batches)[0]


def _sgd_epoch_body(grad_fn, keys, n_samples, max_epochs, batch_size,
                    learning_rate_fn, shuffle, loss_fn, tol,
                    n_iter_no_change, post_step):
    """One SGD epoch on the tuple state
    ``(w, pstate, step, best, bad, n_done, it, done)``, keyed by the
    *global* epoch index (``it``-relative) so a resumed slice draws the
    same shuffles the unsliced scan would. Shared by the unsliced solve
    and every slice."""
    n_batches = -(-n_samples // batch_size)
    padded = n_batches * batch_size
    track = loss_fn is not None and tol is not None

    def epoch(carry, e):
        w, pstate, step, best, bad, n_done, it, done = carry
        # global epoch index -> the SAME per-epoch key as the unsliced
        # scan; clamped for overhanging slice tails (frozen below)
        ekey = keys[jnp.minimum(e, max_epochs - 1)]
        if shuffle:
            perm = jax.random.permutation(ekey, padded) % n_samples
        else:
            perm = jnp.arange(padded) % n_samples
        batches = perm.reshape(n_batches, batch_size)

        w_new, pstate_new, step_new, acc = sgd_batch_scan(
            grad_fn, learning_rate_fn, post_step, loss_fn, track,
            (w, pstate, step, jnp.float32(0.0)), batches,
        )
        # frozen lanes keep everything: early-stopped lanes, and every
        # lane of an epoch index past max_epochs (a slice tail that
        # overhangs the cap — the unsliced scan never reaches it)
        keep = done | (e >= max_epochs)

        def pick(a, b):
            return jnp.where(keep, a, b)

        if track:
            loss = acc / n_batches
            improved = loss < best - tol
            bad_new = jnp.where(improved, 0, bad + 1)
            newly_stopped = bad_new >= n_iter_no_change
            best_new = jnp.minimum(best, loss)
        else:
            bad_new = bad
            newly_stopped = jnp.asarray(False)
            best_new = best
        it_new = jnp.where(e >= max_epochs, it, it + 1)
        done_new = keep | newly_stopped | (it_new >= max_epochs)
        return (
            pick(w, w_new),
            jax.tree_util.tree_map(pick, pstate, pstate_new),
            pick(step, step_new),
            pick(best, best_new),
            pick(bad, bad_new),
            pick(n_done, n_done + 1),
            it_new,
            done_new,
        ), None

    return epoch


def sgd_carry_init(w0, post_state=()):
    """Initial SGD carry (dict over :data:`SGD_CARRY_KEYS`)."""
    return dict(zip(SGD_CARRY_KEYS, (
        w0, post_state, jnp.array(0), jnp.float32(jnp.inf),
        jnp.array(0), jnp.array(0), jnp.array(0), jnp.array(False),
    )))


def sgd_resume(grad_fn, carry, n_steps, n_samples, key, max_epochs,
               batch_size, learning_rate_fn, shuffle=True, loss_fn=None,
               tol=None, n_iter_no_change=5, post_step=None):
    """Advance an SGD carry by ``n_steps`` epochs (a fixed-shape scan;
    lanes already stopped — and slice tails overhanging ``max_epochs``
    — freeze in place, exactly as the unsliced scan freezes stopped
    lanes). ``key`` must be the same PRNG key every call: per-epoch
    keys are re-derived from it and indexed by the carry's global epoch
    clock, so slice boundaries cannot change the shuffle sequence."""
    keys = jax.random.split(key, max_epochs)
    epoch = _sgd_epoch_body(
        grad_fn, keys, n_samples, max_epochs, batch_size,
        learning_rate_fn, shuffle, loss_fn, tol, n_iter_no_change,
        post_step,
    )
    state = tuple(carry[k] for k in SGD_CARRY_KEYS)
    it0 = carry["it"]
    state, _ = lax.scan(epoch, state, it0 + jnp.arange(n_steps))
    return dict(zip(SGD_CARRY_KEYS, state))


def sgd_minimize(grad_fn, w0, n_samples, key, max_epochs, batch_size,
                 learning_rate_fn, shuffle=True, loss_fn=None, tol=None,
                 n_iter_no_change=5, post_step=None, post_state=None):
    """Mini-batch SGD with per-step learning-rate schedule.

    ``grad_fn(w, idx) -> grad`` computes the (penalised) gradient on the
    sample index batch ``idx``. Fixed-shape batches: ``n_samples`` is
    padded up to a multiple of ``batch_size`` with wrap-around indices —
    acceptable for the stochastic setting and keeps shapes static.

    Early stopping (sklearn ``SGDClassifier``'s no-validation rule):
    when ``loss_fn(w, idx) -> weighted mean batch loss`` and ``tol`` (a
    traced scalar is fine — it may ride a vmapped hyper axis) are
    given, the mean per-batch training loss of each epoch is tracked;
    an epoch that fails to beat ``best_loss - tol`` counts against
    ``n_iter_no_change``, and once the count is reached the lane
    FREEZES — the scan still runs ``max_epochs`` iterations (static
    shape, vmap-batchable), but stopped lanes keep their weights, so
    ``tol`` semantics hold per task without dynamic trip counts. A
    ``tol`` of ``-inf`` (the mapping for sklearn's ``tol=None``) never
    triggers and reproduces the fixed-epoch behaviour.

    ``post_step(w, state, lr) -> (w, state)``: stateful per-update
    transform applied AFTER each gradient step, threaded through the
    scan from ``post_state`` (an arbitrary pytree; frozen lanes keep
    it). The truncated-gradient L1 penalty (Tsuruoka et al.'s
    cumulative penalty, what sklearn's SGD applies) lives here — it is
    a proximal-style elementwise operation with persistent (u, q)
    state, not a gradient term.

    Implemented as :func:`sgd_carry_init` + one ``max_epochs``-long
    :func:`sgd_resume`, so iteration-sliced runs share its exact
    epoch sequence. Returns ``(w, n_epochs_run)``.
    """
    if post_step is None:
        post_state = ()
    carry = sgd_carry_init(w0, post_state)
    carry = sgd_resume(
        grad_fn, carry, max_epochs, n_samples, key, max_epochs,
        batch_size, learning_rate_fn, shuffle=shuffle, loss_fn=loss_fn,
        tol=tol, n_iter_no_change=n_iter_no_change, post_step=post_step,
    )
    return carry["w"], carry["n_done"]
