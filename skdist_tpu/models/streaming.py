"""
Streamed (out-of-core) fit drivers: the solver carry forms rewired to
consume a :class:`~skdist_tpu.data.ChunkedDataset` block by block
through the backend's double-buffered host→device pipeline
(``parallel.backend.BlockFeeder``).

Three family forms, selected by the estimator's ``_stream_fit_kind``:

- **"lbfgs"** (LogisticRegression, LinearSVC): the objective's data
  term is row-additive, so one evaluation of ``(f, g)`` at the current
  iterate is a streamed reduction — each block contributes
  ``value_and_grad`` of its block-local data loss (through the same
  ``LinearOperator`` matvec interface as the resident problem, dense or
  packed-CSR; on a mesh with a 'data' axis the block row-shards and
  GSPMD psums the partials), the regulariser is evaluated once, and the
  L-BFGS state machine (two-loop recursion, Armijo backtracking —
  mirroring ``models/solvers._lbfgs_body`` lane for lane) runs
  host-side over the task batch. Each line-search probe is a value-only
  streamed pass. Block accumulation reorders f32 sums, so results agree
  with the resident solve to tolerance, not bitwise.
- **"sgd"** (SGDClassifier): epochs become block streams. An epoch
  visits blocks in order; within a block, mini-batches advance the
  ``(w, pstate, step, acc)`` carry through the SAME traced update as
  the resident scan (``solvers.sgd_batch_scan``), with the global epoch
  clock keying block-local shuffles. With ``shuffle=False`` and batch
  boundaries aligned to block boundaries, the visit order equals the
  resident scan's and the streamed fit is BITWISE identical to it.
  Early stopping applies sklearn's no-improvement rule at epoch
  boundaries exactly as the resident epoch body does.
- **"gram"** (Ridge family): the normal equations accumulate — each
  block contributes its ``(XᵀSX, XᵀST)`` partials, one small solve
  finishes per task.
- **"gbdt"** (DistHistGradientBoosting*): boosting rounds become
  binned-cache streams. Raw features are touched exactly twice up
  front (the quantile-sketch pass and the bin pass that writes the
  uint8 cache, both inside ``ChunkedDataset.with_binned_cache``);
  every boosting round then streams the ~4×-smaller cache: one
  histogram pass per tree level (per-node grad/hess histograms
  accumulated across blocks, psum'd over the mesh 'data' axis by
  GSPMD) plus one update pass advancing the margin carry ``F`` —
  which lives in host memmaps and rides the block tree, so device
  memory stays O(block). Split scoring runs the resident kernel's own
  ``histogram_node_scores``/``pick_level_splits`` on the gathered
  histograms, so resident-vs-streamed trees agree to f32 block-sum
  tolerance. The rung hook fires at every round boundary.

Every driver dispatches per-task batches (the CV search's candidate ×
fold axis, OvR's class axis) through one vmapped program whose task
axis shards over the backend mesh; fault handling is block-granular —
a transient fault re-dispatches the failed block with the reader
RE-OPENED at that offset (``BlockFeeder.seek``), a preemption restarts
the current pass after re-placing device state.
"""

import math
import os
import shutil
import tempfile
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import metrics as obs_metrics
from ..parallel import faults
from ..parallel.backend import BlockFeeder, _RetryState, _RoundFault

__all__ = [
    "stream_fit_estimator",
    "stream_fit_tasks",
    "stream_scores",
    "lbfgs_stream",
]

_EPS = np.float32(1e-12)


# ---------------------------------------------------------------------------
# block plumbing
# ---------------------------------------------------------------------------

def _pad_rows_for(name):
    """Pad value for a per-row array appended to a padded block: all
    streamed row arrays pad with values that cannot influence a fit —
    weights pad 0 (excluded from every contraction), fold ids pad -1
    (never a real split id), labels pad 0 (a valid class index whose
    row has zero weight)."""
    return -1 if name == "fold" else 0


def _make_block_read(dataset, row_arrays, pad=True):
    """``read(i) -> host block tree`` composing the dataset's X block
    with driver-owned per-row vectors (encoded labels, weights, fold
    ids) sliced to the block's global row range."""

    def read(i):
        b = dataset.read_block(i, pad=pad)
        tree = {"X": b.X}
        s, e = b.start, b.stop
        rows = dataset.block_rows if pad else b.n_real
        pad_n = rows - b.n_real
        for name, arr in row_arrays.items():
            sl = np.asarray(arr[s:e])
            if pad_n:
                sl = np.concatenate([
                    sl,
                    np.full((pad_n,) + sl.shape[1:],
                            _pad_rows_for(name), sl.dtype),
                ])
            tree[name] = sl
        return tree

    return read


def _example_block(dataset, row_arrays, extra_scalars=()):
    """Zero-filled block tree with the runtime block's exact structure
    and shapes — what mesh backends with a 'data' axis need to resolve
    per-leaf block shardings without reading data."""
    from ..sparse import PackedX

    r = dataset.block_rows
    if dataset.x_format == "packed":
        X = PackedX(
            np.zeros((r, dataset.packed_m), np.int32),
            np.zeros((r, dataset.packed_m), np.float32),
            dataset.n_features,
        )
    else:
        X = np.zeros((r, dataset.n_features), np.float32)
    tree = {"X": X}
    for name, arr in row_arrays.items():
        arr = np.asarray(arr)
        tree[name] = np.zeros((r,) + arr.shape[1:], arr.dtype)
    for name in extra_scalars:
        tree[name] = np.int32(0)
    return tree


def _stream_stats(backend, sync):
    stats = backend.last_round_stats = obs_metrics.new_round_stats(
        "streamed",
        stream_mode="serial" if sync else "pipelined",
    )
    return stats


def _resolve_sync(backend, sync):
    return bool(getattr(backend, "sync_rounds", False)) if sync is None \
        else bool(sync)


class _BlockRetry:
    """Block-granular fault policy shared by every streamed pass: a
    retryable fault at block ``i`` seeks the feeder back to ``i`` (the
    reader re-opens at exactly that offset) and re-dispatches; budget
    accounting matches the round loop's per-round contract (the counter
    resets on progress). A PREEMPTED fault calls ``restart`` (the
    driver re-places device state and rewinds its accumulators) and
    seeks to the pass start."""

    def __init__(self, stats):
        self.retry = _RetryState()
        self.stats = stats

    def handle(self, exc, feeder, i, restart=None):
        kind = faults.classify(exc)
        if not faults.is_retryable(kind):
            raise exc
        self.retry.admit(_RoundFault([], 0, exc, kind), i)
        self.stats["retries"] = self.retry.total
        if kind == faults.PREEMPTED and restart is not None:
            restart()
            feeder.seek(0)
            return 0
        feeder.seek(i)
        return i


def _dispatch_seam():
    """The fault-injection seam: a planned transient/preempt/fatal
    fires here, where a real device dispatch would fail."""
    inj = faults.active_injector()
    if inj is not None:
        inj.round_dispatched()


def _elastic_replans(backend, plans):
    """The elastic half of a streamed PREEMPTED restart: let an
    elastic backend shrink its mesh to the surviving devices, then
    re-resolve every driver plan in place against the new mesh
    (:meth:`StreamPlan.rebuild`) BEFORE the caller re-places its task
    trees. The divisor rule of the mesh manager keeps the shrunken
    task extent dividing the full one, so task axes already padded to
    full-mesh slots re-place on the shrunken mesh unchanged — which is
    why a resumed streamed fit stays bitwise identical: the same
    lanes, the same block order, the same arithmetic, just fewer
    devices under them. No-op (False) on non-elastic backends."""
    if backend.elastic_preempted():
        for p in plans:
            p.rebuild()
        return True
    return False


def _n_tasks(task_args):
    return len(np.asarray(next(iter(task_args["hyper"].values()))))


def _take_tree(tree, idx):
    """Subset every task-axis leaf to the given lane indices — the
    task-batch SHRINK of a rung kill: retired lanes' slots compact
    away and later passes dispatch fewer programs."""
    return jax.tree_util.tree_map(lambda a: np.asarray(a)[idx], tree)


def _pad_tree_to(tree, T, Tp):
    """Pad every task-axis leaf to exactly ``Tp`` rows by repeating
    the last lane; padded lanes compute duplicate work and their
    outputs are sliced off."""
    if Tp == T:
        return tree
    pad = Tp - T
    return jax.tree_util.tree_map(
        lambda a: np.concatenate(
            [np.asarray(a), np.repeat(np.asarray(a)[-1:], pad, axis=0)]
        ),
        tree,
    )


def _slot_pad_tree(tree, T, slots):
    """Pad every task-axis leaf to a slot multiple by repeating the
    last lane — mesh task sharding needs a divisible axis (the
    streamed analogue of the round loop's tail padding)."""
    Tp = -(-T // max(1, int(slots))) * max(1, int(slots))
    return _pad_tree_to(tree, T, Tp), Tp


# ---------------------------------------------------------------------------
# streamed reductions (the L-BFGS / gram data passes)
# ---------------------------------------------------------------------------

def _streamed_sum(plan, read, n_blocks, tc, stats, sync, restart=None):
    """Sum ``plan.fn(block, tc)`` over all blocks (device-resident
    accumulator; one D2H at the end). ``tc`` may be a zero-arg callable
    re-evaluated per dispatch (so a preemption ``restart`` can swap in
    freshly-placed task trees). The reduction is block-order
    deterministic: serial and pipelined feeds produce bitwise-identical
    sums.

    Fault handling is two-tier, mirroring where XLA surfaces errors:
    dispatch-time faults retry at BLOCK granularity (the feeder
    re-opens the reader at the failed offset), while faults that only
    surface at the blocking gather (asynchronous dispatch poisons the
    whole accumulator chain) retry the PASS — same retry budget."""
    tc_fn = tc if callable(tc) else (lambda: tc)
    pass_guard = _BlockRetry(stats)
    while True:
        acc = None
        # late-bind placement through the plan object: an elastic
        # restart rebuilds the plan in place mid-pass, and the feeder
        # must place subsequent blocks on the NEW mesh
        feeder = BlockFeeder(read, n_blocks, lambda t: plan.put_block(t),
                             sync=sync, stats=stats)
        guard = _BlockRetry(stats)
        try:
            while True:
                item = feeder.next()
                if item is None:
                    break
                i, dev = item
                t0 = time.perf_counter()
                try:
                    _dispatch_seam()
                    out = plan.fn(dev, tc_fn())
                except Exception as exc:
                    preempted = faults.classify(exc) == faults.PREEMPTED
                    guard.handle(exc, feeder, i, restart=restart)
                    if preempted and restart is not None:
                        acc = None  # device accumulator presumed lost
                    continue
                acc = out if acc is None else jax.tree_util.tree_map(
                    jnp.add, acc, out
                )
                stats["dispatch_s"] += time.perf_counter() - t0
        finally:
            feeder.close()
        try:
            return jax.device_get(acc)
        except Exception as exc:
            # an async fault re-surfacing at the gather: the failed
            # block is unknowable, so the whole pass re-runs
            kind = faults.classify(exc)
            if not faults.is_retryable(kind):
                raise
            pass_guard.retry.admit(_RoundFault([], 0, exc, kind), 0)
            stats["retries"] = pass_guard.retry.total
            if kind == faults.PREEMPTED and restart is not None:
                restart()


# ---------------------------------------------------------------------------
# host-side batched L-BFGS (mirrors models/solvers._lbfgs_body)
# ---------------------------------------------------------------------------

def _two_loop_batch(g, S, Y, rho, k):
    T, m, P = S.shape
    rT = np.arange(T)
    n_corr = np.minimum(k, m)
    q = g.astype(np.float32).copy()
    alphas = np.zeros((T, m), np.float32)
    for i in range(m):
        idx = (k - 1 - i) % m
        valid = i < n_corr
        alpha = rho[rT, idx] * np.einsum("tp,tp->t", S[rT, idx], q)
        alpha = np.where(valid, alpha, np.float32(0.0)).astype(np.float32)
        q = q - alpha[:, None] * Y[rT, idx]
        alphas[rT, idx] = alpha
    last = (k - 1) % m
    sy = np.einsum("tp,tp->t", S[rT, last], Y[rT, last])
    yy = np.einsum("tp,tp->t", Y[rT, last], Y[rT, last])
    gamma = np.where(k > 0, sy / (yy + _EPS), np.float32(1.0))
    r = gamma.astype(np.float32)[:, None] * q
    for i in range(m):
        idx = (k - n_corr + i) % m
        valid = i < n_corr
        beta = rho[rT, idx] * np.einsum("tp,tp->t", Y[rT, idx], r)
        upd = S[rT, idx] * (alphas[rT, idx] - beta.astype(np.float32))[:, None]
        r = r + np.where(valid[:, None], upd, np.float32(0.0))
    return -r


def lbfgs_stream(eval_fg, eval_f, w0, tol, max_iter, history=10,
                 max_ls=20, pass_hook=None):
    """Batched L-BFGS whose objective evaluations are STREAMED passes.

    ``eval_fg(W (T,P) f32) -> (f (T,), g (T,P))`` and ``eval_f`` are
    full-objective evaluations (block-accumulated data term + the
    regulariser); the state machine here mirrors
    ``models/solvers._lbfgs_body`` lane for lane — same Armijo
    constants, direction-normalisation rule, curvature filter, and
    ``done`` semantics (converged at ``tol`` | line-search stall |
    iteration cap) — in host numpy f32 over the task batch, with frozen
    lanes masked out of every update. Returns ``(W, n_iter, done)``
    indexed by the ORIGINAL lane order.

    ``pass_hook(pass_idx, lane_ids, w, it, done) -> killed lane ids``
    is the rung seam, called after every iteration (= one block-pass
    group of the dataset): ``lane_ids`` maps the batch's current rows
    to original lanes. Lanes the hook kills are recorded at their
    kill-time iterate and COMPACTED out of every solver array, so
    subsequent streamed evaluations dispatch a smaller task batch.
    Lanes are independent in the batched recursion (every reduction is
    per-lane, the lockstep line search halves per-lane step sizes), so
    survivor trajectories are bitwise identical under compaction.
    """
    w = np.ascontiguousarray(w0, dtype=np.float32)
    T, P = w.shape
    m = int(history)
    tol = np.asarray(tol, dtype=np.float32).reshape(T)
    lanes = np.arange(T)
    out_w = w.copy()
    out_it = np.zeros(T, np.int64)
    out_done = np.zeros(T, bool)
    f, g = eval_fg(w)
    f = np.asarray(f, np.float32).reshape(T)
    g = np.asarray(g, np.float32).reshape(T, P)
    S = np.zeros((T, m, P), np.float32)
    Y = np.zeros((T, m, P), np.float32)
    rho = np.zeros((T, m), np.float32)
    k = np.zeros(T, np.int64)
    it = np.zeros(T, np.int64)
    done = (np.max(np.abs(g), axis=1) <= tol) | (max_iter <= 0)
    rT = np.arange(T)
    pass_idx = 0
    while done.size and not done.all():
        # a pass_hook kill compacts every lane array — the iteration's
        # temporaries must track the LIVE batch size, not the original
        T = lanes.size
        d = _two_loop_batch(g, S, Y, rho, k)
        gd0 = np.einsum("tp,tp->t", g, d)
        descent = gd0 < 0
        d = np.where(descent[:, None], d, -g)
        raw_scale = (~descent) | (k == 0)
        norm = np.linalg.norm(d, axis=1).astype(np.float32) + _EPS
        d = np.where(raw_scale[:, None], d / norm[:, None], d)
        gd = np.einsum("tp,tp->t", g, d).astype(np.float32)
        # Armijo backtracking, lockstep over lanes (each full-objective
        # probe is one streamed pass over every block)
        t_step = np.ones(T, np.float32)
        f_new = np.asarray(
            eval_f((w + t_step[:, None] * d).astype(np.float32)),
            np.float32,
        ).reshape(T)
        ls_it = np.zeros(T, np.int64)
        armijo = f_new <= f + np.float32(1e-4) * t_step * gd
        active = (~armijo) & (ls_it < max_ls) & (~done)
        while active.any():
            t_step = np.where(active, t_step * np.float32(0.5), t_step)
            f_try = np.asarray(
                eval_f((w + t_step[:, None] * d).astype(np.float32)),
                np.float32,
            ).reshape(T)
            f_new = np.where(active, f_try, f_new)
            ls_it = ls_it + active
            armijo = f_new <= f + np.float32(1e-4) * t_step * gd
            active = (~armijo) & (ls_it < max_ls) & (~done)
        ok = f_new <= f + np.float32(1e-4) * t_step * gd
        w_new = (w + t_step[:, None] * d).astype(np.float32)
        f2, g_new = eval_fg(w_new)
        f2 = np.asarray(f2, np.float32).reshape(T)
        g_new = np.asarray(g_new, np.float32).reshape(T, P)
        s = w_new - w
        yv = g_new - g
        sy = np.einsum("tp,tp->t", s, yv)
        store = (sy > 1e-10) & (~done)
        idx = k % m
        S[rT[store], idx[store]] = s[store]
        Y[rT[store], idx[store]] = yv[store]
        rho[rT[store], idx[store]] = (
            np.float32(1.0) / (sy[store].astype(np.float32) + _EPS)
        )
        live = ~done
        converged = np.max(np.abs(g_new), axis=1) <= tol
        stalled = ~ok
        w = np.where(live[:, None], w_new, w)
        f = np.where(live, f2, f)
        g = np.where(live[:, None], g_new, g)
        k = k + (store & live)
        it = it + live
        done = np.where(
            live, converged | stalled | (it >= max_iter), done
        )
        pass_idx += 1
        if pass_hook is not None:
            killed = np.asarray(
                pass_hook(pass_idx, lanes, w, it, done), dtype=np.int64
            ).reshape(-1)
            if killed.size:
                drop = np.isin(lanes, killed)
                out_w[lanes[drop]] = w[drop]
                out_it[lanes[drop]] = it[drop]
                out_done[lanes[drop]] = done[drop]
                keep = ~drop
                w, f, g = w[keep], f[keep], g[keep]
                S, Y, rho = S[keep], Y[keep], rho[keep]
                k, it, done, tol = k[keep], it[keep], done[keep], tol[keep]
                lanes = lanes[keep]
                rT = np.arange(lanes.size)
    out_w[lanes] = w
    out_it[lanes] = it
    out_done[lanes] = done
    return out_w, out_it, out_done


# ---------------------------------------------------------------------------
# family kernel builders
# ---------------------------------------------------------------------------

def _stream_key(est_cls, static, meta, part, extra=()):
    from .linear import _meta_signature
    from ..parallel import structural_key

    return structural_key(
        "stream", est_cls, part, static, _meta_signature(meta), *extra
    )


def _default_derive(block, task):
    """Single-fit / no-fold derive: labels and weights ride the block;
    fold-masked variants are composed by the CV/OvR call sites."""
    return block["X"], block["y"], block["sw"], task["hyper"]


def _lbfgs_stream_kernels(est_cls, meta, static, derive):
    """The three jit programs of one streamed L-BFGS family config:
    per-block data (f, g), per-block data f (line-search probes), and
    the one-shot regulariser (f, g) evaluated on a zero block."""
    from .linear import maybe_exact_matmuls

    problem = est_cls._build_fit_problem(meta, static)

    def fg_kernel(block, tc):
        Xb, yb, swb, hyper = derive(block, tc["task"])
        parts = problem(Xb, yb, swb, hyper, parts=True)
        f, g = jax.value_and_grad(parts[3])(tc["W"])
        return {"f": f, "g": g}

    def f_kernel(block, tc):
        Xb, yb, swb, hyper = derive(block, tc["task"])
        parts = problem(Xb, yb, swb, hyper, parts=True)
        return {"f": parts[3](tc["W"])}

    def reg_kernel(block, tc):
        Xb, yb, swb, hyper = derive(block, tc["task"])
        parts = problem(Xb, yb, swb, hyper, parts=True)
        f, g = jax.value_and_grad(parts[4])(tc["W"])
        return {"f": f, "g": g}

    wrap = lambda fn: maybe_exact_matmuls(est_cls, fn)
    return wrap(fg_kernel), wrap(f_kernel), wrap(reg_kernel)


def _host_unpack(est_cls, meta, static, dataset):
    """The family's ``unpack`` closure, recovered host-side from a
    one-row zero problem (unpack only reshapes; it never touches X)."""
    from ..sparse import PackedX

    problem = est_cls._build_fit_problem(meta, static)
    if dataset.x_format == "packed":
        Xz = PackedX(np.zeros((1, 1), np.int32), np.zeros((1, 1), np.float32),
                     meta["n_features"])
    else:
        Xz = np.zeros((1, meta["n_features"]), np.float32)
    hyper = {
        name: np.float32(1.0)
        for name in getattr(est_cls, "_hyper_names", ())
    }
    out = problem(Xz, np.zeros(1, np.int32), np.zeros(1, np.float32), hyper)
    return out[2]


# ---------------------------------------------------------------------------
# the drivers
# ---------------------------------------------------------------------------

def _check_data_axis_geometry(backend, dataset):
    """2D (task x data) meshes row-shard every placed block: the padded
    block height must split evenly over the 'data' axis, or GSPMD's
    device_put rejects the block with an opaque divisibility error —
    fail here with the remedy instead."""
    dsize = getattr(backend, "data_axis_size", 1)
    if dsize > 1 and dataset.block_rows % dsize:
        raise ValueError(
            f"block_rows={dataset.block_rows} does not divide over the "
            f"mesh 'data' axis (data_axis_size={dsize}); rebuild the "
            "ChunkedDataset with a block_rows that is a multiple of "
            "the data axis size"
        )


def _zero_block_dev(plan, dataset, row_arrays, extra_scalars=(), rows=1):
    """A zero block of ``rows`` rows (all weight-0 padding), placed
    once — the regulariser kernels' dummy shared tree. ``rows`` is the
    mesh's data-axis size on 2D backends: even a dummy block must be
    row-shardable onto the 'data' axis."""
    from ..sparse import PackedX

    rows = max(1, int(rows))
    if dataset.x_format == "packed":
        X = PackedX(np.zeros((rows, dataset.packed_m), np.int32),
                    np.zeros((rows, dataset.packed_m), np.float32),
                    dataset.n_features)
    else:
        X = np.zeros((rows, dataset.n_features), np.float32)
    tree = {"X": X}
    for name, arr in row_arrays.items():
        arr = np.asarray(arr)
        tree[name] = np.full(
            (rows,) + arr.shape[1:], _pad_rows_for(name), arr.dtype
        )
    for name in extra_scalars:
        tree[name] = np.int32(0)
    return plan.put_block(tree)


def _fit_lbfgs_stream(backend, est_cls, meta, static, dataset, row_arrays,
                      task_args, derive, stats, sync, key_extra=(),
                      w_init=None, rung_hook=None):
    st = dict(static)
    max_iter, history = int(st["max_iter"]), int(st["history"])
    width = est_cls._flat_w_width(meta, static)
    T = _n_tasks(task_args)
    fg_kernel, f_kernel, reg_kernel = _lbfgs_stream_kernels(
        est_cls, meta, static, derive
    )
    example = _example_block(dataset, row_arrays)
    plan_fg = backend.prepare_streamed(
        fg_kernel, example,
        cache_key=_stream_key(est_cls, static, meta, "lbfgs_fg", key_extra),
    )
    plan_f = backend.prepare_streamed(
        f_kernel, example,
        cache_key=_stream_key(est_cls, static, meta, "lbfgs_f", key_extra),
    )
    plan_reg = backend.prepare_streamed(
        reg_kernel, example,
        cache_key=_stream_key(est_cls, static, meta, "lbfgs_reg", key_extra),
    )
    read = _make_block_read(dataset, row_arrays, pad=True)
    n_blocks = dataset.n_blocks

    # the solver runs over the LIVE lane subset; a rung kill shrinks
    # sel["idx"] and re-places the task tree, so subsequent passes
    # stream the same bytes through fewer programs. Slot padding (mesh
    # task sharding needs a divisible axis) happens at the dispatch
    # seam on the live subset only.
    sel = {"idx": np.arange(T)}
    state = {}
    zero_dev = {}

    def place_tasks(fresh=True):
        # ``fresh`` recomputes the padded width from the current slot
        # count; an elastic restart keeps the previous width instead
        # (the largest-divisor re-layout guarantees it still divides)
        # so mid-pass device state stays size-consistent.
        L = sel["idx"].size
        if fresh or "Lp" not in state:
            slots = max(1, int(plan_fg.n_task_slots))
            state["Lp"] = -(-L // slots) * slots
        state["tasks"] = plan_fg.put_task(
            _pad_tree_to(_take_tree(task_args, sel["idx"]), L, state["Lp"])
        )
        zero_dev["b"] = _zero_block_dev(
            plan_reg, dataset, row_arrays,
            rows=getattr(backend, "data_axis_size", 1),
        )

    place_tasks()

    def restart():
        # preemption: device state presumed lost — shrink an elastic
        # mesh to the survivors (rebuilding the three plans), then
        # re-place the task tree and the regulariser's zero block
        _elastic_replans(backend, (plan_fg, plan_f, plan_reg))
        place_tasks(fresh=False)
        faults.record("shared_replacements")

    def _pad_W(W):
        L, Lp = W.shape[0], state["Lp"]
        if Lp == L:
            return W
        return np.concatenate([W, np.repeat(W[-1:], Lp - L, axis=0)])

    def eval_fg(W):
        W = np.ascontiguousarray(W, np.float32)
        L = W.shape[0]
        tc = lambda: {"task": state["tasks"],
                      "W": plan_fg.put_task(_pad_W(W))}
        acc = _streamed_sum(plan_fg, read, n_blocks, tc, stats, sync,
                            restart=restart)
        reg = jax.device_get(plan_reg.fn(zero_dev["b"], tc()))
        return (np.asarray(acc["f"])[:L] + np.asarray(reg["f"])[:L],
                np.asarray(acc["g"])[:L] + np.asarray(reg["g"])[:L])

    def eval_f(W):
        W = np.ascontiguousarray(W, np.float32)
        L = W.shape[0]
        tc = lambda: {"task": state["tasks"],
                      "W": plan_f.put_task(_pad_W(W))}
        acc = _streamed_sum(plan_f, read, n_blocks, tc, stats, sync,
                            restart=restart)
        reg = jax.device_get(plan_reg.fn(zero_dev["b"], tc()))
        return np.asarray(acc["f"])[:L] + np.asarray(reg["f"])[:L]

    w0 = np.zeros((T, width), np.float32)
    if w_init is not None:
        # warm start: lanes begin at the caller's (T, width) seeds
        w0[:] = np.asarray(w_init, np.float32).reshape(T, width)
    tol = np.asarray(task_args["hyper"]["tol"], np.float32).reshape(T)
    unpack = _host_unpack(est_cls, meta, static, dataset)

    pass_hook = None
    if rung_hook is not None:
        def pass_hook(pass_idx, lane_ids, w_rows, it_rows, done_rows):
            live = ~done_rows
            live_ids = lane_ids[live]
            if live_ids.size == 0:
                return np.empty(0, np.int64)
            w_live, it_live = w_rows[live], it_rows[live]

            def make_params():
                return _stack_params([
                    unpack(w_live[i], int(it_live[i]))
                    for i in range(live_ids.size)
                ])

            killed = np.asarray(
                rung_hook(pass_idx, live_ids, make_params), np.int64
            ).reshape(-1)
            if killed.size:
                sel["idx"] = lane_ids[~np.isin(lane_ids, killed)]
                if sel["idx"].size:  # all-killed: no further dispatches
                    place_tasks()
                stats["retired_rung"] = (
                    stats.get("retired_rung", 0) + int(killed.size)
                )
                # counterfactual upper bound: a killed lane would have
                # paid at most (max_iter - pass_idx) more solver passes
                stats["passes_saved"] = (
                    stats.get("passes_saved", 0)
                    + int(killed.size) * max(0, max_iter - pass_idx)
                )
            return killed

    W, n_iter, _done = lbfgs_stream(
        eval_fg, eval_f, w0, tol, max_iter, history=history,
        max_ls=20, pass_hook=pass_hook,
    )
    if rung_hook is not None and sel["idx"].size < T:
        # bytes are shared across lanes per pass: the race ending at
        # max(n_iter) instead of the iteration cap saves whole-dataset
        # passes (an upper-bound estimate, documented as such)
        stats["streamed_bytes_saved"] = (
            stats.get("streamed_bytes_saved", 0)
            + int(dataset.nbytes_estimate)
            * max(0, max_iter - int(n_iter.max(initial=0)))
        )
    params = [unpack(W[t], int(n_iter[t])) for t in range(T)]
    return _stack_params(params)


def _fit_gram_stream(backend, est_cls, meta, static, dataset, row_arrays,
                     task_args, derive, stats, sync, key_extra=(),
                     w_init=None, rung_hook=None):
    """Block-accumulated normal equations for the ridge family: stream
    ``(XᵀSX, XᵀST)`` partials, finish with one solve per task.
    ``w_init`` is accepted and ignored — a direct solve has no
    iterate to seed; ``rung_hook`` likewise — a one-pass direct solve
    has no pass boundaries for a rung to act between (an adaptive
    search over a gram family stays exhaustive and warns)."""
    from .linear import (
        _apply_class_weight, _linear_op, maybe_exact_matmuls,
    )

    st = dict(static)
    fit_intercept = st["fit_intercept"]
    d = meta["n_features"]
    k = meta.get("n_classes")
    class_weight = st.get("class_weight")
    cw_arr = meta.get("cw_arr")

    def gram_kernel(block, tc):
        Xb, yb, swb, hyper = derive(block, tc["task"])
        op = _linear_op(Xb, fit_intercept, meta)
        if k is not None:
            swb = _apply_class_weight(swb, yb, k, class_weight, cw_arr)
            if k <= 2:
                T_t = jnp.where(yb == (k - 1), 1.0, -1.0).astype(
                    op.dtype)[:, None]
            else:
                T_t = jnp.where(
                    jax.nn.one_hot(yb, k) > 0, 1.0, -1.0
                ).astype(op.dtype)
        else:
            T_t = yb.astype(jnp.float32).reshape(yb.shape[0], -1)
        G, b = op.weighted_gram_rhs(swb, T_t)
        return {"G": G, "b": b}

    def finish_kernel(_z, tc):
        G, b = tc["G"], tc["b"]
        alpha = tc["task"]["hyper"].get("alpha", jnp.float32(0.0))
        p = G.shape[0]
        reg = jnp.concatenate([jnp.full((d,), alpha), jnp.zeros(p - d)])
        G = G + jnp.diag(reg)
        G = G + 1e-8 * jnp.eye(p, dtype=G.dtype)
        return {"W": jax.scipy.linalg.solve(G, b, assume_a="pos")}

    gram_kernel = maybe_exact_matmuls(est_cls, gram_kernel)
    finish_kernel = maybe_exact_matmuls(est_cls, finish_kernel)
    example = _example_block(dataset, row_arrays)
    plan = backend.prepare_streamed(
        gram_kernel, example,
        cache_key=_stream_key(est_cls, static, meta, "gram", key_extra),
    )
    plan_fin = backend.prepare_streamed(
        finish_kernel, None,
        cache_key=_stream_key(est_cls, static, meta, "gram_fin", key_extra),
    )
    T = _n_tasks(task_args)
    task_args, _Tp = _slot_pad_tree(task_args, T, plan.n_task_slots)
    read = _make_block_read(dataset, row_arrays, pad=True)
    state = {"tasks": plan.put_task(task_args)}

    def restart():
        _elastic_replans(backend, (plan, plan_fin))
        state["tasks"] = plan.put_task(task_args)
        faults.record("shared_replacements")

    acc = _streamed_sum(
        plan, read, dataset.n_blocks,
        lambda: {"task": state["tasks"]}, stats, sync, restart=restart,
    )
    fin = jax.device_get(plan_fin.fn(
        plan_fin.put_block({"z": np.zeros(1, np.float32)}),
        {
            "task": plan_fin.put_task(task_args),
            "G": jnp.asarray(acc["G"]),
            "b": jnp.asarray(acc["b"]),
        },
    ))
    W = np.asarray(fin["W"])  # (T, p, k_out)
    out = []
    for t in range(T):
        Wt = W[t]
        if k is not None and k <= 2:
            Wt = Wt[:, 0]
        elif k is None and meta.get("y_ndim", 1) == 1:
            Wt = Wt[:, 0]
        out.append({"W": Wt})
    return _stack_params(out)


def _fit_sgd_stream(backend, est_cls, meta, static, dataset, row_arrays,
                    task_args, derive, stats, sync, key_extra=(),
                    w_init=None, rung_hook=None):
    """Epochs as block streams: visit blocks in order, advance the
    mini-batch carry through the resident scan's exact update
    (``solvers.sgd_batch_scan``), apply the epoch-end early-stopping
    bookkeeping host-side in f32 — mirroring ``solvers._sgd_epoch_body``
    value for value, so an aligned, unshuffled streamed fit is bitwise
    identical to the resident kernel. ``rung_hook`` (see
    :func:`stream_fit_tasks`) is consulted at every epoch boundary —
    the SGD rendition of the rung-at-block-pass contract: killed lanes
    record their kill-time carry and compact out of the device batch."""
    from .linear import maybe_exact_matmuls
    from .solvers import sgd_batch_scan

    st = dict(static)
    max_iter = int(st["max_iter"])
    batch_size = int(st["batch_size"])
    n_iter_no_change = int(st["n_iter_no_change"])
    shuffle = bool(st.get("shuffle", True))
    penalty = st["penalty"]
    width = est_cls._flat_w_width(meta, static)
    problem = est_cls._build_fit_problem(meta, static)
    R = dataset.block_rows
    n = dataset.n_rows
    if R % batch_size and dataset.n_blocks > 1:
        raise ValueError(
            f"streamed SGD needs block_rows ({R}) divisible by "
            f"batch_size ({batch_size}) so mini-batches never straddle "
            "blocks; rebuild the ChunkedDataset with an aligned "
            "block_rows"
        )

    def block_kernel(block, tc):
        Xb, yb, swb, hyper = derive(block, tc["task"])
        pb = problem(Xb, yb, swb, hyper)
        rows = yb.shape[0]
        n_b = rows // batch_size
        if shuffle:
            bkey = jax.random.fold_in(
                jax.random.fold_in(pb["key"], block["epoch"]),
                block["bid"],
            )
            perm = jax.random.permutation(bkey, rows)
        else:
            perm = jnp.arange(rows)
        batches = perm.reshape(n_b, batch_size)
        carry = tc["carry"]
        w, pstate, step, acc = sgd_batch_scan(
            pb["grad_fn"], pb["lr_fn"], pb["post_step"], pb["loss_fn"],
            True,
            (carry["w"], carry["pstate"], carry["step"], carry["acc"]),
            batches,
        )
        return {"w": w, "pstate": pstate, "step": step, "acc": acc}

    block_kernel = maybe_exact_matmuls(est_cls, block_kernel)
    example = _example_block(dataset, row_arrays, ("epoch", "bid"))
    plan = backend.prepare_streamed(
        block_kernel, example,
        cache_key=_stream_key(est_cls, static, meta, "sgd", key_extra),
    )

    # ---- epoch plan: full blocks + a virtual tail whose trailing
    # batch wraps to the dataset head (the streamed rendition of the
    # resident scan's arange(padded) % n wrap) -----------------------
    base_read = _make_block_read(dataset, row_arrays, pad=False)
    full_blocks = n // R
    rem = n - full_blocks * R
    if rem == 0 and n % batch_size:
        # every block is full but the epoch still needs a wrap batch
        # (possible only for a single-block dataset — aligned
        # block_rows is enforced above for more): demote the last full
        # block to the virtual tail so the wrap rows get appended
        full_blocks -= 1
        rem = R
    tail_rows = 0
    wrap_tree = None
    if rem:
        tail_rows = int(math.ceil(rem / batch_size) * batch_size)
        wrap = tail_rows - rem
        if wrap:
            # wrap rows are the resident scan's arange(padded) % n
            # tail: global rows (n + j) % n = j % n for j < wrap. When
            # wrap <= n they are simply the dataset head; a dataset
            # SMALLER than one batch cycles (possible only when the
            # whole dataset is the tail block, so block 0 holds every
            # row the cycle can touch)
            head = base_read(0)
            avail = rem if full_blocks == 0 else R
            idx = np.arange(wrap) % min(avail, n)
            wrap_tree = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[idx], head
            )

    def read_epoch_block(e):
        def read(i):
            if rem and i == full_blocks:
                tree = base_read(full_blocks)
                if wrap_tree is not None:
                    tree = jax.tree_util.tree_map(
                        lambda a, w_: np.concatenate(
                            [np.asarray(a), w_]
                        ),
                        tree, wrap_tree,
                    )
            else:
                tree = base_read(i)
            tree["epoch"] = np.int32(e)
            tree["bid"] = np.int32(i)
            return tree

        return read

    n_stream_blocks = full_blocks + (1 if rem else 0)
    n_batches_total = np.float32(-(-n // batch_size))

    T = _n_tasks(task_args)
    tol = np.asarray(task_args["hyper"]["tol"], np.float32).reshape(T)
    if penalty in ("l1", "elasticnet"):
        pstate0 = (np.zeros(T, np.float32),
                   np.zeros((T, width), np.float32))
    else:
        pstate0 = ()
    w0 = np.zeros((T, width), np.float32)
    if w_init is not None:
        # warm start: epochs begin at the caller's (T, width) seeds
        w0[:] = np.asarray(w_init, np.float32).reshape(T, width)

    # the device batch covers the LIVE lane subset (sel["idx"]); host
    # bookkeeping stays full-size, indexed through the lane map. A
    # rung kill records the killed lanes' carry into w_out and
    # compacts the device batch — later epochs stream the same blocks
    # through fewer programs.
    sel = {"idx": np.arange(T)}
    dev = {}

    def place_tasks(fresh=True):
        # ``fresh`` recomputes the padded width from the current slot
        # count; an elastic restart keeps the previous width instead
        # (the largest-divisor re-layout guarantees it still divides)
        # so the epoch-start carry snapshot stays size-consistent.
        L = sel["idx"].size
        if fresh or "Lp" not in sel:
            slots = max(1, int(plan.n_task_slots))
            sel["Lp"] = -(-L // slots) * slots
        dev["tasks"] = plan.put_task(
            _pad_tree_to(_take_tree(task_args, sel["idx"]), L, sel["Lp"])
        )

    def place_carry(host_tree_L):
        return plan.put_task(
            _pad_tree_to(host_tree_L, sel["idx"].size, sel["Lp"])
        )

    place_tasks()
    carry = place_carry({
        "w": w0, "pstate": pstate0,
        "step": np.zeros(T, np.int32),
        "acc": np.zeros(T, np.float32),
    })
    # host-side early-stopping state (mirrors _sgd_epoch_body's tail)
    best = np.full(T, np.inf, np.float32)
    bad = np.zeros(T, np.int64)
    n_done = np.zeros(T, np.int64)
    done = np.zeros(T, bool)
    w_out = w0.copy()
    unpack = _sgd_host_unpack(est_cls, meta, static)

    guard = _BlockRetry(stats)
    epoch_guard = _BlockRetry(stats)
    e = 0
    epochs_run = 0
    while e < max_iter:
        lane = sel["idx"]
        L = lane.size
        carry_start = carry
        # host snapshot of the epoch-start carry: the preemption
        # restart below (and the epoch-retry path) re-place from it
        # (device buffers are presumed lost with the worker)
        host_start = jax.device_get(carry_start)
        carry = _reset_acc(carry)
        read = read_epoch_block(e)
        # late-bound placement: an elastic restart rebuilds `plan` in
        # place mid-epoch and later blocks must land on the new mesh
        feeder = BlockFeeder(read, n_stream_blocks,
                             lambda t: plan.put_block(t),
                             sync=sync, stats=stats)
        try:
            while True:
                item = feeder.next()
                if item is None:
                    break
                i, dv = item
                t0 = time.perf_counter()
                try:
                    _dispatch_seam()
                    carry = plan.fn(dv, {"task": dev["tasks"],
                                         "carry": carry})
                except Exception as exc:
                    def restart():
                        # preemption loses device state: shrink an
                        # elastic mesh to the survivors, re-place the
                        # tasks and rewind to the epoch-start carry
                        nonlocal carry
                        _elastic_replans(backend, (plan,))
                        place_tasks(fresh=False)
                        carry = _reset_acc(plan.put_task(host_start))
                        faults.record("shared_replacements")

                    # a TRANSIENT fault at block i leaves the input
                    # carry (the post-(i-1) state) valid: the feeder
                    # re-opens the reader at block i and the identical
                    # dispatch re-runs bitwise
                    guard.handle(exc, feeder, i, restart=restart)
                    continue
                stats["dispatch_s"] += time.perf_counter() - t0
        finally:
            feeder.close()
        try:
            acc = np.asarray(
                jax.device_get(carry["acc"]), np.float32
            )[:L]
        except Exception as exc:
            # async fault surfacing only at the blocking gather: the
            # whole epoch's carry chain is suspect — re-run the epoch
            # from its start snapshot (deterministic, so bitwise)
            kind = faults.classify(exc)
            if not faults.is_retryable(kind):
                raise
            epoch_guard.retry.admit(_RoundFault([], 0, exc, kind), e)
            stats["retries"] = epoch_guard.retry.total
            if kind == faults.PREEMPTED:
                _elastic_replans(backend, (plan,))
                place_tasks(fresh=False)
                faults.record("shared_replacements")
            carry = plan.put_task(host_start)
            continue
        epochs_run = e + 1
        # ---- epoch-end bookkeeping: the resident epoch body's tail,
        # value for value, in host f32 (same IEEE ops => bitwise) -----
        keep = done[lane]
        loss = (acc / n_batches_total).astype(np.float32)
        improved = loss < (best[lane] - tol[lane]).astype(np.float32)
        bad_new = np.where(improved, 0, bad[lane] + 1)
        newly_stopped = bad_new >= n_iter_no_change
        best_new = np.minimum(best[lane], loss).astype(np.float32)
        if keep.any():
            # frozen lanes keep their epoch-start carry, exactly like
            # the resident scan's pick()
            kmask = _pad_tree_to(keep, L, sel["Lp"])
            carry = _pick_carry(plan.put_task(kmask), carry_start, carry)
        best[lane] = np.where(keep, best[lane], best_new)
        bad[lane] = np.where(keep, bad[lane], bad_new)
        n_done[lane] = np.where(keep, n_done[lane], n_done[lane] + 1)
        done[lane] = keep | newly_stopped | ((e + 1) >= max_iter)
        # ---- rung hook at the epoch (block-pass) boundary ----------
        if rung_hook is not None:
            live = ~done[lane]
            live_ids = lane[live]
            if live_ids.size:
                def make_params():
                    w_h = np.asarray(
                        jax.device_get(carry["w"]), np.float32
                    )[:L][live]
                    return _stack_params([
                        unpack(w_h[i], int(n_done[live_ids[i]]))
                        for i in range(live_ids.size)
                    ])

                killed = np.asarray(
                    rung_hook(e + 1, live_ids, make_params), np.int64
                ).reshape(-1)
                if killed.size:
                    host_c = jax.tree_util.tree_map(
                        lambda a: np.asarray(a)[:L],
                        jax.device_get(carry),
                    )
                    drop = np.isin(lane, killed)
                    w_out[lane[drop]] = np.asarray(
                        host_c["w"], np.float32
                    )[drop]
                    done[killed] = True
                    sel["idx"] = lane[~drop]
                    if sel["idx"].size:
                        place_tasks()
                        carry = place_carry(_take_tree(
                            host_c, np.flatnonzero(~drop)
                        ))
                    stats["retired_rung"] = (
                        stats.get("retired_rung", 0) + int(killed.size)
                    )
                    stats["passes_saved"] = (
                        stats.get("passes_saved", 0)
                        + int(killed.size) * max(0, max_iter - (e + 1))
                    )
        lane_now = sel["idx"]
        if lane_now.size == 0 or done[lane_now].all():
            break
        e += 1

    lane = sel["idx"]
    if lane.size:
        w_out[lane] = np.asarray(
            jax.device_get(carry["w"]), np.float32
        )[: lane.size]
    if rung_hook is not None and lane.size < T:
        stats["streamed_bytes_saved"] = (
            stats.get("streamed_bytes_saved", 0)
            + int(dataset.nbytes_estimate)
            * max(0, max_iter - epochs_run)
        )
    # unpack per task (host reshape, identical to the family unpack)
    params = [unpack(w_out[t], int(n_done[t])) for t in range(T)]
    return _stack_params(params)


def _sgd_host_unpack(est_cls, meta, static):
    st = dict(static)
    p = meta["n_features"] + (1 if st["fit_intercept"] else 0)
    k = meta.get("n_classes", 2)
    n_out = 1 if k <= 2 else k

    def unpack(Wf, n_epochs):
        W = np.asarray(Wf).reshape(p, n_out)
        if n_out == 1:
            W = W[:, 0]
        return {"W": W, "n_iter": n_epochs}

    return unpack


def _reset_acc(carry):
    return {**carry, "acc": jnp.zeros_like(carry["acc"])}


def _pick_carry(keep_dev, old, new):
    """``where(keep, old, new)`` leaf-wise with the (T,) mask broadcast
    to each leaf's rank — the device rendition of the resident epoch
    body's freeze pick."""

    def pick(a, b):
        m = jnp.reshape(keep_dev, keep_dev.shape + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)

    return jax.tree_util.tree_map(pick, old, new)


def _fit_gbdt_stream(backend, est_cls, meta, static, dataset, row_arrays,
                     task_args, derive, stats, sync, key_extra=(),
                     w_init=None, rung_hook=None):
    """Boosting rounds as binned-cache streams.

    Round structure (all passes read the uint8 binned cache, never raw
    features): per tree level, a histogram pass routes every block's
    rows to their current node with the partial heap placed in the task
    tree, scatters ``newton_channels(grad, hess, w)`` into per-(class,
    feature, node, bin) histograms, and accumulates across blocks
    (:func:`_streamed_sum`; on a mesh the row-sharded scatter psums
    over 'data'). A device chooser then scores the gathered histograms
    with the resident kernel's OWN :func:`~.tree.histogram_node_scores`
    / :func:`~.tree.pick_level_splits` — parity by shared code. The
    host assembles the round's heap (leaf values from the level totals:
    an unsplit node's Newton step is ``−G/(H+λ)`` of the samples
    resting there; last-level children split their parent's totals via
    the recorded left-cumulative stats, exactly the resident kernel's
    final-assignment scatter re-expressed). One update pass advances
    the margin carry ``F`` and accumulates the early-stop monitor.

    ``F`` lives in two host memmaps sized (T, n, Kt): the update pass
    reads ``F_cur`` and writes ``F_nxt``, committing not-yet-done lanes
    back to ``F_cur`` only after the pass completes — so a transient
    fault replays block i bitwise (its input rows are untouched) and a
    preemption rewinds the whole pass idempotently. ``w_init`` is
    accepted and ignored (an ensemble has no flat iterate to seed).

    ``rung_hook`` fires at every round boundary with finalize-shaped
    params for the live lanes (unrun rounds hold all-zero trees, so the
    decision kernel's full static-T scan is exact mid-race); killed
    lanes compact out of the task batch and their F rows go cold."""
    from .gbdt import _P_EPS, _build_boost_parts, _stacked_tree_walk
    from .tree import (
        _NEG, histogram_node_scores, n_tree_nodes, newton_channels,
        pick_level_splits,
    )

    st = dict(static)
    parts = _build_boost_parts(meta, static)
    grads, loss_vals = parts["grads"], parts["loss_vals"]
    Kt, D, K = parts["Kt"], parts["D"], parts["K"]
    classification = parts["classification"]
    max_iter = parts["T"]
    N = n_tree_nodes(D)
    es = bool(st["_early_stopping"])
    patience = int(st["n_iter_no_change"])
    msl = int(st["min_samples_leaf"])
    B = int(st["max_bins"])
    d = int(st["_n_features"])

    cache = meta.get("binned_cache")
    if cache is None:
        cache = dataset.with_binned_cache(
            edges=np.asarray(meta["edges"], np.float32), max_bins=B
        )
    edges_np = np.asarray(meta["edges"], np.float32)
    stats["binned_bytes_cached"] = (
        stats.get("binned_bytes_cached", 0)
        + (0 if cache.hit else int(cache.nbytes))
    )

    n = dataset.n_rows
    R = dataset.block_rows
    n_blocks = dataset.n_blocks
    T = _n_tasks(task_args)
    lr_h = np.asarray(task_args["hyper"]["learning_rate"],
                      np.float32).reshape(T)
    lam_h = np.asarray(task_args["hyper"]["l2_regularization"],
                       np.float32).reshape(T)
    tol_h = np.asarray(task_args["hyper"]["tol"], np.float32).reshape(T)

    # ---- kernels ----------------------------------------------------

    def _routed_nodes(Xb, f_a, t_a, s_a, level):
        # replay `level` levels of heap routing — tree_predict_kernel's
        # walk against the partial heap (non-split nodes carry
        # is_split=False and hold their samples, like the resident
        # level loop's split_s gate)
        node = jnp.zeros(Xb.shape[0], jnp.int32)
        for _ in range(level):
            f = jnp.clip(f_a[node], 0, d - 1)
            t = t_a[node]
            s = s_a[node]
            b = jnp.take_along_axis(Xb, f[:, None], axis=1)[:, 0]
            child = 2 * node + 1 + (b > t).astype(jnp.int32)
            node = jnp.where(s, child, node)
        return node

    def make_hist_kernel(level):
        nl = 2 ** level
        start = nl - 1

        def kernel(block, tc):
            Xb_u, yb, fit_w, _hyper = derive(block, tc["task"])
            Xb = Xb_u.astype(jnp.int32)
            F_lane = block["F"][tc["task"]["lane"]]
            g, h = grads(F_lane, yb)
            tr = tc["task"]["tree"]

            def one_class(gk, hk, f_a, t_a, s_a):
                node = _routed_nodes(Xb, f_a, t_a, s_a, level)
                at = (node >= start) & (node < start + nl)
                rel = jnp.clip(node - start, 0, nl - 1)
                Ych = newton_channels(gk, hk, fit_w) * \
                    at[:, None].astype(jnp.float32)
                seg = (jnp.arange(d)[None, :] * nl + rel[:, None]) * B + Xb
                hist = jnp.zeros((d * nl * B, 3), jnp.float32).at[
                    seg.reshape(-1)
                ].add(jnp.repeat(Ych, d, axis=0))
                return hist.reshape(d, nl, B, 3)

            if Kt == 1:
                hist = one_class(
                    g, h, tr["feat"][0], tr["thr"][0], tr["split"][0]
                )[None]
            else:
                hist = jax.vmap(one_class, in_axes=(1, 1, 0, 0, 0))(
                    g, h, tr["feat"], tr["thr"], tr["split"]
                )
            return {"hist": hist}  # (Kt, d, nl, B, 3)

        return kernel

    def make_choose_kernel(level):
        nl = 2 ** level

        def kernel(_z, tc):
            lam = tc["task"]["hyper"]["l2_regularization"]

            def one_class(hk):
                cum = jnp.cumsum(hk, axis=2)
                gain, cnt_l, cnt_r, tot = histogram_node_scores(
                    cum, lam, newton=True
                )
                node_cnt = tot[0, :, -1]
                ok = (cnt_l >= msl) & (cnt_r >= msl)
                gain = jnp.where(ok, gain, _NEG)
                # w_root=1 is exact here: the boost kernel fixes
                # min_impurity_decrease=0, so the decrease gate reduces
                # to best_gain > 1e-12 for ANY positive root mass —
                # the same decision the resident kernel takes
                best_f, best_t, _bg, do_split = pick_level_splits(
                    gain, node_cnt, min_samples_split=2,
                    w_root=jnp.float32(1.0), min_impurity_decrease=0.0,
                )
                lstat = cum[best_f, jnp.arange(nl), best_t]
                return {"feat": jnp.where(do_split, best_f, -1),
                        "thr": best_t, "split": do_split,
                        "tot": tot[0], "lstat": lstat}

            hist = tc["hist"]
            if Kt == 1:
                return jax.tree_util.tree_map(
                    lambda a: a[None], one_class(hist[0])
                )
            return jax.vmap(one_class)(hist)

        return kernel

    def update_kernel(block, tc):
        Xb_u, yb, fit_w, _hyper = derive(block, tc["task"])
        Xb = Xb_u.astype(jnp.int32)
        F_lane = block["F"][tc["task"]["lane"]]
        tr = tc["task"]["tree"]
        F_new = F_lane + _stacked_tree_walk(
            Xb, tr["feat"], tr["thr"], tr["split"], tr["leaf"], D
        )
        lv = loss_vals(F_new, yb)
        return {"F": F_new, "mon_num": jnp.sum(fit_w * lv),
                "mon_den": jnp.sum(fit_w)}

    def init_kernel(block, tc):
        # per-lane baseline sufficient statistics (fold-masked weights
        # differ per lane): class-weighted counts / weighted y sum
        _Xb, yb, fit_w, _hyper = derive(block, tc["task"])
        if classification:
            s = jax.nn.one_hot(yb, max(K, 2), dtype=jnp.float32).T @ fit_w
        else:
            s = jnp.sum(fit_w * yb.astype(jnp.float32))[None]
        return {"s": s, "w": jnp.sum(fit_w)[None]}

    # ---- plans ------------------------------------------------------
    example = {"X": np.zeros((R, d), np.uint8)}
    for name, arr in row_arrays.items():
        arr = np.asarray(arr)
        example[name] = np.zeros((R,) + arr.shape[1:], arr.dtype)
    example["F"] = np.zeros((1, R, Kt), np.float32)

    def skey(part):
        return _stream_key(est_cls, static, meta, part, key_extra)

    plans_h = [
        backend.prepare_streamed(make_hist_kernel(l), example,
                                 cache_key=skey(f"gbdt_h{l}"))
        for l in range(D)
    ]
    plans_c = [
        backend.prepare_streamed(make_choose_kernel(l), None,
                                 cache_key=skey(f"gbdt_c{l}"))
        for l in range(D)
    ]
    plan_u = backend.prepare_streamed(update_kernel, example,
                                      cache_key=skey("gbdt_u"))
    plan_b = backend.prepare_streamed(init_kernel, example,
                                      cache_key=skey("gbdt_b"))
    all_plans = plans_h + plans_c + [plan_u, plan_b]

    # ---- host state -------------------------------------------------
    sel = {"idx": np.arange(T)}
    state = {}
    fdir = tempfile.mkdtemp(prefix="skdist_gbdt_F_")
    F_cur = np.lib.format.open_memmap(
        os.path.join(fdir, "F_cur.npy"), mode="w+",
        dtype=np.float32, shape=(T, n, Kt),
    )
    F_nxt = np.lib.format.open_memmap(
        os.path.join(fdir, "F_nxt.npy"), mode="w+",
        dtype=np.float32, shape=(T, n, Kt),
    )

    def read(i):
        s0 = i * R
        e0 = min(s0 + R, n)
        m = e0 - s0
        xb = np.zeros((R, d), np.uint8)
        xb[:m] = cache.xb[s0:e0]
        tree = {"X": xb}
        for name, arr in row_arrays.items():
            sl = np.asarray(arr[s0:e0])
            if m < R:
                sl = np.concatenate([
                    sl,
                    np.full((R - m,) + sl.shape[1:],
                            _pad_rows_for(name), sl.dtype),
                ])
            tree[name] = sl
        idx = sel["idx"]
        L = idx.size
        Fp = np.zeros((state["Lp"], R, Kt), np.float32)
        Fp[:L, :m] = F_cur[idx, s0:e0]
        if state["Lp"] > L:
            Fp[L:] = Fp[L - 1]  # duplicate-last, like _pad_tree_to
        tree["F"] = Fp
        return tree

    def place_current():
        state["tasks"] = plan_u.put_task(state["task_host"])

    def place_round(tree_host):
        L = sel["idx"].size
        if "Lp" not in state:
            slots = max(1, int(plan_u.n_task_slots))
            state["Lp"] = -(-L // slots) * slots
        th = dict(_take_tree(task_args, sel["idx"]))
        th["lane"] = np.arange(L, dtype=np.int32)
        th["tree"] = tree_host
        state["task_host"] = _pad_tree_to(th, L, state["Lp"])
        place_current()

    def restart_pass():
        _elastic_replans(backend, all_plans)
        place_current()
        faults.record("shared_replacements")

    def tc():
        return {"task": state["tasks"]}

    try:
        # ---- baseline pass ------------------------------------------
        zero_heap = {
            "feat": np.full((T, Kt, N), -1, np.int32),
            "thr": np.zeros((T, Kt, N), np.int32),
            "split": np.zeros((T, Kt, N), bool),
        }
        place_round(zero_heap)
        acc0 = _streamed_sum(plan_b, read, n_blocks, tc, stats, sync,
                             restart=restart_pass)
        stats["binned_bytes_streamed"] += int(cache.nbytes)
        sS = np.asarray(acc0["s"], np.float32)[:T]
        wS = np.maximum(np.asarray(acc0["w"], np.float32)[:T, 0],
                        np.float32(1e-12))
        if not classification:
            base_all = (sS[:, :1] / wS[:, None]).astype(np.float32)
        elif K <= 2:
            p = np.clip(sS[:, K - 1] / wS, _P_EPS,
                        np.float32(1.0) - np.float32(_P_EPS))
            base_all = np.log(
                p / (np.float32(1.0) - p)
            ).astype(np.float32)[:, None]
        else:
            pri = sS / wS[:, None]
            base_all = np.log(
                np.clip(pri, _P_EPS, None)
            ).astype(np.float32)
        for t in range(T):
            F_cur[t] = base_all[t][None, :]

        # ---- outputs + early-stop mirrors (resident carry, host f32)
        feat_all = np.full((T, max_iter, Kt, N), -1, np.int32)
        thr_all = np.zeros((T, max_iter, Kt, N), np.int32)
        split_all = np.zeros((T, max_iter, Kt, N), bool)
        leaf_all = np.zeros((T, max_iter, Kt, N), np.float32)
        best = np.full(T, np.inf, np.float32)
        bad = np.zeros(T, np.int64)
        n_rounds = np.zeros(T, np.int64)
        done = np.zeros(T, bool)

        guard = _BlockRetry(stats)
        r = 0
        rounds_run = 0
        while r < max_iter:
            lane = sel["idx"]
            L = lane.size
            # ---- grow one tree per live lane, level by level --------
            featH = np.full((L, Kt, N), -1, np.int32)
            thrH = np.zeros((L, Kt, N), np.int32)
            splitH = np.zeros((L, Kt, N), bool)
            tots, lstats = [], []
            for l in range(D):
                place_round({"feat": featH, "thr": thrH,
                             "split": splitH})
                acc = _streamed_sum(plans_h[l], read, n_blocks, tc,
                                    stats, sync, restart=restart_pass)
                stats["binned_bytes_streamed"] += int(cache.nbytes)
                fin = jax.device_get(plans_c[l].fn(
                    plans_c[l].put_block({"z": np.zeros(1, np.float32)}),
                    {"task": state["tasks"],
                     "hist": jnp.asarray(acc["hist"])},
                ))
                nl = 2 ** l
                i0 = nl - 1
                featH[:, :, i0:i0 + nl] = np.asarray(
                    fin["feat"], np.int32)[:L]
                thrH[:, :, i0:i0 + nl] = np.asarray(
                    fin["thr"], np.int32)[:L]
                splitH[:, :, i0:i0 + nl] = np.asarray(
                    fin["split"], bool)[:L]
                tots.append(np.asarray(fin["tot"], np.float32)[:L])
                lstats.append(np.asarray(fin["lstat"], np.float32)[:L])
            # ---- leaves from the level totals (host f32) ------------
            leafH = np.zeros((L, Kt, N), np.float32)
            lam_l = lam_h[lane][:, None, None]
            for l in range(D):
                nl = 2 ** l
                i0 = nl - 1
                tot = tots[l]
                val = -tot[..., 0] / np.maximum(
                    tot[..., 1] + lam_l, np.float32(1e-12)
                )
                leafH[:, :, i0:i0 + nl] = np.where(
                    splitH[:, :, i0:i0 + nl], np.float32(0.0),
                    val.astype(np.float32),
                )
            nl = 2 ** (D - 1)
            i0 = nl - 1
            left = lstats[D - 1]
            right = tots[D - 1] - left
            spD = splitH[:, :, i0:i0 + nl]
            lv = -left[..., 0] / np.maximum(
                left[..., 1] + lam_l, np.float32(1e-12))
            rv = -right[..., 0] / np.maximum(
                right[..., 1] + lam_l, np.float32(1e-12))
            iD = 2 ** D - 1
            leafH[:, :, iD:iD + 2 * nl:2] = np.where(
                spD, lv.astype(np.float32), np.float32(0.0))
            leafH[:, :, iD + 1:iD + 2 * nl:2] = np.where(
                spD, rv.astype(np.float32), np.float32(0.0))
            leafH *= lr_h[lane][:, None, None]

            # ---- update pass: advance F, accumulate the monitor -----
            place_round({"feat": featH, "thr": thrH, "split": splitH,
                         "leaf": leafH})
            num = np.zeros(L, np.float32)
            den = np.zeros(L, np.float32)
            feeder = BlockFeeder(read, n_blocks,
                                 lambda t_: plan_u.put_block(t_),
                                 sync=sync, stats=stats)
            try:
                while True:
                    item = feeder.next()
                    if item is None:
                        break
                    i, dv = item
                    t0 = time.perf_counter()
                    try:
                        _dispatch_seam()
                        out = jax.device_get(plan_u.fn(dv, tc()))
                    except Exception as exc:
                        def restart_u():
                            restart_pass()
                            num[:] = np.float32(0.0)
                            den[:] = np.float32(0.0)

                        # transient: F_cur rows are untouched until the
                        # pass commits, so block i replays bitwise
                        guard.handle(exc, feeder, i, restart=restart_u)
                        continue
                    stats["dispatch_s"] += time.perf_counter() - t0
                    s0 = i * R
                    e0 = min(s0 + R, n)
                    F_nxt[lane, s0:e0] = np.asarray(
                        out["F"], np.float32)[:L, :e0 - s0]
                    num += np.asarray(out["mon_num"], np.float32)[:L]
                    den += np.asarray(out["mon_den"], np.float32)[:L]
            finally:
                feeder.close()
            stats["binned_bytes_streamed"] += int(cache.nbytes)
            rounds_run = r + 1

            # ---- commit F for lanes not yet frozen (block-wise: the
            # carries are memmaps and must not materialise whole) -----
            keep = done[lane]
            upd = lane[~keep]
            for i in range(n_blocks):
                s0 = i * R
                e0 = min(s0 + R, n)
                F_cur[upd, s0:e0] = F_nxt[upd, s0:e0]

            # ---- round-end bookkeeping: the resident round body's
            # tail, value for value, in host f32 ----------------------
            mon = (num / np.maximum(den, np.float32(1e-12))).astype(
                np.float32)
            improved = mon < (best[lane] - tol_h[lane]).astype(np.float32)
            bad_new = np.where(improved, 0, bad[lane] + 1)
            done_new = np.full(L, (r + 1) >= max_iter)
            if es:
                done_new = done_new | (bad_new >= patience)
            act = ~keep
            ai = lane[act]
            feat_all[ai, r] = featH[act]
            thr_all[ai, r] = thrH[act]
            split_all[ai, r] = splitH[act]
            leaf_all[ai, r] = leafH[act]
            best[lane] = np.where(keep, best[lane],
                                  np.minimum(best[lane], mon))
            bad[lane] = np.where(keep, bad[lane], bad_new)
            n_rounds[lane] = np.where(keep, n_rounds[lane], r + 1)
            done[lane] = keep | done_new

            # ---- rung hook at the round (block-pass) boundary -------
            if rung_hook is not None:
                live_ids = lane[~done[lane]]
                if live_ids.size:
                    def make_params():
                        idx = live_ids
                        return {
                            "feat": feat_all[idx], "thr": thr_all[idx],
                            "is_split": split_all[idx],
                            "leaf": leaf_all[idx],
                            "baseline": base_all[idx],
                            "n_iter": n_rounds[idx].astype(np.int32),
                            "edges": np.repeat(
                                edges_np[None], idx.size, axis=0),
                        }

                    killed = np.asarray(
                        rung_hook(r + 1, live_ids, make_params), np.int64
                    ).reshape(-1)
                    if killed.size:
                        # out arrays already hold kill-time params
                        done[killed] = True
                        sel["idx"] = lane[~np.isin(lane, killed)]
                        state.pop("Lp", None)
                        stats["retired_rung"] = (
                            stats.get("retired_rung", 0)
                            + int(killed.size)
                        )
                        stats["passes_saved"] = (
                            stats.get("passes_saved", 0)
                            + int(killed.size) * (D + 1)
                            * max(0, max_iter - (r + 1))
                        )
            lane_now = sel["idx"]
            if lane_now.size == 0 or done[lane_now].all():
                break
            r += 1
    finally:
        del F_cur, F_nxt
        shutil.rmtree(fdir, ignore_errors=True)

    if rung_hook is not None and sel["idx"].size < T:
        # upper bound: every remaining round was D hist passes + one
        # update pass over the cache
        stats["streamed_bytes_saved"] = (
            stats.get("streamed_bytes_saved", 0)
            + int(cache.nbytes) * (D + 1) * max(0, max_iter - rounds_run)
        )
    return {
        "feat": feat_all, "thr": thr_all, "is_split": split_all,
        "leaf": leaf_all, "baseline": base_all,
        "n_iter": n_rounds.astype(np.int32),
        "edges": np.repeat(edges_np[None], T, axis=0),
    }


def _stack_params(params_list):
    """List of per-task param dicts -> dict of stacked (T, ...) arrays
    (n_iter-style scalars stack to (T,))."""
    out = {}
    for key in params_list[0]:
        out[key] = np.stack([
            np.asarray(p[key]) for p in params_list
        ])
    return out


def stream_fit_tasks(backend, est_cls, meta, static, dataset, row_arrays,
                     task_args, derive=None, sync=None, stats=None,
                     key_extra=(), w_init=None, rung_hook=None):
    """Fit a batch of tasks over a ChunkedDataset with the family's
    streamed driver. ``row_arrays`` maps per-row vector names (``y``
    encoded labels, ``sw`` weights, ``fold`` CV fold ids, ...) to
    ``(n_rows,)`` host arrays sliced per block; ``derive(block, task)
    -> (Xb, yb, swb, hyper)`` adapts a placed block + one task lane to
    the family's fit problem (fold masking, OvR binarisation).
    ``w_init`` (``(T, width)`` flat-layout seeds) warm-starts the
    iterative drivers' solver carries (the gram driver's direct solve
    ignores it).

    ``rung_hook(pass_idx, live_ids, make_params) -> killed lane ids``
    is the streamed ASHA seam: the iterative drivers call it at every
    block-pass boundary (an L-BFGS iteration, an SGD epoch) with the
    not-yet-converged lane ids and a zero-arg ``make_params`` closure
    materialising those lanes' CURRENT fitted params (for a
    sufficient-statistics scoring pass over the already-resident
    blocks, :func:`stream_scores`). Lanes it returns are recorded at
    their kill-time iterate and compacted out of the device batch —
    retired lanes stop paying device FLOPs and their task-tree slots
    compact away. The gram driver has no pass boundaries and ignores
    the hook. Returns a dict of stacked ``(T, ...)`` fitted params
    (killed lanes carry their kill-time params)."""
    kind = getattr(est_cls, "_stream_fit_kind", None)
    if kind is None:
        raise TypeError(
            f"{est_cls.__name__} has no out-of-core fit path "
            "(_stream_fit_kind is unset); materialise the dataset or "
            "use a family with a streamed driver (the linear families "
            "or DistHistGradientBoosting*)"
        )
    _check_data_axis_geometry(backend, dataset)
    sync = _resolve_sync(backend, sync)
    if stats is None:
        stats = _stream_stats(backend, sync)
    derive = derive or _default_derive
    driver = {
        "lbfgs": _fit_lbfgs_stream,
        "sgd": _fit_sgd_stream,
        "gram": _fit_gram_stream,
        "gbdt": _fit_gbdt_stream,
    }[kind]
    stats["tasks"] = stats.get("tasks", 0) + _n_tasks(task_args)
    out = driver(backend, est_cls, meta, static, dataset, row_arrays,
                 task_args, derive, stats, sync, key_extra=key_extra,
                 w_init=w_init, rung_hook=rung_hook)
    # delta-publication (publish_round_stats): safe on a shared/
    # re-published dict — the CV driver hands this same dict to
    # stream_scores, whose own publish folds only the scoring pass
    obs_metrics.publish_round_stats(stats)
    return out


# ---------------------------------------------------------------------------
# streamed scoring
# ---------------------------------------------------------------------------

def stream_scores(backend, est_cls, meta, static, dataset, row_arrays,
                  task_args, params, scorer_specs, weight_fns,
                  sync=None, stats=None, key_extra=()):
    """Evaluate fitted per-task params over the dataset with
    decomposable device scorers (``metrics.STREAM_SCORERS``): one
    streamed pass accumulates each metric's sufficient statistics per
    task, host ``combine`` finishes. ``weight_fns`` maps an output
    prefix ('test', 'train') to ``fn(block, task) -> (rows,) weights``.
    Returns ``{f"{prefix}_{name}": (T,) float64}``."""
    from .linear import maybe_exact_matmuls
    from ..metrics import STREAM_SCORERS

    _check_data_axis_geometry(backend, dataset)
    sync = _resolve_sync(backend, sync)
    if stats is None:
        # continue the fit's dict when one exists (the CV driver's
        # contract) — else a fresh schema-complete dict, NOT a bare {}
        # (the feed/dispatch accounting below += into required keys)
        stats = (backend.last_round_stats
                 or obs_metrics.new_round_stats("streamed_scores"))
    decision_kernel = maybe_exact_matmuls(
        est_cls, est_cls._build_decision_kernel(meta, static)
    )
    needs_proba = any(
        STREAM_SCORERS[m][2] == "proba" for _n, m in scorer_specs
    )
    proba_kernel = (
        maybe_exact_matmuls(est_cls, est_cls._build_proba_kernel(meta, static))
        if needs_proba else None
    )

    def score_kernel(block, tc):
        Xb = block["X"]
        yb = block["y"]
        dec = decision_kernel(tc["params"], Xb)
        outputs = {"decision": dec, "predict": dec}
        if proba_kernel is not None:
            outputs["proba"] = proba_kernel(tc["params"], Xb)
        out = {}
        for prefix, wfn in weight_fns.items():
            wv = wfn(block, tc["task"])
            for name, metric in scorer_specs:
                kernel, _combine, kind = STREAM_SCORERS[metric]
                out[f"{prefix}_{name}"] = kernel(
                    yb, outputs[kind], wv, meta
                )
        return out

    score_kernel = maybe_exact_matmuls(est_cls, score_kernel)
    example = _example_block(dataset, row_arrays)
    plan = backend.prepare_streamed(
        score_kernel, example,
        cache_key=_stream_key(est_cls, static, meta, "score",
                              tuple(sorted(
                                  (p, n, m) for p in weight_fns
                                  for n, m in scorer_specs
                              )) + tuple(key_extra)),
    )
    T = _n_tasks(task_args)
    task_args, _Tp = _slot_pad_tree(task_args, T, plan.n_task_slots)
    params, _Tp = _slot_pad_tree(params, T, plan.n_task_slots)
    read = _make_block_read(dataset, row_arrays, pad=True)
    state = {"tc": {"task": plan.put_task(task_args),
                    "params": plan.put_task(params)}}

    def restart():
        # preemption mid-scoring: same contract as the fit passes —
        # elastic shrink + re-place the task/param trees
        _elastic_replans(backend, (plan,))
        state["tc"] = {"task": plan.put_task(task_args),
                       "params": plan.put_task(params)}
        faults.record("shared_replacements")

    acc = _streamed_sum(plan, read, dataset.n_blocks,
                        lambda: state["tc"], stats, sync,
                        restart=restart)
    obs_metrics.publish_round_stats(stats)  # delta of the scoring pass
    out = {}
    for key, parts in acc.items():
        prefix, name = key.split("_", 1)
        metric = dict(scorer_specs)[name]
        _kernel, combine, _kind = STREAM_SCORERS[metric]
        out[key] = np.asarray([
            combine(jax.tree_util.tree_map(
                lambda a, t=t: np.asarray(a)[t], parts
            ), meta)
            for t in range(T)
        ], dtype=np.float64)
    return out


# ---------------------------------------------------------------------------
# single-estimator entry point
# ---------------------------------------------------------------------------

def stream_fit_estimator(est, dataset, y=None, sample_weight=None,
                         backend=None, coef_init=None,
                         intercept_init=None):
    """``estimator.fit(ChunkedDataset)``: the out-of-core fit of one
    estimator — labels/weights from the dataset (or passed explicitly),
    blocks streamed through the double-buffered pipeline, fitted state
    set exactly like a resident fit. ``coef_init``/``intercept_init``
    (sklearn shapes) warm-start the iterative families' solver
    carries — the catalog refresh loop's streamed warm-refit seam."""
    from ..parallel import resolve_backend
    from .linear import _freeze, hyper_float

    if getattr(est, "engine", None) == "host":
        raise ValueError(
            "engine='host' cannot fit a ChunkedDataset: the f64 BLAS "
            "host engine needs X resident. Use engine='auto'/'xla' for "
            "the streamed XLA path."
        )
    backend = resolve_backend(backend)
    if y is None:
        y = dataset.load_y()
    if sample_weight is None:
        sample_weight = dataset.load_sw()
    y_enc, sw, meta = est._prep_stream_fit(dataset, y, sample_weight)
    static_cfg = est._static_config(meta)
    static = _freeze(static_cfg)
    est_cls = type(est)
    task_args = {"hyper": {
        name: np.asarray([hyper_float(getattr(est, name))], np.float32)
        for name in est_cls._hyper_names
    }}
    if "alpha" not in task_args["hyper"] and \
            getattr(est, "alpha", None) is not None and \
            est._stream_fit_kind == "gram":
        task_args["hyper"]["alpha"] = np.asarray(
            [hyper_float(est.alpha)], np.float32
        )
    row_arrays = {"y": y_enc, "sw": sw}
    w_init = None
    if coef_init is not None or intercept_init is not None:
        k = meta.get("n_classes", 2)
        w_init = est._warm_w0_flat(
            meta["n_features"], 1 if k <= 2 else k,
            coef_init, intercept_init,
        )[None]
    params = stream_fit_tasks(
        backend, est_cls, meta, static, dataset, row_arrays, task_args,
        w_init=w_init,
    )
    est._set_fitted(
        {k: np.asarray(v)[0] for k, v in params.items()}, meta
    )
    return est
