"""
Histogram-based decision trees in XLA (placeholder — implemented with
forests in the ensemble milestone).
"""

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin

__all__ = [
    "DecisionTreeClassifier",
    "DecisionTreeRegressor",
    "ExtraTreeClassifier",
    "ExtraTreeRegressor",
]


class _TreeStub(BaseEstimator):
    def fit(self, X, y, sample_weight=None):
        raise NotImplementedError("tree kernels land in the ensemble milestone")


class DecisionTreeClassifier(_TreeStub, ClassifierMixin):
    pass


class DecisionTreeRegressor(_TreeStub, RegressorMixin):
    pass


class ExtraTreeClassifier(DecisionTreeClassifier):
    pass


class ExtraTreeRegressor(DecisionTreeRegressor):
    pass
