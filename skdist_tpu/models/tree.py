"""
Histogram-based decision trees as pure XLA kernels.

The reference delegated tree building to sklearn's Cython
``tree.fit`` (``/root/reference/skdist/distribute/ensemble.py:106-108``)
— exact, sorted, data-dependent-shape split search that XLA cannot
express. These kernels use the accelerator-native alternative
(LightGBM / XGBoost-hist style):

1. features are quantile-binned once (``ops/binning.py``);
2. the tree grows breadth-first to a *static* ``max_depth``; the node
   assignment of every sample is a vector updated level by level;
3. per-level split search is a histogram reduction — scatter-add of
   per-sample weighted channel vectors into (node, feature, bin,
   channel) — followed by cumulative sums over bins; Gini (or variance)
   gain is evaluated for every (feature, bin) in parallel;
4. row subsets (bootstrap, CV folds, OvR masks) are 0/1 sample weights;
   a dedicated count channel tracks *unweighted* occupancy so
   min_samples rules behave like sklearn's.

Everything is fixed-shape, so a whole forest vmaps over the tree axis
into one compiled program (``models/forest.py``), and the distributed
ensembles shard that axis over the TPU mesh (``distribute/ensemble.py``)
— where the reference shipped one Spark task per tree
(``ensemble.py:304-322``).

Divergences from sklearn (inherent to the histogram approach; mirrored
by every GPU/TPU tree library): split thresholds are bin boundaries,
``max_depth`` is mandatory-static (default 8), min_samples rules are
evaluated on histogram counts.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin
from ..ops.binning import apply_bins, quantile_bin_edges
from .linear import (
    _freeze,
    as_dense_f32,
    encode_labels,
    get_kernel,
    host_stage,
    prepare_sample_weight,
)

__all__ = [
    "DecisionTreeClassifier",
    "DecisionTreeRegressor",
    "ExtraTreeClassifier",
    "ExtraTreeRegressor",
    "build_tree_kernel",
    "histogram_node_scores",
    "newton_channels",
    "pick_level_splits",
    "tree_predict_kernel",
]

_NEG = -1e30


def n_tree_nodes(max_depth):
    return 2 ** (max_depth + 1) - 1


def histogram_node_scores(hist_cum, lam=None, *, newton=False,
                          classification=False, K=1):
    """hist_cum (d, nl, B, C) cumulative over bins → per-(f, node,
    threshold) gain proxies + counts. Returns (gain, cnt_l, cnt_r,
    node_totals) with node_totals (d, nl, C). ``lam`` is the
    traced Newton λ (only consumed by the newton objective).

    Module-level so out-of-core drivers (``models/streaming.py``) can
    score histograms gathered across blocks with the exact ops the
    resident kernel traces — resident-vs-streamed parity is by shared
    code, not by reimplementation."""
    tot = hist_cum[:, :, -1, :]  # (d, nl, C)
    L = hist_cum  # left stats for threshold t = bins <= t
    R = tot[:, :, None, :] - L
    cnt_l = L[..., -1]
    cnt_r = R[..., -1]
    if newton:
        g_l, h_l = L[..., 0], L[..., 1]
        g_r, h_r = R[..., 0], R[..., 1]
        g_t, h_t = tot[..., 0], tot[..., 1]
        gain = (
            g_l**2 / jnp.maximum(h_l + lam, 1e-12)
            + g_r**2 / jnp.maximum(h_r + lam, 1e-12)
            - (g_t**2 / jnp.maximum(h_t + lam, 1e-12))[:, :, None]
        )
    elif classification:
        wl = jnp.sum(L[..., :K], axis=-1)
        wr = jnp.sum(R[..., :K], axis=-1)
        sl = jnp.sum(L[..., :K] ** 2, axis=-1) / jnp.maximum(wl, 1e-12)
        sr = jnp.sum(R[..., :K] ** 2, axis=-1) / jnp.maximum(wr, 1e-12)
        st = jnp.sum(tot[..., :K] ** 2, axis=-1) / jnp.maximum(
            jnp.sum(tot[..., :K], axis=-1), 1e-12
        )
        # (Σ wt·gini improvements): decrease·W_root = sl + sr - st
        gain = sl + sr - st[:, :, None]
    else:
        w_l, wy_l, wy2_l = L[..., 0], L[..., 1], L[..., 2]
        w_r, wy_r, wy2_r = R[..., 0], R[..., 1], R[..., 2]
        sse_l = wy2_l - wy_l**2 / jnp.maximum(w_l, 1e-12)
        sse_r = wy2_r - wy_r**2 / jnp.maximum(w_r, 1e-12)
        wt, wy_t, wy2_t = tot[..., 0], tot[..., 1], tot[..., 2]
        sse_t = wy2_t - wy_t**2 / jnp.maximum(wt, 1e-12)
        gain = sse_t[:, :, None] - (sse_l + sse_r)
    return gain, cnt_l, cnt_r, tot


def pick_level_splits(gain, node_cnt, *, min_samples_split, w_root,
                      min_impurity_decrease):
    """Pick the best (feature, threshold) per node from masked gains.

    ``gain`` (d, nl, B) with invalid cells already at ``_NEG``;
    ``node_cnt`` (nl,) unweighted occupancy. Returns
    (best_f, best_t, best_gain, do_split). Shared by the resident
    level loop and the streamed host chooser."""
    nl = gain.shape[1]
    B = gain.shape[2]
    gain_fb = jnp.transpose(gain, (1, 0, 2)).reshape(nl, -1)
    best_flat = jnp.argmax(gain_fb, axis=1)
    best_gain = jnp.take_along_axis(
        gain_fb, best_flat[:, None], axis=1
    )[:, 0]
    best_f = (best_flat // B).astype(jnp.int32)
    best_t = (best_flat % B).astype(jnp.int32)
    decrease = best_gain / jnp.maximum(w_root, 1e-12)
    do_split = (
        (best_gain > 1e-12)
        & (decrease >= min_impurity_decrease)
        & (node_cnt >= min_samples_split)
    )
    return best_f, best_t, best_gain, do_split


def resolve_hist_config(n_features, n_bins, hist_mode="auto",
                        hist_block=None, allow_native=True,
                        fractional_weights=False):
    """Concrete ``(hist_mode, hist_block)`` for this platform + shape.

    ``"auto"`` takes the MEASURED per-platform winner from
    ``models/hist_calib.json`` (written by ``build_tools/
    tpu_tree_sweep.py``) with a width guard — matmul/pallas contract a
    (n, d·B)-sized one-hot, so they degrade to scatter above the
    calibrated ``d·B`` bound. Platforms with no calibration fall back
    to the shape heuristic (matmul on accelerators at tabular widths).
    Resolution happens OUTSIDE the kernel caches, so recalibrating
    mid-process (the sweep does) takes effect on the next fit.

    ``allow_native=False`` is set by callers that need an IN-PROGRAM
    (XLA) algorithm — distributed fits sharding the tree axis over the
    mesh, and ``build_tree_kernel`` itself. A calibrated/explicit
    ``"native"`` (the host C engine, ``models/native_forest.py``) then
    re-resolves to the platform shape heuristic instead — NOT blindly
    to scatter, which would be the wrong engine on a TPU whose host
    happens to win the local sweep.

    ``fractional_weights=True`` declares that the fit's effective
    per-sample weights are NOT integers (class_weight, non-integral
    sample_weight): a calibrated ``matmul_sib`` pick under ``"auto"``
    then degrades to plain ``matmul`` — sibling subtraction is exact
    only when histogram entries are exact in f32 (integer counts), and
    fractional weights can round and flip near-tie splits. An EXPLICIT
    ``hist_mode='matmul_sib'`` is honoured as-is (the user owns the
    trade).
    """
    from .hist_calib import DEFAULT_MAX_MATMUL_DB, get_calibration

    d, B = n_features, n_bins
    explicit_native = hist_mode == "native"
    resolved = hist_mode == "auto"  # every non-explicit path descends
    calib = get_calibration(jax.default_backend()) or {}
    if hist_mode == "auto":
        hist_mode = calib["mode"] if calib else "_heuristic"
    if hist_mode == "native" and not allow_native:
        if explicit_native:
            # an explicit opt-in must not silently downgrade to the
            # engine the user opted out of — only 'auto' re-resolves
            raise ValueError(
                "hist_mode='native' is the host (LocalBackend) tree "
                "engine and cannot run inside an XLA program "
                "(distributed mesh fits, batched search kernels); use "
                "'auto' or an XLA mode ('scatter'/'matmul'/'pallas')"
            )
        # prefer the sweep's MEASURED best XLA engine (and its
        # measured block size) over the shape heuristic
        xla = calib.get("xla_mode")
        if xla in ("scatter", "matmul", "matmul_sib", "pallas"):
            hist_mode = xla
            if hist_block is None:
                hist_block = (
                    calib.get("xla_hist_block") or calib.get("hist_block")
                )
        else:
            hist_mode = "_heuristic"
    if hist_mode == "_heuristic":
        hist_mode = "matmul" if jax.default_backend() != "cpu" else "scatter"
    if resolved and hist_mode == "matmul_sib" and fractional_weights:
        # calibrated auto default only for integer-effective-weight
        # fits (ADVICE r05 #4): the sweep measures speed, not the
        # f32 rounding of fractional-weight sibling subtraction
        hist_mode = "matmul"
    # single width guard for every RESOLVED path (an explicit
    # matmul/pallas request is honoured as-is): the one-hot contraction
    # is (n, d·B)-sized, degrade to scatter above the calibrated bound
    if (resolved and hist_mode in ("matmul", "matmul_sib", "pallas")
            and d * B > calib.get("max_matmul_db", DEFAULT_MAX_MATMUL_DB)):
        hist_mode = "scatter"
    # the compiled pallas histogram needs n_bins >= 8 (TPU sublane
    # tiling): a RESOLVED pick degrades to the shape heuristic — only
    # an explicit hist_mode='pallas' request raises (build_tree_kernel)
    if resolved and hist_mode == "pallas" and B < 8:
        hist_mode = (
            "matmul" if jax.default_backend() != "cpu" else "scatter"
        )
    if hist_block is None:
        hist_block = calib.get("hist_block") or 8
    return hist_mode, int(hist_block)


def build_tree_kernel(n_features, n_bins, channels, max_depth, max_features,
                      min_samples_split, min_samples_leaf,
                      min_impurity_decrease, extra, classification,
                      hist_block=None, hist_mode="auto",
                      fractional_weights=False, newton=False):
    """Returns ``kernel(Xb, Ych, key) -> tree`` growing one tree.

    - ``Xb`` (n, d) int32 binned features
    - ``Ych`` (n, C) f32 per-sample channels:
      classification C = K + 1: [w·onehot(y) ..., count(w>0)]
      regression C = 4: [w, w·y, w·y², count(w>0)]
      newton C = 3: [s·g, s·h, count(s>0)] (gradient/hessian channels)
    - ``key``: PRNG key (feature subsampling / random thresholds)

    ``newton=True`` is the gradient-boosting objective (XGBoost /
    LightGBM / sklearn-HistGradientBoosting lineage): the channels are
    per-sample gradient/hessian sums of the boosting loss, split gain
    is ``G_L²/(H_L+λ) + G_R²/(H_R+λ) − G_T²/(H_T+λ)`` and the leaf
    value is the Newton step ``−G/(H+λ)``. λ (``l2_regularization``)
    arrives as the kernel's optional 4th argument — a *traced* scalar,
    so a CV grid over λ vmaps into one compiled program. The histogram
    machinery (scatter / matmul / matmul_sib / pallas engines) is
    channel-agnostic and runs unchanged; only the gain and the leaf
    read differently. ``classification`` must be False (the tree
    regresses the Newton step whatever the boosting loss is).

    ``tree`` = {feat (N,), thr (N,), is_split (N,), leaf (N, K_out)}
    with N = 2^(D+1)-1 heap-indexed nodes (children of i: 2i+1, 2i+2).

    ``hist_mode`` selects the per-level histogram algorithm:

    - ``"scatter"``: blocked scatter-add (one segment-add per feature
      block). Best on CPU, where scatters are cheap and FLOPs are not.
    - ``"matmul"``: one-hot matmul — ``hist = Xoh.T @ (nodeoh ⊗ Ych)``
      where ``Xoh`` (n, d·B) is the LEVEL-INVARIANT one-hot of the
      binned features (hoisted out of the level loop) and the right
      factor (n, nl·C) re-weights each sample's channels by its node.
      This trades redundant FLOPs for MXU throughput: the whole
      histogram becomes one large dense matmul per level, the shape TPU
      hardware is built for, displacing the scatter that round-1
      measured as the forest bottleneck (42s vs sklearn's 7.4s per 100
      trees on 20k×54). f32 accumulation, exact 0/1 one-hots.
    - ``"pallas"``: the same contraction as ``"matmul"`` executed by a
      Pallas TPU kernel (``ops/pallas_hist.py``) that builds both
      one-hot factors on the fly in VMEM — nothing of size (n, d·B) or
      (n, nl·C) is ever materialised in HBM. Off-TPU it runs through
      the Pallas interpreter (correct but slow; tests only). The
      compiled path assumes ``n_bins >= 8`` (TPU sublane tiling).
    - ``"matmul_sib"``: the matmul engine with sibling subtraction
      (LightGBM's classic halving): below the root, only LEFT-child
      histograms are computed by matmul — each right child is its
      parent's (previous level's) histogram minus the left sibling,
      zeroed for children of non-split parents. Halves the dominant
      per-level contraction FLOPs. Exactness: with integer effective
      weights (the default — bootstrap counts × unit sample_weight)
      every histogram entry below 2^24 is exact in f32, so the
      subtraction is bitwise-identical to direct summation (measured:
      identical trees on tie-heavy fuzz data); fractional
      class/sample weights can round and flip near-tie splits (the
      same flip class as the xla-vs-native near-ties, NOTES round-4
      fuzz). The sweep may therefore calibrate it as the ``"auto"``
      default, but ``resolve_hist_config`` honours that calibration
      ONLY for integer-effective-weight fits — callers declaring
      ``fractional_weights=True`` (class_weight / non-integral
      sample_weight) degrade the calibrated pick to plain
      ``"matmul"``; an explicit ``hist_mode='matmul_sib'`` is always
      honoured.
    - ``"auto"``: the MEASURED per-platform winner from
      ``models/hist_calib.json`` (written by the on-chip sweep,
      ``build_tools/tpu_tree_sweep.py``), with a width guard — matmul /
      pallas degrade to scatter above the calibrated ``d·B`` bound.
      Platforms with no calibration entry fall back to the shape
      heuristic: matmul on accelerators for tabular widths, scatter
      otherwise. ``hist_block=None`` likewise takes the calibrated
      scatter block size.

    A fifth engine, ``"native"`` (the host C kernels of
    ``models/native_forest.py``), lives OUTSIDE this builder: estimator
    ``fit`` paths route to it before building an XLA kernel, and a
    calibrated ``"native"`` re-resolves here to the sweep's measured
    XLA runner-up (``resolve_hist_config(allow_native=False)``).
    """
    d, B, C, D = n_features, n_bins, channels, max_depth
    if newton and classification:
        raise ValueError(
            "newton=True grows a regression tree on gradient/hessian "
            "channels; pass classification=False (the boosting LOSS, "
            "not the tree, decides classification semantics)"
        )
    K = C - 1 if classification else 1  # leaf output width
    # allow_native=False: the host C engine (models/native_forest.py) is
    # selected at the FOREST level (forest.py routes around the XLA
    # kernel); this builder needs an in-program algorithm
    hist_mode, hist_block = resolve_hist_config(
        d, B, hist_mode, hist_block, allow_native=False,
        fractional_weights=fractional_weights,
    )
    if hist_mode not in ("scatter", "matmul", "matmul_sib", "pallas"):
        raise ValueError(
            f"hist_mode must be 'auto', 'scatter', 'matmul', "
            f"'matmul_sib' or 'pallas'; got {hist_mode!r}"
        )
    if hist_mode == "pallas" and B < 8:
        raise ValueError(
            f"hist_mode='pallas' requires n_bins >= 8 (TPU sublane "
            f"tiling); got n_bins={B}"
        )

    def node_scores(hist_cum, lam=None):
        return histogram_node_scores(
            hist_cum, lam, newton=newton,
            classification=classification, K=K,
        )

    def kernel(Xb, Ych, key, l2=None):
        n = Xb.shape[0]
        N = n_tree_nodes(D)
        lam = (
            (jnp.float32(0.0) if l2 is None else l2) if newton else None
        )
        feat = jnp.full((N,), -1, jnp.int32)
        thr = jnp.zeros((N,), jnp.int32)
        is_split = jnp.zeros((N,), bool)
        gain_rec = jnp.zeros((N,), jnp.float32)
        node_id = jnp.zeros((n,), jnp.int32)
        if newton:
            w_root = jnp.sum(Ych[:, 1])  # total hessian mass
        elif classification:
            w_root = jnp.sum(Ych[:, :K])
        else:
            w_root = jnp.sum(Ych[:, 0])

        # level-invariant histogram inputs, hoisted out of the unrolled
        # level loop
        if hist_mode in ("matmul", "matmul_sib"):
            # (n, d·B) one-hot of the binned features — the left matmul
            # factor for every level
            Xoh = jax.nn.one_hot(Xb, B, dtype=Ych.dtype).reshape(n, d * B)
        elif hist_mode == "pallas":
            pass  # one-hot factors are built inside the kernel, in VMEM
        else:
            # padded feature-major bins and the tiled channel matrix
            # each scatter consumes
            fb = min(hist_block, d)
            n_blocks = -(-d // fb)
            d_pad = n_blocks * fb
            XbT = Xb.T
            if d_pad != d:
                XbT = jnp.concatenate(
                    [XbT, jnp.zeros((d_pad - d, XbT.shape[1]), XbT.dtype)]
                )
            XbT_blocks = XbT.reshape(n_blocks, fb, -1)
            Ych_tiled = jnp.tile(Ych, (fb, 1))  # (fb*n, C)

        prev_hist = prev_split = None  # matmul_sib level-to-level carry
        for level in range(D):
            start = 2**level - 1
            nl = 2**level
            rel = node_id - start
            at_level = (node_id >= start) & (node_id < start + nl)

            if hist_mode == "matmul_sib" and level > 0:
                # ---- sibling subtraction: matmul ONLY the left
                # children (parent-slot one-hot masked to left-going
                # samples, half the contraction width), then derive
                # each right child as parent minus left sibling —
                # children of unsplit parents are zeroed (their "right
                # = parent - 0" would otherwise resurrect the parent's
                # samples)
                nh = nl // 2
                left = at_level & (rel % 2 == 0)
                parent_oh = jax.nn.one_hot(
                    jnp.clip(rel // 2, 0, nh - 1), nh, dtype=Ych.dtype
                ) * left[:, None].astype(Ych.dtype)
                NW = (parent_oh[:, :, None] * Ych[:, None, :]).reshape(
                    n, nh * C
                )
                hist_left = lax.dot_general(
                    Xoh, NW, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ).reshape(d, B, nh, C).transpose(0, 2, 1, 3)
                split_mask = prev_split.astype(jnp.float32)[
                    None, :, None, None
                ]
                hist_right = (prev_hist - hist_left) * split_mask
                hist = jnp.stack(
                    [hist_left, hist_right], axis=2
                ).reshape(d, nl, B, C)
            elif hist_mode in ("matmul", "matmul_sib"):
                # ---- histogram as one MXU matmul per level:
                # (d·B, n) @ (n, nl·C) with samples not at this level
                # zeroed by the node one-hot
                level_oh = jax.nn.one_hot(
                    jnp.clip(rel, 0, nl - 1), nl, dtype=Ych.dtype
                ) * at_level[:, None].astype(Ych.dtype)
                NW = (level_oh[:, :, None] * Ych[:, None, :]).reshape(
                    n, nl * C
                )
                hist = lax.dot_general(
                    Xoh, NW, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                hist = hist.reshape(d, B, nl, C).transpose(0, 2, 1, 3)
            elif hist_mode == "pallas":
                # ---- same contraction, Pallas kernel: one-hot factors
                # built in VMEM, nothing (n, d·B)-sized in HBM
                from ..ops.pallas_hist import (
                    level_histogram,
                    pallas_supported,
                )

                node_key = jnp.where(at_level, rel, nl).astype(jnp.int32)
                hist = level_histogram(
                    Xb, node_key, Ych, nl=nl, n_bins=B,
                    interpret=not pallas_supported(),
                )
            else:
                # ---- histogram: scan over feature BLOCKS, one scatter
                # per block (fewer, larger scatters pipeline better
                # than d tiny ones; block size bounds the buffer)
                seg_node = jnp.where(at_level, rel * B, nl * B * fb)
                f_off = (jnp.arange(fb) * (nl * B))[:, None]

                def hist_blk(_, xcols, seg_node=seg_node, f_off=f_off,
                             nl=nl):
                    # xcols (fb, n)
                    seg = jnp.minimum(seg_node[None, :] + f_off + xcols,
                                      nl * B * fb)
                    h = jnp.zeros((nl * B * fb + 1, C), Ych.dtype)
                    h = h.at[seg.reshape(-1)].add(Ych_tiled)
                    return None, h[: nl * B * fb].reshape(fb, nl, B, C)

                _, hist = lax.scan(hist_blk, None, XbT_blocks)
                hist = hist.reshape(d_pad, nl, B, C)[:d]  # (d, nl, B, C)
            cum = jnp.cumsum(hist, axis=2)
            gain, cnt_l, cnt_r, tot = node_scores(cum, lam)

            # ---- validity
            node_cnt = tot[0, :, -1]  # (nl,) unweighted occupancy
            ok = (cnt_l >= min_samples_leaf) & (cnt_r >= min_samples_leaf)
            gain = jnp.where(ok, gain, _NEG)

            lkey = jax.random.fold_in(key, level)
            if max_features < d:
                r = jax.random.uniform(lkey, (nl, d))
                kth = jnp.sort(r, axis=1)[:, max_features - 1]
                fmask = (r <= kth[:, None]).T  # (d, nl)
                gain = jnp.where(fmask[:, :, None], gain, _NEG)
            if extra:
                # random threshold per (feature, node) within the
                # occupied bin range — ExtraTrees semantics on bins
                cnt_bins = hist[..., -1]  # (d, nl, B)
                occ = cnt_bins > 0
                lo = jnp.argmax(occ, axis=2)  # first occupied
                hi = B - 1 - jnp.argmax(occ[:, :, ::-1], axis=2)  # last
                u = jax.random.uniform(jax.random.fold_in(lkey, 1), (d, nl))
                t_rand = lo + jnp.floor(u * jnp.maximum(hi - lo, 1)).astype(
                    jnp.int32
                )
                t_rand = jnp.clip(t_rand, 0, B - 2)
                sel = (
                    jnp.arange(B)[None, None, :] == t_rand[:, :, None]
                )
                gain = jnp.where(sel, gain, _NEG)

            # ---- pick best (feature, threshold) per node
            best_f, best_t, best_gain, do_split = pick_level_splits(
                gain, node_cnt,
                min_samples_split=min_samples_split,
                w_root=w_root,
                min_impurity_decrease=min_impurity_decrease,
            )

            idx = start + jnp.arange(nl)
            feat = feat.at[idx].set(jnp.where(do_split, best_f, -1))
            thr = thr.at[idx].set(best_t)
            is_split = is_split.at[idx].set(do_split)
            gain_rec = gain_rec.at[idx].set(jnp.where(do_split, best_gain, 0.0))
            if hist_mode == "matmul_sib":
                prev_hist, prev_split = hist, do_split

            # ---- route samples
            f_s = best_f[jnp.clip(rel, 0, nl - 1)]
            t_s = best_t[jnp.clip(rel, 0, nl - 1)]
            split_s = do_split[jnp.clip(rel, 0, nl - 1)] & at_level
            bin_s = jnp.take_along_axis(Xb, f_s[:, None], axis=1)[:, 0]
            child = 2 * node_id + 1 + (bin_s > t_s)
            node_id = jnp.where(split_s, child, node_id)

        # ---- leaf statistics over final assignments
        stats = jnp.zeros((N, C), Ych.dtype).at[node_id].add(Ych)
        if newton:
            # Newton step per node: −G/(H+λ); empty nodes hold exact 0
            # (their stats are all-zero), so unused heap slots — and
            # unused boosting rounds' whole trees — contribute nothing
            leaf = (
                -stats[:, 0] / jnp.maximum(stats[:, 1] + lam, 1e-12)
            )[:, None]
        elif classification:
            wsum = jnp.sum(stats[:, :K], axis=1, keepdims=True)
            leaf = stats[:, :K] / jnp.maximum(wsum, 1e-12)
            leaf = jnp.where(wsum > 0, leaf, 1.0 / K)
        else:
            leaf = (stats[:, 1] / jnp.maximum(stats[:, 0], 1e-12))[:, None]
        return {
            "feat": feat, "thr": thr, "is_split": is_split, "leaf": leaf,
            "gain": gain_rec,
        }

    return kernel


def tree_predict_kernel(max_depth, return_nodes=False):
    """Returns ``predict(tree, Xb) -> leaf values (n, K_out)`` (or final
    node ids when ``return_nodes`` — the ``apply()`` analogue used by
    RandomTreesEmbedding)."""

    def predict(tree, Xb):
        n = Xb.shape[0]
        node = jnp.zeros((n,), jnp.int32)
        for _ in range(max_depth):
            f = tree["feat"][node]
            t = tree["thr"][node]
            s = tree["is_split"][node]
            b = jnp.take_along_axis(
                Xb, jnp.clip(f, 0, Xb.shape[1] - 1)[:, None], axis=1
            )[:, 0]
            child = 2 * node + 1 + (b > t)
            node = jnp.where(s, child, node)
        if return_nodes:
            return node
        return tree["leaf"][node]

    return predict


def feature_importances_from_tree(feat, gain, n_features):
    """Impurity-decrease importances (sklearn semantics), host-side."""
    imp = np.zeros(n_features, dtype=np.float64)
    mask = np.asarray(feat) >= 0
    np.add.at(imp, np.asarray(feat)[mask], np.asarray(gain)[mask])
    total = imp.sum()
    return imp / total if total > 0 else imp


# ---------------------------------------------------------------------------
# channel construction
# ---------------------------------------------------------------------------

def classification_channels(y_idx, sw, n_classes):
    oh = jax.nn.one_hot(y_idx, n_classes, dtype=jnp.float32)
    cnt = (sw > 0).astype(jnp.float32)
    return jnp.concatenate([oh * sw[:, None], cnt[:, None]], axis=1)


def regression_channels(y, sw):
    cnt = (sw > 0).astype(jnp.float32)
    return jnp.stack([sw, sw * y, sw * y * y, cnt], axis=1)


def newton_channels(g, h, sw):
    """GBDT's generalization of the channel builders above: per-sample
    gradient/hessian of the boosting loss, weighted by the (possibly
    fold-masked) sample weights, plus the unweighted-occupancy channel
    the min_samples rules read. Consumed with
    ``build_tree_kernel(newton=True, channels=3)``."""
    cnt = (sw > 0).astype(jnp.float32)
    return jnp.stack([sw * g, sw * h, cnt], axis=1)


def resolve_max_features(max_features, d):
    if max_features in (None, "none", "all"):
        return d
    if max_features == "sqrt":
        return max(1, int(np.sqrt(d)))
    if max_features == "log2":
        return max(1, int(np.log2(d)))
    if isinstance(max_features, float):
        return max(1, int(max_features * d))
    return min(d, int(max_features))


# ---------------------------------------------------------------------------
# estimator classes
# ---------------------------------------------------------------------------

class _BaseTree(BaseEstimator):
    """Single-tree estimator over the histogram kernel.

    ``splitter='random'`` gives ExtraTree behaviour (random thresholds,
    no bootstrap context). The batched-fit contract marks everything
    static: tree structure params shape the compiled program.
    """

    _hyper_names = ()
    _static_names = (
        "max_depth", "n_bins", "max_features", "min_samples_split",
        "min_samples_leaf", "min_impurity_decrease", "splitter",
        "random_state", "hist_mode",
    )
    # histogram matmul operands (one-hots, counts) are exact in TPU's
    # reduced-precision passes; forcing 'highest' would only add passes
    _exact_matmuls = False

    def __init__(self, max_depth=8, n_bins=32, max_features=None,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, splitter="best", random_state=0,
                 hist_mode="auto"):
        self.max_depth = max_depth
        self.n_bins = n_bins
        self.max_features = max_features
        self.min_samples_split = min_samples_split
        self.min_samples_leaf = min_samples_leaf
        self.min_impurity_decrease = min_impurity_decrease
        self.splitter = splitter
        self.random_state = random_state
        self.hist_mode = hist_mode

    @property
    def _classification(self):
        return isinstance(self, ClassifierMixin)

    def _prep_fit_data(self, X, y, sample_weight=None):
        X = as_dense_f32(X)
        sw = prepare_sample_weight(sample_weight, X.shape[0])
        edges = quantile_bin_edges(X, self.n_bins)
        # CV fold masks are 0/1, so integral sw stays integral under
        # the batched search's mask composition — prep time is the one
        # place the weights' integral-ness is decidable for the gate
        # resolve_hist_config applies to a calibrated matmul_sib
        meta = {
            "n_features": X.shape[1], "edges": edges,
            "fractional_weights": bool(np.any(sw != np.rint(sw))),
        }
        if self._classification:
            y_idx, classes = encode_labels(y)
            meta.update(classes=classes, n_classes=len(classes))
            data = {"X": host_stage(X), "y": host_stage(y_idx),
                    "sw": host_stage(sw)}
        else:
            data = {"X": host_stage(X),
                    "y": np.asarray(y, np.float32),
                    "sw": host_stage(sw)}
        # extra data-dependent fit context; the distributed search
        # forwards non-(X,y,sw) entries to the kernel as ``aux``
        data["edges"] = host_stage(edges)
        return data, meta

    def _static_config(self, meta):
        cfg = {k: getattr(self, k) for k in self._static_names}
        cfg["_n_classes"] = meta.get("n_classes", 0)
        cfg["_n_features"] = meta["n_features"]
        # rides the static config so the kernel caches key on it and
        # _build_fit_kernel can apply the matmul_sib weight gate
        cfg["_fractional_weights"] = meta.get("fractional_weights", False)
        return cfg

    @classmethod
    def _build_fit_kernel(cls, meta, static):
        st = dict(static)
        d = st["_n_features"]
        K = st["_n_classes"]
        classification = K > 0
        C = (K + 1) if classification else 4
        grow = build_tree_kernel(
            n_features=d, n_bins=st["n_bins"], channels=C,
            max_depth=st["max_depth"],
            max_features=resolve_max_features(st["max_features"], d),
            min_samples_split=st["min_samples_split"],
            min_samples_leaf=st["min_samples_leaf"],
            min_impurity_decrease=st["min_impurity_decrease"],
            extra=(st["splitter"] == "random"),
            classification=classification,
            hist_mode=st.get("hist_mode", "auto"),
            fractional_weights=st.get("_fractional_weights", False),
        )
        seed = st["random_state"] or 0

        def kernel(X, y, sw, hyper, aux=None):
            # aux carries data-dependent context (bin edges, PRNG key) so
            # the kernel itself is cacheable purely by shape/config
            edges = aux["edges"]
            Xb = apply_bins(X, edges)
            if classification:
                Ych = classification_channels(y, sw, K)
            else:
                Ych = regression_channels(y, sw)
            key = aux.get("key")
            if key is None:
                key = jax.random.PRNGKey(seed)
            tree = grow(Xb, Ych, key)
            tree["edges"] = edges  # predict-side context travels in params
            return tree

        return kernel

    @classmethod
    def _build_decision_kernel(cls, meta, static):
        st = dict(static)
        predict = tree_predict_kernel(st["max_depth"])

        @jax.jit
        def decision(params, X):
            Xb = apply_bins(X, params["edges"])
            out = predict(params, Xb)
            return out[:, 0] if out.shape[1] == 1 else out

        return decision

    def fit(self, X, y, sample_weight=None):
        data, meta = self._prep_fit_data(X, y, sample_weight)
        mode, _ = resolve_hist_config(
            meta["n_features"], self.n_bins, self.hist_mode
        )
        if mode == "native":
            from .native_forest import (
                grow_single_tree_native,
                native_supported_or_raise,
            )

            if native_supported_or_raise(
                self.n_bins, self.hist_mode == "native"
            ):
                # host C engine as a one-tree forest: a single-tree fit
                # pays NO XLA compile (cold == warm — the compile was
                # seconds for one tree). Same engine-caveat as forests:
                # subsample/threshold PRNG streams differ from the
                # device kernel's.
                Xb = np.asarray(
                    apply_bins(jnp.asarray(data["X"]),
                               jnp.asarray(meta["edges"]))
                )
                d = meta["n_features"]
                params = grow_single_tree_native(
                    Xb, data["y"], data["sw"], self.random_state or 0,
                    n_bins=self.n_bins, max_depth=self.max_depth,
                    max_features=resolve_max_features(
                        self.max_features, d
                    ),
                    min_samples_split=self.min_samples_split,
                    min_samples_leaf=self.min_samples_leaf,
                    min_impurity_decrease=self.min_impurity_decrease,
                    extra=(self.splitter == "random"),
                    classification=self._classification,
                    n_classes=meta.get("n_classes", 0) or 1,
                )
                params["edges"] = np.asarray(meta["edges"])
                self._params = params
                self._meta = meta
                self.n_features_in_ = d
                if "classes" in meta:
                    self.classes_ = meta["classes"]
                return self
        static = _freeze(self._static_config(meta))
        kernel = get_kernel(type(self), "fit", meta, static)
        aux = {"edges": jnp.asarray(meta["edges"])}
        params = kernel(data["X"], data["y"], data["sw"], {}, aux)
        self._params = jax.device_get(params)
        self._meta = meta
        self.n_features_in_ = meta["n_features"]
        if "classes" in meta:
            self.classes_ = meta["classes"]
        return self

    def _check_fitted(self):
        if not hasattr(self, "_params"):
            raise AttributeError(
                f"This {type(self).__name__} instance is not fitted yet."
            )

    def _native_walk(self, X, mode):
        """Host C walker on the single tree (viewed as a T=1 forest);
        None falls through to the XLA decision kernel."""
        if jax.default_backend() != "cpu":
            return None
        from ..native import forest_walk_native, hist_tree_available
        from ..ops.binning import apply_bins_np

        # same ordering rationale as the forest's _native_walk:
        # availability before binning; width mismatch falls through to
        # the XLA path's loud shape error
        edges = self._params["edges"]
        if not hist_tree_available() or X.shape[1] != len(edges):
            return None
        trees = {
            k: np.asarray(self._params[k])[None]
            for k in ("feat", "thr", "is_split", "leaf")
        }
        return forest_walk_native(
            apply_bins_np(X, edges), trees, self.max_depth, mode=mode,
        )

    def _leaf_values(self, X):
        self._check_fitted()
        X = as_dense_f32(X)
        out = self._native_walk(X, "predict")
        if out is not None:
            # match the decision kernel's squeeze for regressors
            return out[:, 0] if out.shape[1] == 1 else out
        static = _freeze(self._static_config(self._meta))
        kernel = get_kernel(type(self), "decision", self._meta, static)
        params = jax.tree_util.tree_map(jnp.asarray, self._params)
        return np.asarray(kernel(params, jnp.asarray(X)))

    @property
    def feature_importances_(self):
        self._check_fitted()
        return feature_importances_from_tree(
            self._params["feat"], self._params["gain"], self.n_features_in_
        )

    def apply(self, X):
        """Leaf (node) index per sample — sklearn ``tree.apply`` analogue."""
        self._check_fitted()
        X = as_dense_f32(X)
        out = self._native_walk(X, "apply")
        if out is not None:
            return out[:, 0]
        walk = tree_predict_kernel(self.max_depth, return_nodes=True)
        params = jax.tree_util.tree_map(jnp.asarray, self._params)
        Xb = apply_bins(jnp.asarray(X), params["edges"])
        return np.asarray(walk(params, Xb))


class DecisionTreeClassifier(_BaseTree, ClassifierMixin):
    def predict_proba(self, X):
        return self._leaf_values(X)

    def predict(self, X):
        return self.classes_[np.argmax(self.predict_proba(X), axis=1)]


class DecisionTreeRegressor(_BaseTree, RegressorMixin):
    def predict(self, X):
        return self._leaf_values(X)


class ExtraTreeClassifier(DecisionTreeClassifier):
    def __init__(self, max_depth=8, n_bins=32, max_features=None,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, splitter="random", random_state=0):
        super().__init__(
            max_depth=max_depth, n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, splitter=splitter,
            random_state=random_state,
        )


class ExtraTreeRegressor(DecisionTreeRegressor):
    def __init__(self, max_depth=8, n_bins=32, max_features=None,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, splitter="random", random_state=0):
        super().__init__(
            max_depth=max_depth, n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, splitter=splitter,
            random_state=random_state,
        )
