"""
Native histogram gradient-boosted trees as a first-class fan-out
workload.

The reference treated gradient boosting as an external drop-in (xgboost
listed among the compute sk-dist "leans on", SURVEY §0), so the largest
real tabular workload class — ranking/CTR — never touched the fan-out
machinery. Here boosting is built FROM the framework's own parts:

- one boosting round = one histogram tree (``models/tree.py``'s
  ``build_tree_kernel`` in its ``newton=True`` objective: grad/hess
  channels via ``newton_channels``, gain ``G²/(H+λ)`` with the traced
  ``l2_regularization``, Newton-step leaves), quantile binning once at
  fit entry (``ops/binning.py``);
- the ensemble is a **carry chain**: the carry holds the raw
  predictions F, the stacked tree arrays, and the early-stop
  bookkeeping — exactly the shape ``batched_map_iterative`` /
  :class:`~skdist_tpu.parallel.IterativeKernelSpec` schedule, so a
  candidate×fold grid races through ``DistGridSearchCV`` as batched
  tasks, lanes retire at boosting-round boundaries (early stopping →
  the done flag), and ``adaptive=HalvingSpec(...)`` scores the LIVE
  ensemble every slice (``score_params`` shapes a valid model from the
  current carry — trees grown so far plus the baseline);
- prediction walks the stacked trees (depth-static gathers), so fitted
  models ride ``device_predict_plan`` into ``batch_predict`` and the
  serving registry (``serve_dtype`` tiers quantize the leaf-value
  arrays — ``serve/quantize.py``).

sklearn ``HistGradientBoosting*`` parity semantics: ``learning_rate``,
``max_iter``, ``max_depth``, ``max_bins``, ``l2_regularization``,
``min_samples_leaf``, ``early_stopping``/``validation_fraction``/
``n_iter_no_change``/``tol`` follow sklearn's meanings. Deliberate
divergences (inherent to the fixed-shape device design, shared with
every GPU/TPU tree library): trees are depth-bounded
(``max_depth=5`` ≈ sklearn's default ``max_leaf_nodes=31``) instead of
leaf-count-bounded, split thresholds are quantile-bin boundaries
(``max_bins`` defaults 64, not 255 — raise it when fidelity beats
wall), and the early-stopping validation split is a hash-style
deterministic row mask (sklearn uses ``train_test_split``), so
``n_iter_`` matches sklearn's stopping *rule*, not its exact round.

``learning_rate``, ``l2_regularization`` and ``tol`` are traced
hyperparameters — a grid over them vmaps into ONE XLA program; the
structure params (``max_iter``, ``max_depth``, ``max_bins``, the
early-stop knobs) are compile-shaping statics, so candidates differing
there bucket into separate programs like every other family.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin
from ..ops.binning import MAX_BINS, apply_bins, quantile_bin_edges
from .linear import (
    _freeze,
    _to_jnp,
    as_dense_f32,
    encode_labels,
    get_kernel,
    host_stage,
    hyper_float,
    prepare_sample_weight,
)
from .tree import (
    build_tree_kernel,
    n_tree_nodes,
    newton_channels,
    tree_predict_kernel,
)

__all__ = [
    "DistHistGradientBoostingClassifier",
    "DistHistGradientBoostingRegressor",
]

#: sklearn's early_stopping='auto' rule: on iff the fit sees more rows
EARLY_STOP_AUTO_N = 10000

#: probability floor for the baseline log-odds / log-prior init
_P_EPS = 1e-7


def _check_early_stopping(early_stopping):
    if early_stopping not in ("auto", True, False):
        raise ValueError(
            "early_stopping must be 'auto', True or False; got "
            f"{early_stopping!r}"
        )


def _resolve_early_stopping(early_stopping, n_samples):
    # re-validated because set_params bypasses __init__ (the
    # library-wide convention): a typo'd value must not silently
    # coerce through bool()
    _check_early_stopping(early_stopping)
    if early_stopping == "auto":
        return bool(n_samples > EARLY_STOP_AUTO_N)
    return bool(early_stopping)


def _stacked_tree_walk(Xb, feat, thr, split, leaf, max_depth):
    """Leaf values of ONE stacked tree bank: ``feat/thr/split/leaf``
    are (Kt, N) heap arrays, returns (n, Kt) — the per-round F update
    and the decision kernel share this walker, which is the ONE
    existing traversal (``tree_predict_kernel`` — the single-tree and
    forest families' walker) vmapped over the bank axis, so split
    semantics can never drift between the families."""
    walk = tree_predict_kernel(max_depth)

    def walk_one(f_a, t_a, s_a, l_a):
        tree = {"feat": f_a, "thr": t_a, "is_split": s_a,
                "leaf": l_a[:, None]}
        return walk(tree, Xb)[:, 0]

    return jnp.transpose(jax.vmap(walk_one)(feat, thr, split, leaf))


def _build_boost_parts(meta, static):
    """The one construction point of a GBDT fit's traced pieces:
    ``init_carry`` / ``resume`` / ``finalize`` closures over (X, y, sw,
    hyper, aux). The plain fit kernel (init + one full resume) and the
    iteration-sliced kernels (init + n_slice, step = n_slice more) are
    both generated from these, so a sliced run is bitwise identical to
    the fused solve — the same guarded round body runs in the same
    order, only the loop partitioning differs."""
    st = dict(static)
    K = st.get("_n_classes", 0)
    classification = K > 0
    Kt = 1 if (not classification or K <= 2) else K
    D = int(st["max_depth"])
    T = int(st["max_iter"])
    N = n_tree_nodes(D)
    es = bool(st["_early_stopping"])
    vf = st["validation_fraction"]
    patience = int(st["n_iter_no_change"])
    seed = int(st["random_state"] or 0)
    loss_name = st["loss"]
    if T < 1:
        raise ValueError(f"max_iter must be >= 1; got {T}")
    if patience < 1:
        raise ValueError(
            f"n_iter_no_change must be >= 1; got {patience}"
        )
    if classification and loss_name != "log_loss":
        raise ValueError(
            "DistHistGradientBoostingClassifier supports loss='log_loss'"
        )
    if not classification and loss_name != "squared_error":
        raise ValueError(
            "DistHistGradientBoostingRegressor supports "
            "loss='squared_error'"
        )
    if vf is not None and not 0.0 < float(vf) < 1.0:
        raise ValueError(
            f"validation_fraction must be in (0, 1) or None; got {vf!r}"
        )

    grow = build_tree_kernel(
        n_features=st["_n_features"], n_bins=st["max_bins"], channels=3,
        max_depth=D, max_features=st["_n_features"], min_samples_split=2,
        min_samples_leaf=st["min_samples_leaf"],
        min_impurity_decrease=0.0, extra=False, classification=False,
        hist_mode=st.get("hist_mode", "auto"),
        # grad/hess channels are fractional by construction: a
        # calibrated matmul_sib 'auto' pick must degrade to matmul
        fractional_weights=True, newton=True,
    )

    def grads(F, y):
        """Per-sample (gradient, hessian) of the boosting loss at raw
        predictions ``F`` (n, Kt)."""
        if not classification:
            return F[:, 0] - y, jnp.ones_like(F[:, 0])
        if K <= 2:
            y01 = (y == (K - 1)).astype(jnp.float32)
            p = jax.nn.sigmoid(F[:, 0])
            return p - y01, p * (1.0 - p)
        P = jax.nn.softmax(F, axis=1)
        Y1 = jax.nn.one_hot(y, K, dtype=jnp.float32)
        return P - Y1, P * (1.0 - P)

    def loss_vals(F, y):
        """Per-sample loss at raw predictions — what the early-stop
        monitor averages (sklearn's scoring='loss')."""
        if not classification:
            return 0.5 * (y - F[:, 0]) ** 2
        if K <= 2:
            y01 = (y == (K - 1)).astype(jnp.float32)
            z = F[:, 0]
            return jax.nn.softplus(z) - y01 * z
        lse = jax.nn.logsumexp(F, axis=1)
        fy = jnp.take_along_axis(
            F, y.astype(jnp.int32)[:, None], axis=1
        )[:, 0]
        return lse - fy

    def baseline_of(y, w):
        """Constant raw prediction minimising the loss on the weighted
        train rows: weighted mean / log-odds / log-priors."""
        wsum = jnp.maximum(jnp.sum(w), 1e-12)
        if not classification:
            return (jnp.sum(w * y) / wsum)[None]
        if K <= 2:
            y01 = (y == (K - 1)).astype(jnp.float32)
            p = jnp.clip(jnp.sum(w * y01) / wsum, _P_EPS, 1.0 - _P_EPS)
            return jnp.log(p / (1.0 - p))[None]
        pri = jax.nn.one_hot(y, K, dtype=jnp.float32).T @ w / wsum
        return jnp.log(jnp.clip(pri, _P_EPS, None))

    def fit_weights(sw, n):
        """(train_w, monitor_w): the early-stop validation split is a
        deterministic PRNG row mask shared by init/step/finalize (they
        are separate jit entries, so the mask must be a pure function
        of static config + n). Rows outside this task's CV fold carry
        sw == 0 and drop out of both sides."""
        if es and vf is not None:
            r = jax.random.uniform(
                jax.random.PRNGKey(seed ^ 0x5DEECE66), (n,)
            )
            vmask = (r < float(vf)).astype(jnp.float32)
            return sw * (1.0 - vmask), sw * vmask
        return sw, sw

    def init_carry(X, y, sw, hyper, aux=None):
        n = X.shape[0]
        train_w, _ = fit_weights(sw, n)
        b0 = baseline_of(y, train_w).astype(jnp.float32)  # (Kt*,)
        F0 = jnp.broadcast_to(b0[None, :], (n, Kt)).astype(jnp.float32)
        zi = jnp.zeros((T, Kt, N), jnp.int32)
        return {
            "F": F0,
            "feat": jnp.full((T, Kt, N), -1, jnp.int32),
            "thr": zi,
            "split": jnp.zeros((T, Kt, N), bool),
            "leaf": jnp.zeros((T, Kt, N), jnp.float32),
            "baseline": b0,
            "it": jnp.int32(0),
            "done": jnp.asarray(False),
            "best": jnp.float32(np.inf),
            "bad": jnp.int32(0),
        }

    def resume(X, y, sw, hyper, carry, n_rounds, aux=None):
        n = X.shape[0]
        Xb = apply_bins(X, aux["edges"])
        lr = hyper["learning_rate"]
        lam = hyper["l2_regularization"]
        tol = hyper["tol"]
        train_w, monitor_w = fit_weights(sw, n)
        base_key = jax.random.PRNGKey(seed)

        def round_body(c):
            it = c["it"]
            g, h = grads(c["F"], y)  # (n,) or (n, K)
            key = jax.random.fold_in(base_key, it)
            if Kt == 1:
                Ych = newton_channels(g, h, train_w)
                tree = grow(Xb, Ych, key, lam)
                feat_r = tree["feat"][None]        # (1, N)
                thr_r = tree["thr"][None]
                split_r = tree["is_split"][None]
                leaf_r = (tree["leaf"][:, 0] * lr)[None]
            else:
                Ych_k = jax.vmap(
                    lambda gk, hk: newton_channels(gk, hk, train_w),
                    in_axes=(1, 1),
                )(g, h)  # (K, n, 3)
                keys = jax.random.split(key, Kt)
                trees = jax.vmap(
                    lambda ych, k: grow(Xb, ych, k, lam),
                    in_axes=(0, 0),
                )(Ych_k, keys)
                feat_r = trees["feat"]             # (K, N)
                thr_r = trees["thr"]
                split_r = trees["is_split"]
                leaf_r = trees["leaf"][..., 0] * lr
            F_new = c["F"] + _stacked_tree_walk(
                Xb, feat_r, thr_r, split_r, leaf_r, D
            )
            mon = jnp.sum(monitor_w * loss_vals(F_new, y)) / jnp.maximum(
                jnp.sum(monitor_w), 1e-12
            )
            improved = mon < c["best"] - tol
            it1 = it + 1
            done = it1 >= T
            bad = jnp.where(improved, 0, c["bad"] + 1).astype(jnp.int32)
            if es:
                done = done | (bad >= patience)
            return {
                "F": F_new,
                "feat": c["feat"].at[it].set(feat_r),
                "thr": c["thr"].at[it].set(thr_r),
                "split": c["split"].at[it].set(split_r),
                "leaf": c["leaf"].at[it].set(leaf_r),
                "baseline": c["baseline"],
                "it": it1,
                "done": done,
                "best": jnp.minimum(c["best"], mon),
                "bad": bad,
            }

        def guarded(_, c):
            new = round_body(c)
            return jax.tree_util.tree_map(
                lambda o, v: jnp.where(c["done"], o, v), c, new
            )

        return lax.fori_loop(0, int(n_rounds), guarded, carry)

    def finalize(carry, aux=None):
        return {
            "feat": carry["feat"],
            "thr": carry["thr"],
            "is_split": carry["split"],
            "leaf": carry["leaf"],
            "baseline": carry["baseline"],
            "n_iter": carry["it"],
            "edges": aux["edges"],
        }

    return {
        "init_carry": init_carry, "resume": resume, "finalize": finalize,
        "Kt": Kt, "D": D, "T": T, "classification": classification,
        "K": K,
        # the loss pieces alone, for the streamed driver
        # (models/streaming._fit_gbdt_stream): per-block grad/hess and
        # monitor terms recompute from the SAME closures the resident
        # round body traces — parity by shared code
        "grads": grads, "loss_vals": loss_vals,
    }


class _BaseGBDT(BaseEstimator):
    """Shared surface of the two boosting estimators: the batched-fit
    contract (``_hyper_names``/``_static_names``/``_prep_fit_data``/
    ``_build_fit_kernel``/``_build_decision_kernel``), the
    iteration-sliced contract the convergence-compacted scheduler and
    ASHA consume (``_build_fit_slice_kernels`` — one boosting round per
    iteration, the live carry scoreable at every slice boundary), and
    the fitted predict surface ``device_predict_plan`` stages into the
    serving registry."""

    _hyper_names = ("learning_rate", "l2_regularization", "tol")
    _static_names = (
        "loss", "max_iter", "max_depth", "max_bins", "min_samples_leaf",
        "early_stopping", "validation_fraction", "n_iter_no_change",
        "random_state", "hist_mode",
    )
    #: tree kernels opt out of the 'highest' matmul pass (see
    #: linear.exact_matmuls): the histogram contraction accumulates f32
    #: via preferred_element_type on every engine already
    _exact_matmuls = False
    #: packed-CSR input has no histogram form; prepare_fit_X densifies
    _supports_packed_X = False
    #: the compacted scheduler's gate: boosting rounds are the
    #: iteration axis, early stopping is the done flag
    _supports_sliced_fit = True
    #: out-of-core driver (models/streaming._fit_gbdt_stream): boosting
    #: rounds stream the uint8 binned block cache, the margin carry F
    #: lives in host memmaps, rungs fire at round boundaries
    _stream_fit_kind = "gbdt"

    def __init__(self, loss, learning_rate=0.1, max_iter=100, max_depth=5,
                 max_bins=64, l2_regularization=0.0, min_samples_leaf=20,
                 early_stopping="auto", validation_fraction=0.1,
                 n_iter_no_change=10, tol=1e-7, random_state=0,
                 hist_mode="auto"):
        self.loss = loss
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.l2_regularization = l2_regularization
        self.min_samples_leaf = min_samples_leaf
        self.early_stopping = early_stopping
        self.validation_fraction = validation_fraction
        self.n_iter_no_change = n_iter_no_change
        self.tol = tol
        self.random_state = random_state
        self.hist_mode = hist_mode
        if not 2 <= int(max_bins) <= MAX_BINS:
            raise ValueError(
                f"max_bins must be in [2, {MAX_BINS}]; got {max_bins}"
            )
        _check_early_stopping(early_stopping)
        self._check_hypers()

    def _check_hypers(self):
        """sklearn-parity domain validation of the traced hypers (the
        values sklearn's HistGradientBoosting* rejects): called from
        __init__ AND from _prep_fit_data, so clone+set_params fits (the
        generic search path included) revalidate like the statics do.
        Batched grids validate the estimator's own values per bucket;
        per-candidate hyper arrays ride the traced task axis unchecked
        — grid authors own those like every traced hyper."""
        lr = getattr(self, "learning_rate", 0.1)
        if not (lr is None or float(lr) > 0):
            raise ValueError(
                f"learning_rate must be > 0; got {lr!r}"
            )
        l2 = getattr(self, "l2_regularization", 0.0)
        if not (l2 is None or float(l2) >= 0):
            raise ValueError(
                f"l2_regularization must be >= 0; got {l2!r}"
            )

    @property
    def _classification(self):
        return isinstance(self, ClassifierMixin)

    @classmethod
    def _batched_task_cost(cls, hyper):
        """Round-packing heuristic: a smaller learning rate needs more
        boosting rounds before the no-improvement rule fires, and a
        tighter tol delays it further (tol=None → -inf never stops
        early and sorts last — the linear families' convention)."""
        lr = np.asarray(hyper.get("learning_rate", 0.1), dtype=np.float64)
        tol = np.asarray(hyper.get("tol", 1e-7), dtype=np.float64)
        cost = -np.log(np.maximum(lr, 1e-30)) - np.where(
            tol > 0, np.log(np.where(tol > 0, tol, 1.0)), -np.inf
        )
        return np.broadcast_to(
            cost, np.broadcast_shapes(lr.shape, tol.shape)
        )

    # ---- fit-data prep ----------------------------------------------------
    def _prep_fit_data(self, X, y, sample_weight=None):
        self._check_hypers()
        X = as_dense_f32(X)
        sw = prepare_sample_weight(sample_weight, X.shape[0])
        edges = quantile_bin_edges(X, self.max_bins)
        meta = {
            "n_features": X.shape[1],
            "n_samples": X.shape[0],
            "edges": edges,
            # stamps batched dispatches as the histogram-tree family in
            # last_round_stats (linear.kernel_mode_of)
            "kernel_family": "hist_tree",
        }
        if self._classification:
            y_idx, classes = encode_labels(y)
            meta.update(classes=classes, n_classes=len(classes))
            data = {"X": host_stage(X), "y": host_stage(y_idx),
                    "sw": host_stage(sw)}
        else:
            data = {"X": host_stage(X),
                    "y": np.asarray(y, np.float32).reshape(-1),
                    "sw": host_stage(sw)}
        data["edges"] = host_stage(edges)
        return data, meta

    def _static_config(self, meta):
        cfg = {k: getattr(self, k) for k in self._static_names}
        cfg["_n_classes"] = meta.get("n_classes", 0)
        cfg["_n_features"] = meta["n_features"]
        # 'auto' resolves against the fit's row count, so it must ride
        # the compiled program's key — two datasets straddling the
        # threshold are different programs
        cfg["_early_stopping"] = _resolve_early_stopping(
            self.early_stopping, meta.get("n_samples", 0)
        )
        return cfg

    # ---- kernels ----------------------------------------------------------
    @classmethod
    def _build_fit_kernel(cls, meta, static):
        parts = _build_boost_parts(meta, static)

        def kernel(X, y, sw, hyper, aux=None):
            carry = parts["init_carry"](X, y, sw, hyper, aux)
            carry = parts["resume"](
                X, y, sw, hyper, carry, parts["T"], aux
            )
            return parts["finalize"](carry, aux)

        return kernel

    @classmethod
    def _build_fit_slice_kernels(cls, meta, static, n_slice):
        """Iteration-sliced boosting: ``init`` starts the carry chain
        and runs the first ``n_slice`` rounds, ``step`` boosts another
        slice, ``finalize`` shapes the ensemble params. The carry's
        ``done`` leaf (early-stopped or round budget exhausted) is the
        flags-only gather the compaction loop reads, and
        ``score_params`` shapes a VALID model from the live carry —
        trees grown so far plus the baseline — so ASHA rungs read
        trajectories without perturbing them."""
        parts = _build_boost_parts(meta, static)
        n_slice = int(n_slice)

        def init(X, y, sw, hyper, aux=None):
            carry = parts["init_carry"](X, y, sw, hyper, aux)
            return parts["resume"](X, y, sw, hyper, carry, n_slice, aux)

        def step(X, y, sw, hyper, carry, aux=None):
            return parts["resume"](X, y, sw, hyper, carry, n_slice, aux)

        def finalize(X, y, sw, hyper, carry, aux=None):
            return parts["finalize"](carry, aux)

        return {
            "init": init, "step": step, "finalize": finalize,
            # F (n, Kt) and the early-stop scalars never leave the
            # device at retirement — only the tree bank does
            "finalize_keys": ("feat", "thr", "split", "leaf",
                              "baseline", "it"),
            "score_params": finalize,
        }

    @classmethod
    def _build_decision_kernel(cls, meta, static):
        st = dict(static)
        K = st.get("_n_classes", 0)
        classification = K > 0
        Kt = 1 if (not classification or K <= 2) else K
        D = int(st["max_depth"])

        @jax.jit
        def decision(params, X):
            Xb = apply_bins(X, params["edges"])

            def one_round(F, tr):
                feat_r, thr_r, split_r, leaf_r = tr
                return F + _stacked_tree_walk(
                    Xb, feat_r, thr_r, split_r, leaf_r, D
                ), None

            n = Xb.shape[0]
            F0 = jnp.broadcast_to(
                params["baseline"][None, :], (n, Kt)
            ).astype(jnp.float32)
            # rounds past n_iter hold all-zero trees (no splits, zero
            # leaves), so scanning the full static T is exact
            F, _ = lax.scan(
                one_round, F0,
                (params["feat"], params["thr"], params["is_split"],
                 params["leaf"]),
            )
            return F[:, 0] if Kt == 1 else F

        return decision

    # ---- streamed (out-of-core) fit ---------------------------------------
    def _prep_stream_fit(self, dataset, y, sample_weight=None):
        """Stage a ChunkedDataset fit: sketch bin edges in one raw pass,
        build (or memory-map back) the uint8 binned block cache in a
        second, and hand the driver a meta that carries both — boosting
        rounds then stream only the cache, never the raw features."""
        self._check_hypers()
        if dataset.x_format != "dense":
            raise TypeError(
                f"{type(self).__name__} has no histogram form for "
                f"packed ('{dataset.x_format}') ChunkedDatasets; "
                "stream a dense dataset or materialise + densify"
            )
        if y is None:
            raise ValueError(
                f"{type(self).__name__} needs labels: the "
                "ChunkedDataset carries none and no y was passed"
            )
        es = _resolve_early_stopping(self.early_stopping, dataset.n_rows)
        if es and self.validation_fraction is not None:
            raise ValueError(
                f"{type(self).__name__} cannot hold out a validation "
                "fraction from a streamed fit (blocks arrive once per "
                "pass; there is no resident split to carve). Supported "
                "over a ChunkedDataset: validation_fraction=None "
                "(early stopping monitors the streaming train loss, "
                "like the resident train-loss monitor) or "
                "early_stopping=False"
            )
        # fail fast on one-shot readers BEFORE the sketch pass spends a
        # full traversal: the fit needs the raw stream exactly twice
        # (sketch + bin) and the cached stream once per boosting pass
        dataset.check_seekable()
        cache = dataset.with_binned_cache(max_bins=self.max_bins)
        sw = prepare_sample_weight(sample_weight, dataset.n_rows)
        meta = {
            "n_features": dataset.n_features,
            "n_samples": dataset.n_rows,
            "edges": cache.edges,
            "kernel_family": "hist_tree",
            "binned_cache": cache,
        }
        if self._classification:
            y_idx, classes = encode_labels(y)
            meta.update(classes=classes, n_classes=len(classes))
            y_enc = y_idx
        else:
            y_enc = np.asarray(y, np.float32).reshape(-1)
        return y_enc, sw, meta

    def _set_fitted(self, params, meta):
        """Land a fitted state from the streamed driver (mirrors
        linear._set_fitted): the binned cache is a fit-time artifact,
        not part of the fitted surface — predict bins raw features
        against ``edges`` in-program."""
        meta = {k: v for k, v in meta.items() if k != "binned_cache"}
        self._params = jax.device_get(params)
        self._meta = meta
        self.n_features_in_ = meta["n_features"]
        if "classes" in meta:
            self.classes_ = meta["classes"]
        self.n_iter_ = int(np.asarray(self._params["n_iter"]).reshape(()))
        return self

    # ---- fitted surface ---------------------------------------------------
    def fit(self, X, y=None, sample_weight=None):
        from ..data import is_chunked

        if is_chunked(X):
            from .streaming import stream_fit_estimator

            return stream_fit_estimator(
                self, X, y=y, sample_weight=sample_weight
            )
        if y is None:
            raise TypeError(f"{type(self).__name__}.fit requires y")
        data, meta = self._prep_fit_data(X, y, sample_weight)
        static = _freeze(self._static_config(meta))
        hyper = {k: jnp.asarray(hyper_float(getattr(self, k)))
                 for k in self._hyper_names}
        kernel = get_kernel(type(self), "fit", meta, static)
        params = kernel(data["X"], data["y"], data["sw"], hyper,
                        {"edges": jnp.asarray(meta["edges"])})
        return self._set_fitted(params, meta)

    def _check_fitted(self):
        if not hasattr(self, "_params"):
            raise AttributeError(
                f"This {type(self).__name__} instance is not fitted yet."
            )

    def decision_function(self, X):
        self._check_fitted()
        from ..data import is_chunked

        if is_chunked(X):
            raise TypeError(
                "decision_function does not take a ChunkedDataset; use "
                "skdist_tpu.distribute.batch_predict(model, dataset)"
            )
        X = as_dense_f32(X)
        static = _freeze(self._static_config(self._meta))
        kernel = get_kernel(type(self), "decision", self._meta, static)
        return np.asarray(kernel(_to_jnp(self._params), jnp.asarray(X)))


class DistHistGradientBoostingClassifier(_BaseGBDT, ClassifierMixin):
    """Histogram gradient-boosting classifier (log loss).

    Binary fits grow one tree per round on the sigmoid gradient/
    hessian; K-class fits grow K trees per round (one compiled program
    — the class axis vmaps inside the round) on the softmax grad/hess,
    sklearn/XGBoost's one-vs-all Newton boosting. ``decision_function``
    returns raw logits ((n,) binary / (n, K) multiclass), so the
    device scorers, ``DistGridSearchCV``'s fused CV kernel, and the
    serving plane consume it exactly like the linear classifiers.
    """

    def __init__(self, loss="log_loss", learning_rate=0.1, max_iter=100,
                 max_depth=5, max_bins=64, l2_regularization=0.0,
                 min_samples_leaf=20, early_stopping="auto",
                 validation_fraction=0.1, n_iter_no_change=10, tol=1e-7,
                 random_state=0, hist_mode="auto"):
        super().__init__(
            loss=loss, learning_rate=learning_rate, max_iter=max_iter,
            max_depth=max_depth, max_bins=max_bins,
            l2_regularization=l2_regularization,
            min_samples_leaf=min_samples_leaf,
            early_stopping=early_stopping,
            validation_fraction=validation_fraction,
            n_iter_no_change=n_iter_no_change, tol=tol,
            random_state=random_state, hist_mode=hist_mode,
        )
        if loss != "log_loss":
            raise ValueError(
                "DistHistGradientBoostingClassifier supports "
                "loss='log_loss'"
            )

    @classmethod
    def _build_proba_kernel(cls, meta, static):
        decision = cls._build_decision_kernel(meta, static)
        binary = meta.get("n_classes", 2) <= 2

        @jax.jit
        def proba(params, X):
            z = decision(params, X)
            if binary:
                p1 = jax.nn.sigmoid(z)
                return jnp.stack([1.0 - p1, p1], axis=1)
            return jax.nn.softmax(z, axis=1)

        return proba

    def predict_proba(self, X):
        self._check_fitted()
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict_proba")
        X = as_dense_f32(X)
        static = _freeze(self._static_config(self._meta))
        kernel = get_kernel(type(self), "proba", self._meta, static)
        return np.asarray(kernel(_to_jnp(self._params), jnp.asarray(X)))

    def predict_log_proba(self, X):
        return np.log(np.clip(self.predict_proba(X), 1e-15, None))

    def predict(self, X):
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict")
        scores = self.decision_function(X)
        if scores.ndim == 1:
            idx = (scores > 0).astype(np.int64)
        else:
            idx = np.argmax(scores, axis=1)
        return self.classes_[idx]


class DistHistGradientBoostingRegressor(_BaseGBDT, RegressorMixin):
    """Histogram gradient-boosting regressor (squared error): one tree
    per round on the residuals (unit hessian), Newton leaves with the
    traced ``l2_regularization``. ``decision_function``/``predict``
    return raw predictions (n,), the shape the regression device
    scorers (r2 / neg_mean_squared_error / ...) consume as ``kind
    ='predict'`` — including as ASHA rung metrics."""

    def __init__(self, loss="squared_error", learning_rate=0.1,
                 max_iter=100, max_depth=5, max_bins=64,
                 l2_regularization=0.0, min_samples_leaf=20,
                 early_stopping="auto", validation_fraction=0.1,
                 n_iter_no_change=10, tol=1e-7, random_state=0,
                 hist_mode="auto"):
        super().__init__(
            loss=loss, learning_rate=learning_rate, max_iter=max_iter,
            max_depth=max_depth, max_bins=max_bins,
            l2_regularization=l2_regularization,
            min_samples_leaf=min_samples_leaf,
            early_stopping=early_stopping,
            validation_fraction=validation_fraction,
            n_iter_no_change=n_iter_no_change, tol=tol,
            random_state=random_state, hist_mode=hist_mode,
        )
        if loss != "squared_error":
            raise ValueError(
                "DistHistGradientBoostingRegressor supports "
                "loss='squared_error'"
            )

    def predict(self, X):
        from ..data import is_chunked

        if is_chunked(X):
            from ..distribute.predict import batch_predict

            return batch_predict(self, X, method="predict")
        return self.decision_function(X)
