"""
JAX/XLA estimator kernels.

The reference (sk-dist) borrowed all its per-task compute from sklearn's
native code: liblinear/lbfgs C solvers for linear models and Cython tree
builders for forests (SURVEY §2.2). skdist_tpu supplies that compute as
jit/vmap-able JAX kernels so that *many fits of the same shape compile
into one XLA program* — the core idiomatic win over per-task Spark
dispatch. Every model exposes the sklearn estimator protocol
(``fit/predict/predict_proba/score/get_params/set_params``) plus a
batched-fit contract consumed by the distributed meta-estimators.
"""

from .linear import (
    LinearRegression,
    LinearSVC,
    LogisticRegression,
    Ridge,
    RidgeClassifier,
    SGDClassifier,
)
from .tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    ExtraTreeClassifier,
    ExtraTreeRegressor,
)
from .forest import (
    ExtraTreesClassifier,
    ExtraTreesRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    RandomTreesEmbedding,
)
from .gbdt import (
    DistHistGradientBoostingClassifier,
    DistHistGradientBoostingRegressor,
)
from .naive_bayes import GaussianNB, MultinomialNB

__all__ = [
    "DistHistGradientBoostingClassifier",
    "DistHistGradientBoostingRegressor",
    "LogisticRegression",
    "LinearSVC",
    "SGDClassifier",
    "Ridge",
    "RidgeClassifier",
    "LinearRegression",
    "DecisionTreeClassifier",
    "DecisionTreeRegressor",
    "ExtraTreeClassifier",
    "ExtraTreeRegressor",
    "RandomForestClassifier",
    "RandomForestRegressor",
    "ExtraTreesClassifier",
    "ExtraTreesRegressor",
    "RandomTreesEmbedding",
    "GaussianNB",
    "MultinomialNB",
]
