"""Measured per-platform calibration for the tree histogram kernel.

``build_tree_kernel(hist_mode="auto")`` used to hard-code "matmul on
accelerators, scatter on CPU" from CPU-only timings (round-2 VERDICT
weak #3: the opposite order is *expected* on the MXU but was never
measured). This module replaces the guess with a small committed table,
``hist_calib.json``, written by ``build_tools/tpu_tree_sweep.py`` from
actual on-platform sweeps (mode × hist_block on the NOTES benchmark
shape, 20k×54×7 depth 8, 32 bins):

    {"cpu":  {"mode": "scatter", "hist_block": 8, ...provenance...},
     "tpu":  {"mode": "matmul",  ...}}

``auto`` resolution asks :func:`get_calibration` for the current
platform; a missing entry falls back to the shape heuristic in
``tree.py``. Width guard: matmul/pallas materialise or contract a
(n, d·B)-sized one-hot, so a calibrated "matmul" still degrades to
scatter above ``max_matmul_db`` (d·B product), whatever the table says.

The reference leaned on sklearn's Cython ``tree.fit`` for this
(reference ``skdist/distribute/ensemble.py:106-108``); here the engine
choice is a measured, persisted decision per platform.
"""

import json
import os
import threading

_DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "hist_calib.json")
#: env override so sweeps can stage candidate entries in a scratch file
#: without a crash mid-sweep leaving a half-measured entry as the
#: committed table (build_tools/tpu_tree_sweep.py sets it for ranking)
PATH_ENV = "SKDIST_HIST_CALIB_PATH"
_LOCK = threading.Lock()
_CACHE = {}  # path -> (mtime, table)


def _calib_path():
    return os.environ.get(PATH_ENV) or _DEFAULT_PATH

#: matmul/pallas refuse wider than this d·B product under "auto"
#: (a 20-newsgroups-style hashed width would put a multi-GB one-hot in
#: HBM for FLOP gains that scale the wrong way)
DEFAULT_MAX_MATMUL_DB = 16384

#: matmul_sib is a legal CALIBRATED mode: build_tools/tpu_tree_sweep.py
#: measures it as a candidate (recording a matmul_sib winner used to
#: crash record_calibration), and resolve_hist_config gates the 'auto'
#: pick to integer-effective-weight fits (fractional weights degrade to
#: plain matmul — see models/tree.py)
_VALID_MODES = ("scatter", "matmul", "matmul_sib", "pallas", "native")


def _load_table():
    path = _calib_path()
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return {}
    with _LOCK:
        ent = _CACHE.get(path)
        if ent is None or ent[0] != mtime:
            try:
                with open(path) as f:
                    ent = (mtime, json.load(f))
                _CACHE[path] = ent
            except (OSError, ValueError):
                return ent[1] if ent else {}
        return ent[1] or {}


def get_calibration(platform):
    """Measured entry for ``platform`` (e.g. ``"cpu"``, ``"tpu"``) or
    None. Entries with unknown modes are ignored (forward compat)."""
    ent = _load_table().get(platform)
    if not isinstance(ent, dict) or ent.get("mode") not in _VALID_MODES:
        return None
    return ent


def record_calibration(platform, mode, hist_block=8, measured=None,
                       source=None, xla_mode=None, xla_hist_block=None):
    """Persist a sweep result for ``platform`` (used by
    ``build_tools/tpu_tree_sweep.py``). Merges with existing entries so
    a CPU sweep does not erase a TPU one.

    ``xla_mode``: the measured best IN-PROGRAM engine — recorded
    alongside a ``"native"`` winner so callers that need an XLA
    algorithm (distributed mesh fits) re-resolve to the measured XLA
    runner-up instead of a shape heuristic."""
    if mode not in _VALID_MODES:
        raise ValueError(f"mode must be one of {_VALID_MODES}; got {mode!r}")
    if xla_mode is not None and xla_mode not in ("scatter", "matmul",
                                                 "matmul_sib", "pallas"):
        raise ValueError(f"xla_mode must be an XLA engine; got {xla_mode!r}")
    path = _calib_path()
    with _LOCK:
        table = {}
        try:
            with open(path) as f:
                table = json.load(f)
        except (OSError, ValueError):
            pass
        table[platform] = {
            "mode": mode,
            "hist_block": int(hist_block),
            "max_matmul_db": DEFAULT_MAX_MATMUL_DB,
            "measured": measured or {},
            "source": source or "build_tools/tpu_tree_sweep.py",
        }
        if xla_mode is not None:
            table[platform]["xla_mode"] = xla_mode
            if xla_hist_block is not None:
                table[platform]["xla_hist_block"] = int(xla_hist_block)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        _CACHE.pop(path, None)
    return table[platform]
