"""
Forest kernels: RandomForest / ExtraTrees (classifier + regressor) and
RandomTreesEmbedding.

Where the reference ships one Spark task per tree — broadcast the data,
``sc.parallelize(seeds).map(_build_trees).collect()`` the fitted Cython
trees back (``/root/reference/skdist/distribute/ensemble.py:278-325``) —
here the tree axis is the vmapped task axis of ONE histogram-tree
program (``models/tree.py``): per-tree PRNG seeds ride the task axis,
bootstrap resampling is a scatter-add count vector times the sample
weights (the reference's ``_generate_sample_indices`` + bincount,
ensemble.py:51-55,88-104, done on device), and the fitted forest is a
stacked pytree of tree arrays living in host memory. The distributed
wrappers (``distribute/ensemble.py``) shard the same axis over the TPU
mesh via ``backend.batched_map``.
"""

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin, TransformerMixin
from ..ops.binning import apply_bins, quantile_bin_edges
from ..parallel import LocalBackend
from .linear import (
    as_dense_f32,
    class_weight_vector,
    encode_labels,
    prepare_sample_weight,
)
from .tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    build_tree_kernel,
    classification_channels,
    feature_importances_from_tree,
    n_tree_nodes,
    regression_channels,
    resolve_hist_config,
    resolve_max_features,
    tree_predict_kernel,
)

__all__ = [
    "RandomForestClassifier",
    "RandomForestRegressor",
    "ExtraTreesClassifier",
    "ExtraTreesRegressor",
    "RandomTreesEmbedding",
]

MAX_RAND_SEED = np.iinfo(np.int32).max

# module-level cache of jitted forest walkers: jax.jit caches on function
# identity, so per-call closures would recompile on every predict
_WALKER_CACHE = {}


def _forest_walker(max_depth, mode):
    key = (max_depth, mode)
    fn = _WALKER_CACHE.get(key)
    if fn is None:
        walk = tree_predict_kernel(max_depth, return_nodes=(mode == "apply"))

        if mode == "apply":
            @jax.jit
            def fn(trees, Xb):
                return jax.vmap(lambda t: walk(t, Xb))(trees).T  # (n, T)
        else:
            @jax.jit
            def fn(trees, Xb):
                per_tree = jax.vmap(lambda t: walk(t, Xb))(trees)  # (T,n,K)
                return jnp.mean(per_tree, axis=0)

        _WALKER_CACHE[key] = fn
    return fn


def _bootstrap_counts(seed, n, dtype=jnp.float32):
    """Reproduce a tree's bootstrap draw from its seed (the same draw
    the fit kernel made), so OOB masks never need to be persisted."""
    kboot, _ = jax.random.split(jax.random.PRNGKey(seed))
    idx = jax.random.randint(kboot, (n,), 0, n)
    return jnp.zeros((n,), dtype).at[idx].add(1.0)


@lru_cache(maxsize=8)
def _bootstrap_counts_batch(n):
    """Jitted (seeds,) -> (T, n) bootstrap counts; cached per n so
    repeat host-engine fits skip re-tracing (~2 s per fit otherwise)."""
    return jax.jit(jax.vmap(lambda s: _bootstrap_counts(s, n)))


def _oob_aggregator(max_depth):
    """Cached jitted OOB aggregation (same function-identity caching
    rationale as _forest_walker). Masks are regenerated from the stored
    per-tree seeds, so warm-started trees participate too."""
    key = (max_depth, "oob")
    fn = _WALKER_CACHE.get(key)
    if fn is None:
        walk = tree_predict_kernel(max_depth)

        @jax.jit
        def fn(trees, seeds, Xb):
            n = Xb.shape[0]
            per_tree = jax.vmap(lambda t: walk(t, Xb))(trees)  # (T, n, K)
            counts = jax.vmap(lambda s: _bootstrap_counts(s, n))(seeds)
            m = (counts == 0).astype(per_tree.dtype)  # (T, n)
            num = jnp.sum(per_tree * m[:, :, None], axis=0)
            cnt = jnp.sum(m, axis=0)
            return num / jnp.maximum(cnt, 1.0)[:, None], cnt

        _WALKER_CACHE[key] = fn
    return fn


def make_forest_tree_kernel(d, n_bins, channels, max_depth, max_features,
                            min_samples_split, min_samples_leaf,
                            min_impurity_decrease, extra, classification,
                            bootstrap, hist_mode="auto", hist_block=None,
                            fractional_weights=False):
    """One-tree task kernel for ``backend.batched_map``: the task is a
    scalar PRNG seed (mirroring the reference's per-tree random states,
    ensemble.py:278). The seed is stored with the tree so OOB masks
    (``_oob_aggregator``) regenerate the bootstrap draw on demand.

    The kernel is MEMOISED on its full static config: ``_jit_vmapped``'s
    compile cache keys on kernel identity, so handing back the same
    closure for the same config is what lets a warm refit (or the next
    forest in a grid) skip XLA compilation entirely — a fresh closure
    per fit silently recompiled every forest. ``hist_mode="auto"`` is
    resolved to a concrete (mode, block) BEFORE the memo key, so a
    recalibration (the on-chip sweep writes one mid-process) still
    takes effect on the next fit."""
    # allow_native=False: this kernel IS the XLA path — forest.fit
    # routes native-mode fits to the host engine before reaching here
    hist_mode, hist_block = resolve_hist_config(
        d, n_bins, hist_mode, hist_block, allow_native=False,
        fractional_weights=fractional_weights,
    )
    return _forest_kernel_cached(
        d, n_bins, channels, max_depth, max_features, min_samples_split,
        min_samples_leaf, min_impurity_decrease, extra, classification,
        bootstrap, hist_mode, hist_block,
    )


@lru_cache(maxsize=64)
def _forest_kernel_cached(d, n_bins, channels, max_depth, max_features,
                          min_samples_split, min_samples_leaf,
                          min_impurity_decrease, extra, classification,
                          bootstrap, hist_mode, hist_block):
    grow = build_tree_kernel(
        n_features=d, n_bins=n_bins, channels=channels, max_depth=max_depth,
        max_features=max_features, min_samples_split=min_samples_split,
        min_samples_leaf=min_samples_leaf,
        min_impurity_decrease=min_impurity_decrease, extra=extra,
        classification=classification, hist_mode=hist_mode,
    )
    K = channels - 1 if classification else 1

    def kernel(shared, task):
        Xb, y, sw = shared["Xb"], shared["y"], shared["sw"]
        n = Xb.shape[0]
        key = jax.random.PRNGKey(task["seed"])
        _, kgrow = jax.random.split(key)
        w = sw
        if bootstrap:
            w = sw * _bootstrap_counts(task["seed"], n, sw.dtype)
        if classification:
            Ych = classification_channels(y, w, K)
        else:
            Ych = regression_channels(y, w)
        tree = grow(Xb, Ych, kgrow)
        # the seed travels with the tree: OOB masks and bootstrap draws
        # are reproducible from it (nothing O(n) is persisted)
        tree["seed"] = task["seed"]
        return tree

    # structural compile-cache key: the closure is fully determined by
    # this memo's own (fully-resolved) argument tuple; the batched_map
    # call site passes it so the jit/AOT caches survive an lru_cache
    # eviction of the closure itself
    from ..parallel import structural_key

    kernel.cache_key = structural_key(
        "forest_tree", "tree_kernel", d, n_bins, channels, max_depth,
        max_features, min_samples_split, min_samples_leaf,
        min_impurity_decrease, extra, classification, bootstrap,
        hist_mode, hist_block,
    )
    return kernel


# Two SEPARATE memos, same identity + weakref-validation scheme as the
# backend's broadcast cache (a recycled id() can never serve stale
# entries; collecting X evicts them):
#   _EDGE_MEMO: (id(X), n_bins) -> (weakref(X), quantile edges) —
#       written ONLY by _memo_edges, so it only ever holds edges that
#       are quantile_bin_edges(X) for that exact X.
#   _XB_MEMO:   (id(X), n_bins) -> (weakref(X), edges, Xb) — written
#       by _memo_apply_bins with WHATEVER edges the caller passed
#       (a warm_start refit legitimately applies inherited edges).
# Keeping them separate closes the poisoning path where a warm-start
# apply on a new X wrote its inherited edges where _memo_edges would
# later serve them as X's own quantile edges, silently changing the
# trees a subsequent fresh fit grows.
_EDGE_MEMO = {}
_XB_MEMO = {}
_BIN_MEMO_MAX = 4


def _memo_lookup(memo, X, n_bins, enabled):
    if not enabled or not isinstance(X, np.ndarray):
        return None, None
    key = (id(X), int(n_bins))
    ent = memo.get(key)
    if ent is not None:
        if ent[0]() is X:
            return key, ent
        memo.pop(key, None)
    return key, None


def _memo_store(memo, key, X, *values):
    import weakref

    memo[key] = (weakref.ref(X, lambda _r: memo.pop(key, None)), *values)
    while len(memo) > _BIN_MEMO_MAX:
        try:
            memo.pop(next(iter(memo)))
        except (KeyError, StopIteration):
            break


def _memo_edges(X, n_bins, enabled):
    key, ent = _memo_lookup(_EDGE_MEMO, X, n_bins, enabled)
    if ent is not None:
        return ent[1]
    edges = quantile_bin_edges(X, n_bins)
    if key is not None:
        _memo_store(_EDGE_MEMO, key, X, np.asarray(edges))
    return edges


def _memo_apply_bins(X, edges, n_bins, enabled):
    key, ent = _memo_lookup(_XB_MEMO, X, n_bins, enabled)
    if ent is not None and np.array_equal(ent[1], edges):
        return ent[2]
    Xb = np.asarray(apply_bins(jnp.asarray(X), jnp.asarray(edges)))
    if key is not None:
        _memo_store(_XB_MEMO, key, X, np.asarray(edges), Xb)
    return Xb


class _BaseForest(BaseEstimator):
    """Shared forest machinery; subclasses set ``_extra`` (random
    thresholds) and classification/regression via mixins.

    ``warm_start=True`` keeps previously grown trees and appends
    ``n_estimators - len(grown)`` new ones (reference ensemble.py:250-272).
    """

    _extra = False

    def __init__(self, n_estimators=100, max_depth=8, n_bins=32,
                 max_features="sqrt", min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=True, oob_score=False,
                 class_weight=None, warm_start=False, random_state=None,
                 n_jobs=None, hist_mode="auto"):
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.n_bins = n_bins
        self.max_features = max_features
        self.min_samples_split = min_samples_split
        self.min_samples_leaf = min_samples_leaf
        self.min_impurity_decrease = min_impurity_decrease
        self.bootstrap = bootstrap
        self.oob_score = oob_score
        self.class_weight = class_weight
        self.warm_start = warm_start
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.hist_mode = hist_mode

    @property
    def _classification(self):
        return isinstance(self, ClassifierMixin)

    # distributed wrappers override to route through their backend
    def _resolve_fit_backend(self):
        return LocalBackend(n_jobs=self.n_jobs), None

    def fit(self, X, y, sample_weight=None):
        X = as_dense_f32(X)
        n, d = X.shape
        sw = prepare_sample_weight(sample_weight, n)
        backend, round_size = self._resolve_fit_backend()
        # binning is a pure function of (X, n_bins); under the backend's
        # reuse_broadcast contract (mutating X after handing it over is
        # user error, as with a Spark broadcast) repeat fits on the same
        # host X skip both the quantile pass and the bin-apply transfer
        # — and the memoised Xb's stable identity is what lets the
        # broadcast cache hit on the placement below.
        reuse = getattr(backend, "reuse_broadcast", False)
        warm = self.warm_start and getattr(self, "_trees", None) is not None
        if warm:
            # existing trees' thresholds are bin ids under the original
            # edges — a warm refit must keep binning consistent
            edges = self._edges
        else:
            edges = _memo_edges(X, self.n_bins, reuse)

        if self._classification:
            y_enc, classes = encode_labels(y)
            self.classes_ = classes
            K = len(classes)
            channels = K + 1
            cw = getattr(self, "class_weight", None)
            if cw is not None:
                if cw == "balanced":
                    counts = np.bincount(y_enc, minlength=K).astype(np.float64)
                    per_class = len(y_enc) / (K * np.maximum(counts, 1))
                elif isinstance(cw, dict):
                    per_class = class_weight_vector(cw, classes)
                else:
                    raise ValueError(
                        f"Unsupported class_weight {cw!r}: use 'balanced' "
                        "or a {label: weight} dict"
                    )
                sw = sw * per_class[y_enc].astype(np.float32)
        else:
            y_enc = np.asarray(y, dtype=np.float32)
            K = 1
            channels = 4
        if self.oob_score and not self.bootstrap:
            raise ValueError("oob_score requires bootstrap=True")

        prev = getattr(self, "_trees", None) if warm else None
        n_prev = 0
        if prev is not None:
            n_prev = int(prev["feat"].shape[0])
        n_more = self.n_estimators - n_prev
        if n_more < 0:
            raise ValueError(
                f"warm_start: n_estimators={self.n_estimators} is smaller "
                f"than the {n_prev} trees already grown"
            )

        if n_more > 0:
            rng = np.random.RandomState(self.random_state)
            if n_prev:  # advance the stream past already-drawn seeds
                rng.randint(MAX_RAND_SEED, size=n_prev)
            seeds = rng.randint(MAX_RAND_SEED, size=n_more).astype(np.int32)
            Xb = _memo_apply_bins(X, edges, self.n_bins, reuse)
            mode, _ = resolve_hist_config(
                d, self.n_bins, getattr(self, "hist_mode", "auto")
            )
            # explicit opt-in that can't be honored on this host raises
            # (shared diagnosis with tree.py); the distributed-backend
            # case raises from resolve_hist_config(allow_native=False)
            # inside make_forest_tree_kernel instead
            from .native_forest import native_supported_or_raise

            use_native = (
                mode == "native"
                and isinstance(backend, LocalBackend)
                and native_supported_or_raise(
                    self.n_bins,
                    getattr(self, "hist_mode", "auto") == "native",
                )
            )
            if use_native:
                new_trees = self._fit_native(Xb, y_enc, sw, seeds, d)
            else:
                kernel = make_forest_tree_kernel(
                    d=d, n_bins=self.n_bins, channels=channels,
                    max_depth=self.max_depth,
                    max_features=resolve_max_features(self.max_features, d),
                    min_samples_split=self.min_samples_split,
                    min_samples_leaf=self.min_samples_leaf,
                    min_impurity_decrease=self.min_impurity_decrease,
                    extra=self._extra, classification=self._classification,
                    bootstrap=self.bootstrap,
                    hist_mode=getattr(self, "hist_mode", "auto"),
                    # sw already folds class_weight in, so one integral
                    # check covers both fractional sources; only a
                    # calibrated matmul_sib 'auto' pick consults this
                    fractional_weights=bool(
                        np.any(np.asarray(sw) != np.rint(sw))
                    ),
                )
                shared = {
                    "Xb": Xb,  # host-staged: batched_map places (and can
                    "y": np.asarray(y_enc),  # cache) the sharded replicas
                    "sw": np.asarray(sw),
                }
                new_trees = backend.batched_map(
                    kernel, {"seed": seeds}, shared, round_size=round_size,
                    cache_key=kernel.cache_key,
                )
            if prev is not None:
                self._trees = jax.tree_util.tree_map(
                    lambda a, b: np.concatenate([a, b], axis=0), prev, new_trees
                )
            else:
                self._trees = new_trees
        self._edges = edges
        self.n_features_in_ = d
        if self.oob_score:
            self._compute_oob(X, y_enc)
        return self

    def _fit_native(self, Xb, y_enc, sw, seeds, d):
        """Grow trees with the host engine (models/native_forest.py):
        same histogram algorithm, per-level accumulation in the
        multithreaded C kernel instead of an XLA scatter, zero compile
        time. Bootstrap weights reproduce the device path's
        ``_bootstrap_counts`` draw exactly — OOB scoring regenerates
        masks from the stored seeds through that one function, so both
        engines must agree on what each seed drew."""
        from .native_forest import grow_forest_native

        n = Xb.shape[0]
        sw = np.asarray(sw, np.float32)
        bootstrap = self.bootstrap

        def weights(t0, t1):
            # per-chunk: a 500-tree x 1M-row fit must not materialise
            # the full (T, n) weight matrix the engine's budget
            # chunking exists to avoid
            if bootstrap:
                counts = np.asarray(
                    _bootstrap_counts_batch(n)(jnp.asarray(seeds[t0:t1]))
                )
                return sw[None, :] * counts
            return np.broadcast_to(sw, (t1 - t0, n)).copy()

        n_jobs = self.n_jobs
        # joblib convention: None -> default, negative -> all cores
        # (LocalBackend treats the same attribute this way; the C
        # kernel would clamp a raw -1 to ONE thread)
        n_threads = None if n_jobs is None or n_jobs < 1 else int(n_jobs)
        return grow_forest_native(
            Xb, y_enc, weights, seeds,
            n_bins=self.n_bins, max_depth=self.max_depth,
            max_features=resolve_max_features(self.max_features, d),
            min_samples_split=self.min_samples_split,
            min_samples_leaf=self.min_samples_leaf,
            min_impurity_decrease=self.min_impurity_decrease,
            extra=self._extra, classification=self._classification,
            n_classes=len(getattr(self, "classes_", ())) or 1,
            n_threads=n_threads,
        )

    def _compute_oob(self, X, y_enc):
        """Real out-of-bag scoring (the reference stubbed this,
        ensemble.py:338-340): each sample is scored by the trees whose
        bootstrap missed it. The per-tree masks are consumed here and
        stripped from the fitted trees — they index the training rows
        and must not survive into predict/pickle/warm-start."""
        nodes = self._native_walk(X, "apply")
        if nodes is not None:
            # host path: per-tree leaf gather + mask, no XLA walker
            # compile; ONLY the bootstrap-draw regeneration stays on
            # jax (PRNG parity with the device path is the contract)
            n, T = nodes.shape
            leaf = np.asarray(self._trees["leaf"])  # (T, N, K)
            seeds = np.asarray(self._trees["seed"])
            num = np.zeros((n, leaf.shape[2]), np.float32)
            cnt = np.zeros(n, np.float32)
            # seeds in chunks: the counts matrix stays (16, n)-sized,
            # honouring the same no-(T, n)-materialisation contract as
            # _fit_native's weights() callback
            ch = 16
            for t0 in range(0, T, ch):
                counts = np.asarray(_bootstrap_counts_batch(n)(
                    jnp.asarray(seeds[t0:t0 + ch])
                ))
                for i in range(counts.shape[0]):
                    t = t0 + i
                    m = counts[i] == 0
                    num[m] += leaf[t, nodes[m, t]]
                    cnt += m
            agg = num / np.maximum(cnt, 1.0)[:, None]
        else:
            trees = jax.tree_util.tree_map(jnp.asarray, self._trees)
            Xb = apply_bins(jnp.asarray(X), jnp.asarray(self._edges))
            oob_agg = _oob_aggregator(self.max_depth)
            agg, cnt = jax.device_get(
                oob_agg(trees, trees["seed"], Xb)
            )
        covered = np.asarray(cnt) > 0
        if not covered.all():
            import warnings

            warnings.warn(
                "Some samples were in-bag for every tree; OOB estimates "
                "for them are undefined and excluded from oob_score_."
            )
        if self._classification:
            self.oob_decision_function_ = agg
            pred = np.argmax(agg, axis=1)
            self.oob_score_ = float(
                np.mean(pred[covered] == np.asarray(y_enc)[covered])
            ) if covered.any() else float("nan")
        else:
            self.oob_prediction_ = agg[:, 0]
            yv = np.asarray(y_enc)[covered]
            pv = agg[covered, 0]
            ss_res = float(np.sum((yv - pv) ** 2))
            ss_tot = float(np.sum((yv - yv.mean()) ** 2))
            self.oob_score_ = (
                1.0 - ss_res / ss_tot if ss_tot > 0 else float("nan")
            )

    # ------------------------------------------------------------------
    def _check_fitted(self):
        if not hasattr(self, "_trees"):
            raise AttributeError(
                f"This {type(self).__name__} instance is not fitted yet."
            )

    def _native_walk(self, X, mode):
        """Host C walker (native/hist_tree.c::forest_walk): on a
        CPU-backed process the predict side, like the native fit,
        needs no XLA compile at all. Returns None to fall through to
        the XLA walker (accelerator platforms, C kernel unavailable)."""
        if jax.default_backend() != "cpu":
            return None
        from ..native import forest_walk_native, hist_tree_available
        from ..ops.binning import apply_bins_np

        # availability first (binning a big X only to discard it on a
        # compiler-less host would tax every predict); the width check
        # falls through so the XLA path raises its usual loud shape
        # error instead of the C walker reading past Xb
        if not hist_tree_available() or X.shape[1] != len(self._edges):
            return None
        n_jobs = getattr(self, "n_jobs", None)
        return forest_walk_native(
            apply_bins_np(X, self._edges), self._trees, self.max_depth,
            mode=mode,
            n_threads=None if n_jobs is None or n_jobs < 1 else int(n_jobs),
        )

    def _forest_values(self, X):
        """Mean over trees of per-tree leaf outputs → (n, K_out)."""
        self._check_fitted()
        X = as_dense_f32(X)
        out = self._native_walk(X, "predict")
        if out is not None:
            return out
        fn = _forest_walker(self.max_depth, "predict")
        trees = jax.tree_util.tree_map(jnp.asarray, self._trees)
        Xb = apply_bins(jnp.asarray(X), jnp.asarray(self._edges))
        return np.asarray(fn(trees, Xb))

    def apply(self, X):
        """(n, n_estimators) leaf ids — sklearn ``forest.apply``."""
        self._check_fitted()
        X = as_dense_f32(X)
        out = self._native_walk(X, "apply")
        if out is not None:
            return out
        fn = _forest_walker(self.max_depth, "apply")
        trees = jax.tree_util.tree_map(jnp.asarray, self._trees)
        Xb = apply_bins(jnp.asarray(X), jnp.asarray(self._edges))
        return np.asarray(fn(trees, Xb))

    @property
    def feature_importances_(self):
        self._check_fitted()
        T = self._trees["feat"].shape[0]
        imps = np.stack([
            feature_importances_from_tree(
                self._trees["feat"][t], self._trees["gain"][t],
                self.n_features_in_,
            )
            for t in range(T)
        ])
        return imps.mean(axis=0)

    @property
    def estimators_(self):
        """Per-tree estimator views (reference parity: fitted trees are
        collected into ``estimators_``, ensemble.py:325)."""
        self._check_fitted()
        cls = (
            DecisionTreeClassifier if self._classification
            else DecisionTreeRegressor
        )
        out = []
        T = self._trees["feat"].shape[0]
        for t in range(T):
            est = cls(
                max_depth=self.max_depth, n_bins=self.n_bins,
                max_features=self.max_features,
                min_samples_split=self.min_samples_split,
                min_samples_leaf=self.min_samples_leaf,
                min_impurity_decrease=self.min_impurity_decrease,
                splitter="random" if self._extra else "best",
            )
            est._params = jax.tree_util.tree_map(
                lambda a: np.asarray(a[t]), self._trees
            )
            est._params["edges"] = np.asarray(self._edges)
            est._meta = {"n_features": self.n_features_in_}
            est.n_features_in_ = self.n_features_in_
            if self._classification:
                est.classes_ = self.classes_
                est._meta.update(
                    classes=self.classes_, n_classes=len(self.classes_)
                )
            out.append(est)
        return out


class _ForestClassifierMixin(ClassifierMixin):
    def predict_proba(self, X):
        return self._forest_values(X)

    def predict_log_proba(self, X):
        return np.log(np.clip(self.predict_proba(X), 1e-15, None))

    def predict(self, X):
        return self.classes_[np.argmax(self.predict_proba(X), axis=1)]


class _ForestRegressorMixin(RegressorMixin):
    def predict(self, X):
        out = self._forest_values(X)
        return out[:, 0] if out.ndim == 2 and out.shape[1] == 1 else out


class RandomForestClassifier(_BaseForest, _ForestClassifierMixin):
    """Histogram random forest (bagged best-split trees)."""


class RandomForestRegressor(_BaseForest, _ForestRegressorMixin):
    def __init__(self, n_estimators=100, max_depth=8, n_bins=32,
                 max_features=1.0, min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=True, oob_score=False,
                 warm_start=False, random_state=None, n_jobs=None,
                 hist_mode="auto"):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth, n_bins=n_bins,
            max_features=max_features, min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs, hist_mode=hist_mode,
        )


class ExtraTreesClassifier(_BaseForest, _ForestClassifierMixin):
    """Extremely randomised trees: random per-(node, feature) thresholds,
    no bootstrap by default (sklearn semantics)."""

    _extra = True

    def __init__(self, n_estimators=100, max_depth=8, n_bins=32,
                 max_features="sqrt", min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=False, oob_score=False,
                 class_weight=None, warm_start=False, random_state=None,
                 n_jobs=None, hist_mode="auto"):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth, n_bins=n_bins,
            max_features=max_features, min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, class_weight=class_weight,
            warm_start=warm_start, random_state=random_state, n_jobs=n_jobs,
            hist_mode=hist_mode,
        )


class ExtraTreesRegressor(_BaseForest, _ForestRegressorMixin):
    _extra = True

    def __init__(self, n_estimators=100, max_depth=8, n_bins=32,
                 max_features=1.0, min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=False, oob_score=False,
                 warm_start=False, random_state=None, n_jobs=None,
                 hist_mode="auto"):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth, n_bins=n_bins,
            max_features=max_features, min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs, hist_mode=hist_mode,
        )


class RandomTreesEmbedding(_BaseForest, TransformerMixin):
    """Unsupervised leaf-index embedding (reference ensemble.py:619-716):
    extra-random regression trees fit on uniform random targets; transform
    one-hot-encodes each sample's leaf per tree."""

    _extra = True
    _estimator_type = None

    def __init__(self, n_estimators=100, max_depth=5, n_bins=32,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, sparse_output=True,
                 warm_start=False, random_state=None, n_jobs=None,
                 hist_mode="auto"):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth, n_bins=n_bins,
            max_features=1.0, min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=False,
            warm_start=warm_start, random_state=random_state, n_jobs=n_jobs,
            hist_mode=hist_mode,
        )
        self.sparse_output = sparse_output

    @property
    def _classification(self):
        return False

    def fit(self, X, y=None, sample_weight=None):
        # uniform random targets (reference ensemble.py:704-706)
        rng = np.random.RandomState(self.random_state)
        y_rand = rng.uniform(size=np.asarray(X).shape[0]).astype(np.float32)
        super().fit(X, y_rand, sample_weight=sample_weight)
        # fit-time one-hot layout: one block of 2^(D+1)-1 slots per tree
        self._n_nodes = n_tree_nodes(self.max_depth)
        return self

    def fit_transform(self, X, y=None, sample_weight=None):
        return self.fit(X, y, sample_weight).transform(X)

    def transform(self, X):
        self._check_fitted()
        leaves = self.apply(X)  # (n, T)
        n, T = leaves.shape
        N = self._n_nodes
        cols = (leaves + np.arange(T)[None, :] * N).ravel()
        rows = np.repeat(np.arange(n), T)
        from scipy import sparse

        out = sparse.csr_matrix(
            (np.ones(n * T, dtype=np.float32), (rows, cols)),
            shape=(n, T * N),
        )
        return out if self.sparse_output else np.asarray(out.todense())
