"""
Forest kernels (placeholder — implemented in the ensemble milestone).
"""

from ..base import BaseEstimator, ClassifierMixin, RegressorMixin, TransformerMixin

__all__ = [
    "RandomForestClassifier",
    "RandomForestRegressor",
    "ExtraTreesClassifier",
    "ExtraTreesRegressor",
    "RandomTreesEmbedding",
]


class _ForestStub(BaseEstimator):
    def fit(self, X, y=None, sample_weight=None):
        raise NotImplementedError("forest kernels land in the ensemble milestone")


class RandomForestClassifier(_ForestStub, ClassifierMixin):
    pass


class RandomForestRegressor(_ForestStub, RegressorMixin):
    pass


class ExtraTreesClassifier(_ForestStub, ClassifierMixin):
    pass


class ExtraTreesRegressor(_ForestStub, RegressorMixin):
    pass


class RandomTreesEmbedding(_ForestStub, TransformerMixin):
    pass
