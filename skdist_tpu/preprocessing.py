"""
Pipeline-compatible preprocessing transformers.

Counterparts of the reference's ``skdist/preprocessing.py:21-339``:
column selection, dtype casting, null imputation, dense/sparse
conversion, pipeline-safe label encoding, memory-efficient univariate
selection, chunked hashing vectorisation, and multi-hot encoding. These
are host-side (featurisation feeds the device-resident matrices the
JAX kernels consume); they exist so Encoderizer default pipelines and
user pipelines from sk-dist port over unchanged.
"""

import warnings

import numpy as np
import pandas as pd
from scipy import sparse
from sklearn import feature_selection
from sklearn.feature_extraction.text import HashingVectorizer
from sklearn.preprocessing import LabelEncoder, MultiLabelBinarizer, normalize

from .base import BaseEstimator, TransformerMixin

__all__ = [
    "SelectField",
    "FeatureCast",
    "ImputeNull",
    "DenseTransformer",
    "SparseTransformer",
    "LabelEncoderPipe",
    "SelectorMem",
    "HashingVectorizerChunked",
    "FastHashingVectorizer",
    "MultihotEncoder",
    "TruncatedSVDTransformer",
]

def _check_docs_iterable(X):
    if isinstance(X, str):
        raise ValueError(
            "Iterable over raw text documents expected, "
            "string object received."
        )


def _doc_chunks(X, chunksize):
    """Split a document list into transform chunks (shared by the
    chunked vectorizers)."""
    if chunksize is None or len(X) <= chunksize:
        return [X]
    return [X[i:i + chunksize] for i in range(0, len(X), chunksize)]


_SELECTOR_LOOKUP = {
    "fpr": feature_selection.SelectFpr,
    "fdr": feature_selection.SelectFdr,
    "kbest": feature_selection.SelectKBest,
    "percentile": feature_selection.SelectPercentile,
    "fwe": feature_selection.SelectFwe,
}


class SelectField(BaseEstimator, TransformerMixin):
    """Select columns from a pandas DataFrame → numpy values
    (reference preprocessing.py:77-94)."""

    def __init__(self, cols=None, single_dimension=False):
        self.cols = cols
        self.single_dimension = single_dimension

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        if self.cols is None:
            return X.values
        if len(self.cols) == 1 and self.single_dimension:
            return X[self.cols[0]].values
        return X[list(self.cols)].values


class FeatureCast(BaseEstimator, TransformerMixin):
    """Cast array dtype (reference preprocessing.py:143-154)."""

    def __init__(self, cast_type=None):
        self.cast_type = cast_type

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        if self.cast_type is None:
            return X
        return X.astype(self.cast_type)


class ImputeNull(BaseEstimator, TransformerMixin):
    """Replace nulls (per ``pd.isnull``) with a constant (reference
    preprocessing.py:175-186)."""

    def __init__(self, impute_val=None):
        self.impute_val = impute_val

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        if self.impute_val is None:
            return X
        X = np.asarray(X, dtype=object) if not isinstance(X, np.ndarray) else X.copy()
        X[pd.isnull(X)] = self.impute_val
        return X


class DenseTransformer(BaseEstimator, TransformerMixin):
    """Densify sparse input (reference preprocessing.py:105-112)."""

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        return np.asarray(X.todense()) if sparse.issparse(X) else X


class SparseTransformer(BaseEstimator, TransformerMixin):
    """Sparsify dense input (reference preprocessing.py:114-124)."""

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        return X if sparse.issparse(X) else sparse.csr_matrix(X)


class LabelEncoderPipe(BaseEstimator, TransformerMixin):
    """Pipeline-safe LabelEncoder producing a column vector (reference
    preprocessing.py:189-203)."""

    def fit(self, X, y=None):
        self.le_ = LabelEncoder().fit(X)
        return self

    def transform(self, X, y=None):
        return self.le_.transform(X).reshape(-1, 1)


class SelectorMem(BaseEstimator, TransformerMixin):
    """Univariate feature selection storing only the cheaper of
    bool-mask vs int-indices (reference preprocessing.py:206-261)."""

    def __init__(self, selector="fpr",
                 score_func=feature_selection.f_classif, threshold=0.05):
        self.selector = selector
        self.score_func = score_func
        self.threshold = threshold

    def fit(self, X, y=None):
        sel = _SELECTOR_LOOKUP[self.selector.lower()](
            score_func=self.score_func, **self._threshold_kw()
        )
        sel.fit(X, y)
        mask_idx = sel.get_support(indices=True)
        mask_bool = sel.get_support(indices=False)
        self.mask = (
            mask_idx
            if np.asarray(mask_bool).nbytes > np.asarray(mask_idx).nbytes
            else mask_bool
        )
        return self

    def _threshold_kw(self):
        name = self.selector.lower()
        if name == "kbest":
            return {"k": self.threshold}
        if name == "percentile":
            return {"percentile": self.threshold}
        return {"alpha": self.threshold}

    def transform(self, X, y=None):
        return X[:, self.mask]


class HashingVectorizerChunked(HashingVectorizer):
    """HashingVectorizer with chunked transform to bound peak memory
    (reference preprocessing.py:264-310)."""

    def __init__(self, chunksize=100000, n_features=2**20, norm="l2",
                 binary=False, alternate_sign=True, analyzer="word",
                 ngram_range=(1, 1), lowercase=True, stop_words=None,
                 token_pattern=r"(?u)\b\w\w+\b", strip_accents=None,
                 decode_error="strict", input="content", encoding="utf-8",
                 preprocessor=None, tokenizer=None, dtype=np.float64):
        self.chunksize = chunksize
        HashingVectorizer.__init__(
            self, n_features=n_features, norm=norm, binary=binary,
            alternate_sign=alternate_sign, analyzer=analyzer,
            ngram_range=ngram_range, lowercase=lowercase,
            stop_words=stop_words, token_pattern=token_pattern,
            strip_accents=strip_accents, decode_error=decode_error,
            input=input, encoding=encoding, preprocessor=preprocessor,
            tokenizer=tokenizer, dtype=dtype,
        )

    def transform(self, X):
        _check_docs_iterable(X)
        chunks = _doc_chunks(X, self.chunksize)
        if len(chunks) == 1:
            return HashingVectorizer.transform(self, chunks[0])
        return sparse.vstack([
            HashingVectorizer.transform(self, c) for c in chunks
        ]).tocsr()


class FastHashingVectorizer(BaseEstimator, TransformerMixin):
    """Text hashing through the native C kernel
    (``skdist_tpu/native/fasthash.c``), with a byte-identical
    pure-Python fallback when no compiler is available.

    The framework's own replacement for the Cython featurisation the
    reference borrowed from sklearn: word or char_wb n-grams, FNV-1a
    hashed into ``n_features`` buckets, optional binary counts and
    L1/L2 row normalisation. Stateless (fit is a no-op), chunked
    transform bounds peak memory like ``HashingVectorizerChunked``.
    """

    def __init__(self, n_features=2**12, ngram_range=(1, 1),
                 analyzer="word", lowercase=True, binary=False, norm="l2",
                 chunksize=100000, force_python=False):
        self.n_features = n_features
        self.ngram_range = ngram_range
        self.analyzer = analyzer
        self.lowercase = lowercase
        self.binary = binary
        self.norm = norm
        self.chunksize = chunksize
        self.force_python = force_python

    def fit(self, X, y=None):
        return self

    def transform(self, X, y=None):
        from .native import hash_documents

        _check_docs_iterable(X)
        X = list(X)
        chunks = _doc_chunks(X, self.chunksize)
        outs = [
            hash_documents(
                c, n_features=self.n_features, ngram_range=self.ngram_range,
                analyzer=self.analyzer, lowercase=self.lowercase,
                binary=self.binary, force_python=self.force_python,
            )
            for c in chunks
        ]
        out = outs[0] if len(outs) == 1 else sparse.vstack(outs).tocsr()
        if self.norm is not None and out.shape[0] > 0:
            out = normalize(out, norm=self.norm, copy=False)
        return out


class MultihotEncoder(BaseEstimator, TransformerMixin):
    """Pipeline-safe MultiLabelBinarizer ignoring unseen labels
    (reference preprocessing.py:313-339)."""

    def __init__(self, sparse_output=False):
        self.sparse_output = sparse_output

    def fit(self, X, y=None):
        self.transformer_ = MultiLabelBinarizer().fit(X)
        return self

    def transform(self, X, y=None):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            X_t = self.transformer_.transform(X)
        return sparse.csr_matrix(X_t) if self.sparse_output else X_t

    @property
    def classes_(self):
        return self.transformer_.classes_


class TruncatedSVDTransformer(BaseEstimator, TransformerMixin):
    """Randomized truncated SVD (Halko-Martinsson-Tropp) for feature
    reduction ahead of the device dense path.

    The densify guardrail (``skdist_tpu/sparse.py::_check_densify_budget``)
    names this transformer as a remedy for hashed-text widths too wide
    to densify (packable sparse input now routes to the packed fit
    plane first): ``X`` (sparse or dense, width ``d``) is projected
    onto its top ``n_components`` right-singular directions, and the
    (n, n_components) output is narrow enough for the MXU kernels.

    TPU-first split of the work: the randomized range finder's matmuls
    against the FULL-width X stay on host — for the guardrail's target
    case X is sparse and ``X @ G`` rides scipy's CSR kernels, while the
    dense X that can't exist on host is exactly the case this avoids —
    and every post-projection step is small. Dense inputs route the
    same matmuls through jax so they land on the accelerator. No
    centering is applied (sklearn ``TruncatedSVD`` semantics, which is
    what keeps X sparse).

    Mirrors sklearn's fitted surface: ``components_``,
    ``singular_values_``, ``explained_variance_``,
    ``explained_variance_ratio_``.
    """

    def __init__(self, n_components=128, n_iter=4, n_oversamples=10,
                 random_state=0):
        self.n_components = n_components
        self.n_iter = n_iter
        self.n_oversamples = n_oversamples
        self.random_state = random_state

    def _matmul(self, A, B):
        """A @ B with A possibly scipy-sparse; dense ndarrays ride jax
        (device when available)."""
        if sparse.issparse(A):
            return np.asarray(A @ B)
        import jax.numpy as jnp

        return np.asarray(jnp.asarray(A) @ jnp.asarray(B))

    def fit(self, X, y=None):
        n, d = X.shape
        k = int(self.n_components)
        if not 1 <= k <= min(n, d):
            raise ValueError(
                f"n_components={k} must be in [1, min(n, d)="
                f"{min(n, d)}]"
            )
        sketch = min(k + int(self.n_oversamples), min(n, d))
        rng = np.random.RandomState(self.random_state)
        G = rng.normal(size=(d, sketch)).astype(np.float32)
        Y = self._matmul(X, G)
        # power iterations with QR re-orthonormalisation each half-step
        # (f32 range-finding loses the small singular directions
        # without it)
        XT = X.T.tocsr() if sparse.issparse(X) else X.T
        for _ in range(int(self.n_iter)):
            Q, _ = np.linalg.qr(Y)
            Z = self._matmul(XT, Q)
            Q, _ = np.linalg.qr(Z)
            Y = self._matmul(X, Q)
        Q, _ = np.linalg.qr(Y)
        B = self._matmul(XT, Q).T  # (sketch, d)
        _, s, Vt = np.linalg.svd(B, full_matrices=False)
        self.components_ = np.ascontiguousarray(Vt[:k])
        self.singular_values_ = s[:k]
        self.n_features_in_ = d
        # sklearn parity: variance of the projected columns over the
        # TRAINING rows, and its share of total feature variance
        Xt = self._matmul(X, self.components_.T)
        self.explained_variance_ = Xt.var(axis=0)
        if sparse.issparse(X):
            mean = np.asarray(X.mean(axis=0)).ravel()
            sq = np.asarray(X.multiply(X).mean(axis=0)).ravel()
            full_var = float((sq - mean ** 2).sum())
        else:
            full_var = float(np.asarray(X).var(axis=0).sum())
        self.explained_variance_ratio_ = (
            self.explained_variance_ / full_var if full_var > 0
            else np.zeros_like(self.explained_variance_)
        )
        return self

    def transform(self, X, y=None):
        if X.shape[1] != self.n_features_in_:
            raise ValueError(
                f"X has {X.shape[1]} features; TruncatedSVDTransformer "
                f"was fitted with {self.n_features_in_}"
            )
        return self._matmul(X, self.components_.T)
