"""
Distributed meta-estimators — the core product surface, mirroring the
reference's ``skdist/distribute/__init__.py``.
"""

__all__ = [
    "search",
    "multiclass",
    "ensemble",
    "eliminate",
    "encoder",
    "predict",
]
