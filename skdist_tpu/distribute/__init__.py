"""
Distributed meta-estimators — the core product surface, mirroring the
reference's ``skdist/distribute/__init__.py``.
"""

# extended as subsystems land (multiclass, ensemble, eliminate,
# encoder, predict follow the reference inventory)
__all__ = ["search"]
