"""
Large-scale batch prediction (reference ``/root/reference/skdist/
distribute/predict.py:59-179``).

The reference wraps a fitted model's ``predict``/``predict_proba`` in a
pyarrow-vectorised pandas UDF so Spark streams DataFrame partitions
through it. The TPU-native analogue has two layers:

- :func:`get_prediction_udf` — API-compatible factory: returns a
  callable over pandas Series columns (the reference's three feature
  layouts: 'numpy' column-stack, 'pandas' named frame, 'text' single
  column — predict.py:59-71) producing a pandas Series of predictions
  (or list-valued Series of probabilities).
- :func:`batch_predict` — the throughput path: rows are cut into
  fixed-size blocks that ride the mapped task axis across the TPU mesh
  (``backend.batched_map``), so inference over millions of rows is a
  handful of sharded XLA dispatches with no per-row Python. Host
  (sklearn) models fall back to thread-chunked predict.
"""

import numpy as np
import pandas as pd

from ..parallel import resolve_backend

__all__ = ["get_prediction_udf", "batch_predict", "device_predict_plan"]


class DevicePredictPlan:
    """The ONE construction of a fitted JAX estimator's block-inference
    program, shared by every consumer: ``batch_predict``'s offline row
    blocks, the sparse CSR path, and ``skdist_tpu.serve``'s
    micro-batcher. Holding the memoised decision/proba kernel, the
    host-staged parameters, and the structural cache key in one object
    guarantees online and offline dispatches of matching shapes resolve
    to the SAME compiled executable (bitwise-identical outputs), and
    lets long-lived callers (the prediction UDF, the serving registry)
    stage parameters once instead of per call.
    """

    __slots__ = ("model", "method", "which", "kernel", "static",
                 "meta_sig", "cls", "params", "serve_dtype")

    def block_kernel(self):
        """``(shared, task) -> {'out': scores}`` over a dense row block
        — the kernel ``batched_map``/``BatchedPlan`` vmaps on the task
        axis."""
        kernel = self.kernel

        def bk(shared, task):
            return {"out": kernel(shared["params"], task["X"])}

        return bk

    def cache_key(self):
        from ..parallel import structural_key

        return structural_key(
            "predict", self.cls, self.which, self.static, self.meta_sig,
            self.serve_dtype,
        )

    def postprocess(self, out):
        """Raw kernel scores → the method's user-facing output
        (classifier label mapping for ``predict``)."""
        return _postprocess_predict(self.model, out, self.method)

    @property
    def n_features(self):
        return self.model._meta["n_features"]

    @property
    def out_width(self):
        """Estimated trailing width of the kernel output (for memory
        capping): class count for classifiers, else 1."""
        classes = getattr(self.model, "classes_", None)
        return len(classes) if classes is not None else 1


def device_predict_plan(model, method="predict", serve_dtype="float32"):
    """Build the device block-kernel plan for a fitted JAX estimator,
    or None when the model exposes no device kernels (host models take
    thread-chunked fallbacks). Parameters are staged host-side ONCE
    here; backend placement (and the broadcast-reuse cache) happens at
    dispatch.

    ``serve_dtype`` selects the stored-parameter precision tier
    (``serve.quantize``): bf16/int8 plans stage the QUANTIZED tree —
    that is what backend placement puts in HBM — and wrap the
    decision/proba kernel with the in-program dequant, which XLA fuses
    into the matmul's operand read (f32 accumulation throughout). The
    tier is part of the structural cache key, so each dtype compiles
    (and AOT-caches) its own program family and a prewarmed dtype
    serves with zero steady-state compiles like any other entry.
    """
    if not hasattr(model, "_params") or not hasattr(model, "_meta"):
        return None
    import jax

    from ..models.linear import _freeze, _meta_signature, get_kernel
    from ..serve.quantize import dequantize_params, quantize_params

    which = "proba" if method == "predict_proba" else "decision"
    try:
        static = _freeze(model._static_config(model._meta))
        kernel = get_kernel(type(model), which, model._meta, static)
    except AttributeError:
        return None
    plan = DevicePredictPlan()
    plan.model = model
    plan.method = method
    plan.which = which
    plan.static = static
    plan.meta_sig = _meta_signature(model._meta)
    plan.cls = type(model)
    plan.serve_dtype = serve_dtype
    params = jax.tree_util.tree_map(np.asarray, model._params)
    if serve_dtype == "float32":
        plan.kernel = kernel
        plan.params = params
    else:
        plan.params = quantize_params(params, serve_dtype)

        def quantized_kernel(qparams, X, _base=kernel,
                             _dtype=serve_dtype):
            return _base(dequantize_params(qparams, _dtype), X)

        plan.kernel = quantized_kernel
    return plan


def _get_vals(cols, feature_type, names):
    """Assemble feature matrix from column Series (reference
    predict.py:59-71)."""
    if feature_type == "numpy":
        return np.column_stack([np.asarray(c) for c in cols])
    if feature_type == "pandas":
        if names is None:
            raise ValueError("feature_type='pandas' requires names")
        return pd.DataFrame(
            {name: np.asarray(c) for name, c in zip(names, cols)}
        )[list(names)]
    if feature_type == "text":
        if len(cols) != 1:
            raise ValueError("feature_type='text' expects exactly one column")
        return np.asarray(cols[0])
    raise ValueError(f"Unknown feature_type: {feature_type!r}")


def get_prediction_udf(model, method="predict", feature_type="numpy",
                       names=None, backend=None, batch_size=None):
    """Build a columnar prediction function (reference predict.py:74-179).

    Returns ``predict_func(*cols) -> pd.Series``; probabilities come
    back as a Series of lists (the reference's Array(Double) UDF
    return type).
    """
    if method not in ("predict", "predict_proba"):
        raise ValueError("method must be 'predict' or 'predict_proba'")
    if not hasattr(model, method):
        raise ValueError(f"model has no {method} method")
    return _PredictionUDF(model, method, feature_type, names, backend,
                          batch_size)


class _PredictionUDF:
    """The callable ``get_prediction_udf`` returns.

    A class (not a closure) for two contracts that pull apart:

    - **hot path**: the resolved backend and the
      :func:`device_predict_plan` (staged params, memoised kernel) are
      built ONCE per process and reused across calls — the UDF is
      invoked once per partition/flush, and re-resolving per call was
      pure overhead;
    - **shippability**: like the reference's pandas UDF, the object
      must pickle to ride to executors. Live runtime handles cannot
      (``TaskBackend.__reduce__`` refuses by design), so
      ``__getstate__`` drops the resolved runtime and the destination
      process lazily re-resolves on first call. Only the user's raw
      ``backend`` argument is carried — pass None/'tpu'/'local' (not a
      live instance) for a picklable UDF, exactly as before.
    """

    def __init__(self, model, method, feature_type, names, backend,
                 batch_size):
        self.model = model
        self.method = method
        self.feature_type = feature_type
        self.names = names
        self.backend = backend
        self.batch_size = batch_size
        self._runtime = None

    def _ensure_runtime(self):
        # the cached plan snapshots the model's fitted params; a REFIT
        # replaces model._params with a new object, so key the cache on
        # that identity — a refit model must never be served through
        # the pre-refit plan (stale coefficients, possibly stale width)
        params = getattr(self.model, "_params", None)
        runtime = self._runtime
        if runtime is None or runtime[2] is not params:
            runtime = self._runtime = (
                resolve_backend(self.backend),
                device_predict_plan(self.model, self.method),
                params,
            )
        return runtime

    def __call__(self, *cols):
        backend, plan, _ = self._ensure_runtime()
        X = _get_vals(cols, self.feature_type, self.names)
        out = batch_predict(
            self.model, X, method=self.method, backend=backend,
            batch_size=self.batch_size, _plan=plan,
        )
        if self.method == "predict_proba":
            # pinned output contract (the reference's Array(Double) UDF
            # return type): one list-valued row per input row, columns
            # in model.classes_ order, float values
            return pd.Series(list(np.asarray(out)))
        return pd.Series(np.asarray(out))

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_runtime"] = None
        return state


def batch_predict(model, X, method="predict", backend=None,
                  batch_size=None, _plan=None):
    """Predict over X in device-sharded row blocks.

    JAX estimators (anything exposing the batched-kernel contract) run
    their decision/proba kernel with row blocks on the mapped axis of
    the mesh; other models run thread-chunked on host. ``_plan`` lets
    long-lived callers (the prediction UDF, the serving engine) pass a
    pre-built :func:`device_predict_plan` so params staging is not
    repeated per call.
    """
    backend = resolve_backend(backend)
    if _plan is None:
        _plan = device_predict_plan(model, method)

    from ..data import is_chunked

    if is_chunked(X):
        return _batch_predict_chunked(model, X, method, backend, _plan)

    fn = getattr(model, method)
    n = X.shape[0] if hasattr(X, "shape") else len(X)
    if batch_size is None:
        batch_size = _default_batch_size(n, backend, _plan)

    if _is_sparse_2d(X):
        device_out = _try_device_predict_sparse(
            model, X, method, backend, batch_size, plan=_plan
        )
        if device_out is not None:
            return device_out

    sparse_groups = _sparse_row_groups(X, n)
    if sparse_groups is not None:
        # tall-sparse input headed for a HOST model whose densified
        # whole would blow the budget: the full dense matrix can never
        # exist, but each row group's can — stream groups through the
        # normal path and concatenate. Group-local densification stays
        # under the budget by construction, so as_dense_f32's guardrail
        # never fires here.
        X = X.tocsr()  # coo & friends don't support row slicing
        outs = [
            batch_predict(model, X[i:j], method=method, backend=backend,
                          batch_size=batch_size, _plan=_plan)
            for i, j in sparse_groups
        ]
        return np.concatenate(outs, axis=0)

    device_out = _try_device_predict(
        model, X, method, backend, batch_size, plan=_plan
    )
    if device_out is not None:
        return device_out

    if n <= batch_size:
        return np.asarray(fn(X))
    chunks = [
        (X.iloc[i:i + batch_size] if hasattr(X, "iloc")
         else X[i:i + batch_size])
        for i in range(0, n, batch_size)
    ]
    outs = backend.run_tasks(lambda c: np.asarray(fn(c)), chunks)
    return np.concatenate(outs, axis=0)


#: historical staging ceiling — now only the UPPER clamp of the
#: HBM-derived default block size (and the CPU fallback, where the
#: device reports no memory stats)
_MAX_DEFAULT_BATCH = 1 << 18


def _default_batch_size(n, backend, plan):
    """Default rows per predict block: derived from the backend's free
    device memory (``hbm_round_cap`` billed per ROW — argument + output
    bytes), clamped at the historical ``1 << 18`` ceiling, so
    wide-feature dense blocks can no longer overshoot HBM just because
    the old fixed constant assumed narrow rows. CPU backends (no
    memory stats) keep the historical ceiling."""
    cap = None
    if plan is not None:
        bytes_per_row = 4 * (int(plan.n_features) + int(plan.out_width))
        cap = backend.hbm_round_cap(bytes_per_row)
    size = _MAX_DEFAULT_BATCH if cap is None else min(
        _MAX_DEFAULT_BATCH, int(cap)
    )
    return max(1, min(n, size))


def _batch_predict_chunked(model, dataset, method, backend, plan):
    """Stream a ChunkedDataset through the model's block-inference
    program: blocks are read + device-placed one ahead of the dispatch
    (``BlockFeeder``), every dispatch is the SAME compiled executable a
    resident block of this shape runs (``DevicePredictPlan`` →
    ``BatchedPlan``), and only the per-block OUTPUTS accumulate on host
    — a 100M-row predict holds ~two blocks of X resident, never the
    matrix. Output is byte-identical to the blocked resident path: same
    kernels, same block shapes, same padding rule.

    Host (non-JAX) models fall back to a serial block loop through
    their own ``predict`` — still bounded memory, no device programs.
    """
    import jax

    from ..parallel import faults
    from ..parallel.backend import BlockFeeder, _RetryState, _RoundFault

    n = dataset.n_rows
    if plan is None:
        # host model: block loop through the model's own method —
        # bounded host memory is the contract, speed is not
        from ..data import packed_block_dense

        fn = getattr(model, method)
        outs = []
        for i in range(dataset.n_blocks):
            b = dataset.read_block(i, pad=False)
            Xb = b.X
            if hasattr(Xb, "idx"):  # PackedX → scipy for host models
                from scipy import sparse as sp

                Xb = sp.csr_matrix(packed_block_dense(Xb, b.n_real))
            outs.append(np.asarray(fn(Xb)))
        return np.concatenate(outs, axis=0)

    bplan = backend.prepare_batched(
        plan.block_kernel(), {"params": plan.params},
        cache_key=plan.cache_key(),
    )
    from ..obs import metrics as obs_metrics

    stats = backend.last_round_stats = obs_metrics.new_round_stats(
        "streamed_predict", tasks=int(dataset.n_blocks),
    )
    sync = bool(getattr(backend, "sync_rounds", False))

    # blocks ride the TASK axis in groups of the mesh's task slots (a
    # LocalBackend group is one block — the resident-parity shape); the
    # tail group pads by repeating its last block, outputs sliced off
    slots = max(1, int(bplan.n_task_slots))
    n_blocks = dataset.n_blocks
    groups = [
        list(range(s, min(s + slots, n_blocks)))
        for s in range(0, n_blocks, slots)
    ]

    def read(gi):
        idxs = groups[gi]
        trees = [dataset.read_block(i, pad=True).X for i in idxs]
        while len(trees) < slots:
            trees.append(trees[-1])
        return {"X": jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *trees
        )}

    feeder = BlockFeeder(read, len(groups), bplan.put,
                         sync=sync, stats=stats)
    retry = _RetryState()
    outs = {}
    pending = []  # [(group_idx, dev_out)]

    def drain_one():
        gi, dev_out = pending[0]
        out = np.asarray(bplan.gather(dev_out)["out"])  # may raise
        pending.pop(0)
        for j, bi in enumerate(groups[gi]):
            start, stop = dataset.block_range(bi)
            outs[bi] = out[j][: stop - start]

    def salvage(exc, gi):
        """Classify a dispatch- or gather-time fault and rewind the
        feeder to the earliest group whose output has not landed —
        the reader re-opens at exactly that offset."""
        kind = faults.classify(exc)
        if not faults.is_retryable(kind):
            raise exc
        retry.admit(_RoundFault([], 0, exc, kind), gi)
        stats["retries"] = retry.total
        resume = pending[0][0] if pending else gi
        pending.clear()
        feeder.seek(resume)

    injector = faults.active_injector()
    try:
        while len(outs) < n_blocks:
            item = feeder.next()
            if item is not None:
                gi, dev = item
                try:
                    if injector is not None:
                        injector.round_dispatched()
                    dev_out = bplan.run_async_placed(dev)
                except Exception as exc:
                    salvage(exc, gi)
                    continue
                pending.append((gi, dev_out))
                stats["rounds"] += 1
                if len(pending) < 2:
                    continue  # keep one round in flight (depth 2)
            elif not pending:
                break  # exhausted with nothing in flight
            gi = pending[0][0]
            try:
                drain_one()
            except Exception as exc:  # async fault at the gather:
                salvage(exc, gi)     # seek re-feeds the lost groups
    finally:
        feeder.close()
    out = np.concatenate([outs[i] for i in range(n_blocks)], axis=0)
    obs_metrics.publish_round_stats(stats)
    return plan.postprocess(out)


def _is_sparse_2d(X):
    from ..sparse import is_sparse_2d

    return is_sparse_2d(X)


def _max_nnz_per_row(X):
    """Packed width m from indptr alone — ONE shared definition
    (``skdist_tpu.sparse.max_nnz_per_row``) for the budget guardrail,
    this predict path, and the fit plane's packing, so a changed
    padding rule can never let one undercount another."""
    from ..sparse import max_nnz_per_row

    return max_nnz_per_row(X)


def _pack_csr_rows(X):
    """CSR → (idx, val) padded-row pair — the SHARED packing
    (``skdist_tpu.sparse.pack_csr_rows``, promoted from this module's
    former private copy): the fit plane, this predict path, and the
    packed matvec kernels all consume one format."""
    from ..sparse import pack_csr_rows

    return pack_csr_rows(X)


def _try_device_predict_sparse(model, X, method, backend, batch_size,
                               plan=None):
    """Device CSR path for sparse inference (VERDICT round-2 item 5):
    ship only (idx, val) — 2·nnz·4 bytes, not n·d·4 — and rebuild each
    row block ON DEVICE with one scatter-add, then run the model's
    existing decision/proba kernel on the dense block (the matmul stays
    on the MXU; the host never materialises anything (n, d)-sized).
    Returns None when the model has no device kernels, handing over to
    the host paths. Rows with wildly skewed nnz pay padding to the max
    row; hashed-text rows are near-uniform, the target workload.
    """
    if plan is None:
        plan = device_predict_plan(model, method)
    if plan is None:
        return None
    kernel = plan.kernel

    X = X.tocsr()
    n, d = X.shape

    # bound the packed task tensors the same way the dense streaming
    # bounds densified groups: (idx+val) is n·m·8 bytes. The budget
    # check runs BEFORE _pack_csr_rows — the pack allocates ~3× n·m·8
    # bytes of intermediates, so packing the full matrix first could
    # OOM the host before the guardrail it feeds ever fired (round-3
    # advisor, medium); m comes from indptr alone, which is free.
    m = _max_nnz_per_row(X)
    from ..utils.meminfo import densify_budget_bytes

    budget, _ = densify_budget_bytes()
    if budget is not None and n * m * 8 > budget // 2:
        rows = max(1, int(budget // 8) // max(m * 8, 1))
        if rows < n:
            outs = [
                _try_device_predict_sparse(
                    model, X[i:min(i + rows, n)], method, backend,
                    batch_size, plan=plan)
                for i in range(0, n, rows)
            ]
            return np.concatenate(outs, axis=0)

    idx, val = _pack_csr_rows(X)
    block = min(batch_size, max(1, n))
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    if pad:
        idx = np.concatenate([idx, np.zeros((pad, m), idx.dtype)])
        val = np.concatenate([val, np.zeros((pad, m), val.dtype)])
    idx = idx.reshape(n_blocks, block, m)
    val = val.reshape(n_blocks, block, m)

    from ..sparse import packed_to_dense

    def block_kernel(shared, task):
        dense = packed_to_dense(task["idx"], task["val"], d)
        return {"out": kernel(shared["params"], dense)}

    from ..parallel import structural_key

    out = backend.batched_map(
        block_kernel, {"idx": idx, "val": val}, {"params": plan.params},
        # the closure bakes in the dense block shape (block, d) on top
        # of the memoised decision/proba kernel — all of it in the key,
        # so repeated sparse predicts share one traced program
        cache_key=structural_key(
            "predict_sparse", plan.cls, plan.which, plan.static,
            plan.meta_sig, block, d,
        ),
    )["out"]
    out = out.reshape(-1, *out.shape[2:])[:n]
    return plan.postprocess(out)


def _postprocess_predict(model, out, method):
    if method == "predict":
        if getattr(model, "_estimator_type", None) == "classifier":
            if out.ndim == 1:
                idx = (out > 0).astype(np.int64)
            else:
                idx = np.argmax(out, axis=1)
            return model.classes_[idx]
        return out
    return out


def _sparse_row_groups(X, n):
    """Row-group plan [(start, stop), ...] for a 2-D sparse X whose
    densified whole would blow the memory budget; None when X is not
    sparse or fits as-is. Groups target 1/8 of the budget (several
    groups in flight: host staging + device replica + outputs)."""
    if not (hasattr(X, "toarray") and hasattr(X, "tocsr")
            and len(X.shape) == 2):
        return None
    from ..utils.meminfo import densify_budget_bytes

    budget, _ = densify_budget_bytes()
    if budget is None:
        return None
    d = int(X.shape[1])
    est = int(n) * d * 4
    if est <= budget // 2:
        return None
    rows = max(1, int(budget // 8) // max(d * 4, 1))
    if rows >= n:
        return None
    return [(i, min(i + rows, n)) for i in range(0, n, rows)]


def _try_device_predict(model, X, method, backend, batch_size, plan=None):
    """Mesh-sharded inference for JAX estimators; None → host path."""
    if plan is None:
        plan = device_predict_plan(model, method)
    if plan is None:
        return None
    from ..models.linear import as_dense_f32

    try:
        X_arr = as_dense_f32(X)
    except Exception:
        return None
    n, d = X_arr.shape
    block = min(batch_size, max(1, n))
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    if pad:
        X_arr = np.concatenate([X_arr, np.repeat(X_arr[-1:], pad, axis=0)])
    blocks = X_arr.reshape(n_blocks, block, d)

    out = backend.batched_map(
        plan.block_kernel(), {"X": blocks}, {"params": plan.params},
        cache_key=plan.cache_key(),
    )["out"]
    out = out.reshape(-1, *out.shape[2:])[:n]
    return plan.postprocess(out)
