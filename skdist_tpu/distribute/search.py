"""
Distributed hyperparameter search: ``DistGridSearchCV``,
``DistRandomizedSearchCV``, ``DistMultiModelSearch``.

Re-design of the reference flagship (``/root/reference/skdist/distribute/
search.py:291-714``). The reference enumerates ``fit_sets =
product(candidate_params, cv_splits)`` and ships each ``_fit_and_score``
closure to a Spark executor (search.py:378-437). Here the same task set
takes one of two execution paths:

- **batched device path** (JAX estimators, device-supported scorers):
  candidates are bucketed by compile-shaping params, numeric
  hyperparameters are stacked onto a task axis together with a fold id,
  and the whole bucket runs as ONE vmapped, jit-compiled XLA program
  whose task axis shards across the TPU mesh. CV folds are 0/1 weight
  masks (static shapes); scores come back as a single gathered array.
  This is the "many fits = one program" win Spark cannot express.

- **generic host path** (any sklearn-compatible estimator): the same
  task list fans out over backend threads, preserving sk-dist's ability
  to wrap arbitrary estimators; semantics match sklearn exactly.

``cv_results_`` reproduces sklearn's schema: ``split{i}_test_*``,
``mean/std/rank_test_*`` (rank via min-method rankdata, reference
search.py:481-484), masked param arrays, fit/score times. The best
candidate is refit on the driver (search.py:543-550) and all runtime
handles are stripped post-fit so the artifact pickles clean
(search.py:568-570).
"""

import time
import warnings
from itertools import product

import numpy as np
from numpy.ma import MaskedArray
from scipy.stats import rankdata

from ..base import BaseEstimator, clone, strip_runtime
from ..metrics import (
    BINARY_ONLY_SCORERS,
    DEVICE_SCORERS,
    aggregate_score_dicts,
    check_multimetric_scoring,
    default_device_scorer,
    device_scorer_compatible,
    resolve_rung_scorer,
    resolve_stream_rung,
    scorer_task_compatible,
)
from ..parallel import (
    RungController,
    faults,
    iterative_fit_supported,
    parse_partitions,
    prefers_host_engine,
    resolve_backend,
    row_sharded_specs,
)
from .adaptive import (
    HalvingSpec,
    RungKilledWarning,
    check_adaptive,
    rung_per_candidate,
    warn_not_engaged,
)
from ..utils.validation import (
    check_error_score,
    check_estimator_backend,
    check_is_fitted,
    check_n_iter,
    full_length_sample_weight,
    index_fit_params,
    num_samples,
    safe_split,
)

__all__ = [
    "DistBaseSearchCV",
    "DistGridSearchCV",
    "DistRandomizedSearchCV",
    "DistMultiModelSearch",
    "HalvingSpec",
    "RungKilledWarning",
]


def _nan_as_worst(scores):
    """Replace NaN scores (failed fits under error_score=np.nan) with a
    value strictly below the finite minimum before ranking.

    scipy>=1.10 rankdata propagates NaN, so a single failed fit would
    make EVERY rank NaN; the int32 cast then turns them into garbage and
    best_index_ silently selects the wrong candidate. Modern sklearn
    ranks failed candidates last; so do we.
    """
    scores = np.asarray(scores, dtype=np.float64)
    nan_mask = np.isnan(scores)
    if not nan_mask.any():
        return scores
    worst = np.nanmin(scores) - 1.0 if not nan_mask.all() else 0.0
    return np.where(nan_mask, worst, scores)


# ---------------------------------------------------------------------------
# generic per-task closure (host path) — reference _fit_and_score
# (search.py:180-288)
# ---------------------------------------------------------------------------

def _fit_and_score(estimator, X, y, scorers, train, test, parameters,
                   fit_params=None, error_score=np.nan,
                   return_train_score=False, est_instance=None,
                   return_estimator=False):
    """``est_instance``: a pre-built clone (already parameterised, may
    carry warm-start hints) to fit instead of cloning ``estimator``;
    ``return_estimator`` adds the fitted instance under ``"estimator"``
    (used by the warm C-path runner to chain optima)."""
    if est_instance is not None:
        est = est_instance
    else:
        est = clone(estimator)
        if parameters:
            est.set_params(**parameters)
    X_train, y_train = safe_split(est, X, y, train)
    X_test, y_test = safe_split(est, X, y, test, train)
    # array-valued fit params (full-length sample_weight etc.) are
    # sliced to the train fold (reference search.py:208-210)
    fit_params = index_fit_params(X, fit_params or {}, train)
    start = time.perf_counter()
    result = {}
    try:
        if y_train is None:
            est.fit(X_train, **fit_params)
        else:
            est.fit(X_train, y_train, **fit_params)
        fit_time = time.perf_counter() - start
        score_start = time.perf_counter()
        for name, scorer in scorers.items():
            result[f"test_{name}"] = scorer(est, X_test, y_test)
        score_time = time.perf_counter() - score_start
        if return_train_score:
            for name, scorer in scorers.items():
                result[f"train_{name}"] = scorer(est, X_train, y_train)
    except Exception as exc:
        # reference error_score policy (search.py:232-259): 'raise' or a
        # numeric substitute recorded with a warning
        fit_time = time.perf_counter() - start
        score_time = 0.0
        if error_score == "raise":
            raise
        if not isinstance(error_score, (int, float)):
            raise ValueError(
                "error_score must be 'raise' or numeric"
            ) from None
        warnings.warn(
            f"Estimator fit failed ({type(exc).__name__}: {exc}); "
            f"score set to {error_score}.",
            FitFailedWarning,
        )
        for name in scorers:
            result[f"test_{name}"] = float(error_score)
            if return_train_score:
                result[f"train_{name}"] = float(error_score)
    result["fit_time"] = fit_time
    result["score_time"] = score_time
    if return_estimator:
        result["estimator"] = est
    return result


class FitFailedWarning(RuntimeWarning):
    """Raised-as-warning marker for failed per-task fits (the reference
    referenced sklearn's FitFailedWarning without importing it —
    search.py:248-253 — a dead path we make real)."""


# ---------------------------------------------------------------------------
# fault-tolerance helpers: checkpoint signature + lane quarantine
# ---------------------------------------------------------------------------

def _canonical_value(v):
    """Address-free canonical form of one value: simple scalars by
    repr, sequences element-wise, dicts sorted, callables by
    module-qualified name, everything else (estimators, backends,
    scorer objects) by type name. A plain ``repr`` of a callable
    embeds its object address — which would make the checkpoint
    signature differ across exactly the process restarts a resume
    spans, silently turning kill+resume into a full re-run."""
    if isinstance(v, (str, bytes, int, float, bool, type(None))):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return tuple(_canonical_value(x) for x in v)
    if isinstance(v, dict):
        return tuple(
            (repr(k), _canonical_value(x))
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0]))
        )
    if callable(v) and hasattr(v, "__qualname__"):
        return (getattr(v, "__module__", "?") or "?") + ":" + v.__qualname__
    qual = type(v).__module__ + "." + type(v).__qualname__
    if hasattr(v, "get_params"):
        # nested estimators: the CONFIG matters, not just the class —
        # a resumed search with a retuned inner estimator must not
        # restore the old estimator's journaled scores
        return (qual, _canonical_params(v.get_params(deep=False)))
    if callable(v):
        # callable instances (sklearn's make_scorer objects): the type
        # name alone collides across every _Scorer — canonicalize the
        # configuring attributes (score func, kwargs, sign) instead
        attrs = getattr(v, "__dict__", None) or {}
        return (qual, tuple(
            (k, _canonical_value(x)) for k, x in sorted(attrs.items())
        ))
    return type(v).__name__


def _canonical_params(params):
    """Stable, process-independent signature of a param dict (see
    :func:`_canonical_value`). Feeds the checkpoint grid signature, so
    it must be identical across the process restarts a resume spans."""
    return tuple(
        (k, _canonical_value(v)) for k, v in sorted(params.items())
    )


def _checkpoint_signature(search, estimator, candidate_params, splits,
                          X, y, fit_params):
    """Structural identity of one search for the durable-checkpoint
    journal: anything that changes what task id ``t`` MEANS
    participates — estimator class+params, the candidate list, the
    actual CV split indices (not just the fold count: a reshuffled cv
    renumbers every task), scoring config, and digests of the training
    data and array-valued fit params."""
    split_sig = faults.data_digest(
        np.concatenate([
            np.concatenate([np.asarray(tr, np.int64).ravel(),
                            np.asarray(te, np.int64).ravel()])
            for tr, te in splits
        ]) if splits else np.empty(0, np.int64)
    )
    fp_sig = tuple(
        (k, faults.data_digest(v) if hasattr(v, "__len__")
            and not isinstance(v, (str, bytes, dict))
            else _canonical_value(v))
        for k, v in sorted(fit_params.items())
    )
    adaptive = getattr(search, "adaptive", None)
    return faults.grid_signature(
        type(search).__name__,
        type(estimator).__module__ + "." + type(estimator).__qualname__,
        _canonical_params(estimator.get_params(deep=False)),
        tuple(_canonical_params(c) for c in candidate_params),
        len(splits), split_sig,
        _canonical_value(search.scoring), bool(search.return_train_score),
        # adaptive config participates ONLY when set: a journal written
        # by one halving race (its rows include rung-killed error_score
        # rows) must not resume a search with a different eta/cadence/
        # metric — and the candidate list above is the SAMPLED list for
        # randomized search, so a same-random_state rerun resumes past
        # completed rungs instead of resampling a new grid. adaptive=
        # None contributes NO element, keeping exhaustive signatures
        # byte-identical to the pre-adaptive release (an in-flight
        # journal survives the upgrade).
        *(() if adaptive is None else (_canonical_value(adaptive),)),
        faults.data_digest(X),
        faults.data_digest(y) if y is not None else "y=None",
        fp_sig,
    )


def _quarantine_nonfinite(out_rows, error_score, context="search",
                          exempt=()):
    """The lane-quarantine guard over assembled batched-path score
    rows: a non-finite score can only mean a numerically diverged
    (poisoned) fit lane — the device kernels have no error path — so
    it maps to sklearn ``error_score`` semantics exactly like a raised
    host fit: 'raise' raises, a numeric substitutes with a
    :class:`FitFailedWarning`. Runs host-side over already-gathered
    floats (no device work, no compiles); ``SKDIST_FAULT_GUARD=0``
    disables. ``exempt`` rows (adaptive rung kills — already mapped to
    error_score by :func:`_apply_rung_retirement`, with their own
    warning) are skipped: a killed lane must not be double-reported as
    a diverged one, nor raise under ``error_score='raise'``."""
    if not faults.guard_enabled():
        return
    bad = []
    for i, row in enumerate(out_rows):
        if row is None or i in exempt:
            continue
        for k, v in row.items():
            if k.startswith(("test_", "train_")) and not np.isfinite(v):
                bad.append(i)
                break
    if not bad:
        return
    if error_score == "raise":
        raise RuntimeError(
            f"{len(bad)} batched {context} fit(s) produced non-finite "
            f"scores (diverged lanes, e.g. task {bad[0]}) and "
            "error_score='raise'. Set error_score to a number to "
            "record them as failed fits instead."
        )
    faults.record("lanes_quarantined", len(bad))
    warnings.warn(
        f"{len(bad)} of {len(out_rows)} batched {context} fits "
        f"produced non-finite scores (diverged lanes); their scores "
        f"are set to error_score={error_score!r}.",
        FitFailedWarning,
    )
    for i in bad:
        row = out_rows[i]
        for k in row:
            if k.startswith(("test_", "train_")):
                row[k] = float(error_score)


def _apply_rung_retirement(out_rows, killed, error_score,
                           checkpoint=None, context="search"):
    """Map adaptive-rung-killed lanes to sklearn-compatible rows: the
    PR-5 ``error_score`` semantics (a numeric substitutes for every
    test/train score) with ONE :class:`RungKilledWarning` naming the
    count. ``error_score='raise'`` maps to NaN instead of raising — a
    rung kill is a scheduling decision, not a failed fit, and raising
    would make adaptive search unusable under the strict setting (the
    NaN rows still rank last). With a ``checkpoint``, the MAPPED row is
    re-journaled (last-write-wins on replay) tagged ``rung_killed`` so
    a resumed search restores the kill, not the partial fit's raw
    finalize scores."""
    if not killed:
        return
    es = float("nan") if error_score == "raise" else float(error_score)
    warnings.warn(
        f"{len(killed)} of {len(out_rows)} batched {context} fits were "
        f"retired early by adaptive successive halving; their scores "
        f"are recorded as error_score={es!r} and the rung_ column "
        "records where each candidate died.",
        RungKilledWarning,
    )
    faults.record("lanes_rung_killed", len(killed))
    for gid, rung in killed.items():
        row = out_rows[gid]
        if row is None:
            continue
        for k in row:
            if k.startswith(("test_", "train_")):
                row[k] = es
        if checkpoint is not None:
            checkpoint.record(gid, {**row, "rung_killed": float(rung)})


# ---------------------------------------------------------------------------
# batched device path helpers
# ---------------------------------------------------------------------------

def _candidate_buckets(estimator, candidate_params):
    """Group candidate indices by compile-shaping ("static") params.

    Returns None if any candidate touches a param that is neither a
    batchable hyper nor a declared static — those need the generic path.
    """
    from ..models.linear import _freeze

    hyper_names = set(getattr(type(estimator), "_hyper_names", ()))
    static_names = set(getattr(type(estimator), "_static_names", ()))
    buckets = {}
    for idx, cand in enumerate(candidate_params):
        for name in cand:
            if name not in hyper_names and name not in static_names:
                return None
        overrides = {k: v for k, v in cand.items() if k in static_names}
        key = _freeze(overrides)
        buckets.setdefault(key, (overrides, []))[1].append(idx)
    return buckets


def _resolve_device_scoring(estimator, scoring):
    """Map the user ``scoring`` arg to device scorer specs, or None if
    any requested metric has no device kernel."""
    if scoring is None:
        names = [("score", default_device_scorer(estimator))]
    elif isinstance(scoring, str):
        names = [("score", scoring)]
    elif isinstance(scoring, (list, tuple, set)):
        names = [(s, s) for s in scoring]
    else:
        return None  # dict-of-callables etc: host path
    specs = []
    for out_name, metric in names:
        if metric not in DEVICE_SCORERS:
            return None
        # task-kind mismatches (a regression metric on a classifier,
        # whose device 'predict' output is decision scores rather than
        # labels; a classification metric on a regressor, whose meta
        # has no n_classes to trace against) route to the host path,
        # where sklearn's own scorer semantics — including its raises
        # under the error_score contract — apply per task
        if not scorer_task_compatible(metric, estimator):
            return None
        kernel, kind = DEVICE_SCORERS[metric]
        specs.append((out_name, metric, kernel, kind))
    return specs


def _resolve_stream_scoring(estimator, scoring, y=None):
    """Map ``scoring`` to streamed scorer specs ``[(out_name, metric)]``
    or raise — the streamed search has no host fallback, so an
    unsupported metric must say so instead of silently degrading."""
    from ..metrics import STREAM_SCORERS

    if scoring is None:
        names = [("score", default_device_scorer(estimator))]
    elif isinstance(scoring, str):
        names = [("score", scoring)]
    elif isinstance(scoring, (list, tuple, set)):
        names = [(s, s) for s in scoring]
    else:
        raise ValueError(
            "streamed search scoring must be None, a metric name, or a "
            "list of metric names (callable scorers need resident "
            f"predictions); got {scoring!r}"
        )
    classes = np.unique(y) if y is not None else None
    for _out, metric in names:
        if metric not in STREAM_SCORERS:
            raise ValueError(
                f"scoring={metric!r} has no streamed (decomposable) "
                "kernel; streamed search supports "
                f"{sorted(STREAM_SCORERS)}"
            )
        if not scorer_task_compatible(metric, estimator):
            # the streamed path has no host fallback: a task-kind
            # mismatch must raise — a regression metric on a
            # classifier would silently score raw decision values
            # (sklearn scores predicted labels), and a classification
            # metric on a regressor would trace against a meta with
            # no n_classes and crash mid-dispatch
            raise ValueError(
                f"scoring={metric!r} does not fit a "
                f"{getattr(estimator, '_estimator_type', 'model')}: "
                "streamed scoring has no host fallback, so the metric "
                "must match the estimator kind"
            )
        if metric in BINARY_ONLY_SCORERS and not \
                device_scorer_compatible(metric, classes):
            raise ValueError(
                f"scoring={metric!r} is binary-only with positive "
                "class 1; this label set needs a resident fit"
            )
    return names


def _partition_fold_ids(splits, n):
    """Collapse CV splits into one ``(n,)`` fold-id vector — the O(n)
    representation the streamed CV path slices per block. Requires the
    splits to PARTITION the rows with complementary train sets
    (KFold/StratifiedKFold-style); overlapping or subsampling splitters
    would need per-split masks, which is exactly the O(n_splits · n)
    host state streaming exists to avoid."""
    fold_id = np.full(n, -1, dtype=np.int32)
    for s, (train, test) in enumerate(splits):
        test = np.asarray(test)
        if (fold_id[test] != -1).any():
            raise ValueError(
                "streamed search needs partition-style CV (each row in "
                "exactly one test fold, train = complement), e.g. "
                "KFold/StratifiedKFold; this splitter assigns rows to "
                "multiple test folds"
            )
        fold_id[test] = s
        if len(train) + len(test) != n:
            raise ValueError(
                "streamed search needs partition-style CV with "
                "train = complement of test (KFold/StratifiedKFold); "
                f"split {s} covers {len(train) + len(test)} of {n} rows"
            )
    if (fold_id == -1).any():
        raise ValueError(
            "streamed search needs partition-style CV: "
            f"{int((fold_id == -1).sum())} rows appear in no test fold"
        )
    return fold_id


#: sample-axis layout of the CV shared dict (consumed by
#: parallel.row_sharded_specs on 2D meshes)
_CV_SAMPLE_AXES = {
    "X": 0, "y": 0, "sw": 0, "Y": 0,
    "train_masks": 1, "test_masks": 1,
}


def _cv_kernel_key(est_cls, meta, static, scorer_specs, return_train_score):
    """Structural compile-cache key of one CV kernel: estimator class
    qualname + static config + scorer names/kinds + meta signature
    (``parallel.compile_cache.structural_key``). Shared by the kernel
    memo below and by the ``cache_key`` handed to ``batched_map``, so
    the closure, its traced jit entry, and its AOT executables all key
    on the same stable semantics — in this process and (through the
    on-disk XLA cache) across processes."""
    from ..models.linear import _meta_signature
    from ..parallel import structural_key

    return structural_key(
        "cv", est_cls, static,
        # scorer kernels are module-level objects; their NAMES are the
        # stable cross-process identity
        tuple((out, metric, kind) for out, metric, _k, kind in scorer_specs),
        bool(return_train_score),
        _meta_signature(meta),
    )


def _cached_cv_kernel(est_cls, meta, static, scorer_specs,
                      return_train_score, key=None):
    """Cache cv kernels on their structural key so repeated searches
    reuse both the closure and (via the backend's jit cache) the
    compiled XLA program. ``key``: the precomputed
    :func:`_cv_kernel_key` when the caller also needs it for
    ``batched_map``'s ``cache_key`` — one computation, one source of
    truth for both tiers."""
    from ..parallel import compile_cache

    if key is None:
        key = _cv_kernel_key(est_cls, meta, static, scorer_specs,
                             return_train_score)
    return compile_cache.kernel_memo(
        key,
        lambda: _build_cv_kernel(est_cls, meta, static, scorer_specs,
                                 return_train_score),
    )


def _cost_order(est_cls, task_hyper, split_ids):
    """Cost-ordered round packing: a permutation of the task axis
    sorting by the estimator family's convergence-cost heuristic
    (ascending), fold id fastest — so each chunk-shaped round holds
    tasks of similar expected iteration count and the compacted loop
    retires whole rounds instead of dragging one straggler per round.
    Returns None when the family has no heuristic or the order is
    already cost-sorted."""
    cost_fn = getattr(est_cls, "_batched_task_cost", None)
    if cost_fn is None or len(split_ids) <= 1:
        return None
    try:
        cost = np.asarray(cost_fn(task_hyper), dtype=np.float64)
    except Exception:
        return None
    if cost.shape != (len(split_ids),):
        return None
    order = np.lexsort((np.asarray(split_ids), cost))
    if np.array_equal(order, np.arange(len(order))):
        return None
    return order


def _cv_iterative_spec(est_cls, meta, static, scorer_specs,
                       return_train_score, n_slice, fallback,
                       fallback_key, rung_spec=None, mask_x=False):
    """Build (memoised) the iteration-sliced CV kernels: init/step
    advance the estimator's sliced fit on the fold-masked weights;
    finalize shapes params from the carry and computes the same scorer
    outputs as the classic fused kernel. Delegates to the shared
    ``_iterative_fit_spec`` entry point (``distribute/multiclass.py``)
    that OvR/OvO and the feature eliminator also build on. Returns
    ``(spec, cache_key)``.

    ``rung_spec`` (an ``(out_name, metric, kernel, kind)`` device
    scorer tuple — see :func:`~skdist_tpu.metrics.resolve_rung_scorer`)
    additionally equips the spec with the adaptive rung evaluator:
    params shaped from the LIVE carry, scored on the held-out fold mask
    — the quality signal ASHA kills on. ``mask_x=True`` multiplies the
    shared X by a per-task ``task["fmask"]`` column mask everywhere
    (fit, scoring, rung) — the feature eliminator's task axis."""
    from ..models.linear import _meta_signature, maybe_exact_matmuls
    from ..parallel import structural_key
    from .multiclass import _iterative_fit_spec

    key = structural_key(
        "cv_iter", est_cls, static,
        tuple((out, metric, kind) for out, metric, _k, kind in scorer_specs),
        bool(return_train_score),
        _meta_signature(meta),
        int(n_slice),
        None if rung_spec is None else (rung_spec[1], rung_spec[3]),
        bool(mask_x),
    )

    decision_kernel = maybe_exact_matmuls(
        est_cls, est_cls._build_decision_kernel(meta, static)
    )
    needs_proba = any(kind == "proba" for *_, kind in scorer_specs) or (
        rung_spec is not None and rung_spec[3] == "proba"
    )
    proba_kernel = (
        maybe_exact_matmuls(
            est_cls, est_cls._build_proba_kernel(meta, static)
        )
        if needs_proba else None
    )

    def task_X(shared, task):
        return shared["X"] * task["fmask"] if mask_x else shared["X"]

    def derive(shared, task):
        fit_w = shared["sw"] * shared["train_masks"][task["split"]]
        return (task_X(shared, task), shared["y"], fit_w, task["hyper"],
                shared["aux"])

    def model_outputs(params, shared, task):
        X = task_X(shared, task)
        outputs = {"decision": decision_kernel(params, X)}
        outputs["predict"] = outputs["decision"]
        if proba_kernel is not None:
            outputs["proba"] = proba_kernel(params, X)
        return outputs

    def outputs(params, shared, task):
        om = model_outputs(params, shared, task)
        y = shared["y"]
        train_w = shared["train_masks"][task["split"]]
        test_w = shared["test_masks"][task["split"]]
        scores = {}
        for out_name, _metric, score_kernel, kind in scorer_specs:
            scores[f"test_{out_name}"] = score_kernel(
                y, om[kind], test_w, meta
            )
            if return_train_score:
                scores[f"train_{out_name}"] = score_kernel(
                    y, om[kind], train_w, meta
                )
        return scores

    rung_score = None
    if rung_spec is not None:
        _out, _metric, rung_kernel, rung_kind = rung_spec

        def rung_score(params, shared, task):
            om = model_outputs(params, shared, task)
            test_w = shared["test_masks"][task["split"]]
            return rung_kernel(shared["y"], om[rung_kind], test_w, meta)

    spec = _iterative_fit_spec(
        est_cls, meta, static, n_slice, derive, fallback, fallback_key,
        key, outputs=outputs, rung_score=rung_score,
    )
    return spec, key


def _build_cv_kernel(est_cls, meta, static, scorer_specs, return_train_score):
    """One (fold-masked fit + scores) program; vmapped by the backend."""
    from ..models.linear import maybe_exact_matmuls

    fit_kernel = maybe_exact_matmuls(
        est_cls, est_cls._build_fit_kernel(meta, static)
    )
    decision_kernel = maybe_exact_matmuls(
        est_cls, est_cls._build_decision_kernel(meta, static)
    )
    needs_proba = any(kind == "proba" for *_, kind in scorer_specs)
    proba_kernel = (
        maybe_exact_matmuls(est_cls, est_cls._build_proba_kernel(meta, static))
        if needs_proba else None
    )

    def kernel(shared, task):
        X, y, sw = shared["X"], shared["y"], shared["sw"]
        # user sample_weight (carried in sw) weights the FIT only;
        # train/test scoring is over the raw fold masks, like sklearn
        # scorers called without sample_weight
        fit_w = sw * shared["train_masks"][task["split"]]
        train_w = shared["train_masks"][task["split"]]
        test_w = shared["test_masks"][task["split"]]
        params = fit_kernel(X, y, fit_w, task["hyper"], shared["aux"])
        outputs = {"decision": decision_kernel(params, X)}
        outputs["predict"] = outputs["decision"]
        if proba_kernel is not None:
            outputs["proba"] = proba_kernel(params, X)
        scores = {}
        for out_name, _metric, score_kernel, kind in scorer_specs:
            scores[f"test_{out_name}"] = score_kernel(y, outputs[kind], test_w, meta)
            if return_train_score:
                scores[f"train_{out_name}"] = score_kernel(
                    y, outputs[kind], train_w, meta
                )
        return scores

    return kernel


# ---------------------------------------------------------------------------
# the meta-estimator
# ---------------------------------------------------------------------------

class DistBaseSearchCV(BaseEstimator):
    """Base class for distributed CV search (reference search.py:291-581)."""

    def __init__(self, estimator, backend=None, partitions="auto", cv=5,
                 scoring=None, refit=True, return_train_score=False,
                 error_score=np.nan, n_jobs=None, preds=False, verbose=0,
                 adaptive=None):
        self.estimator = estimator
        self.backend = backend
        self.partitions = partitions
        self.cv = cv
        self.scoring = scoring
        self.refit = refit
        self.return_train_score = return_train_score
        self.error_score = error_score
        self.n_jobs = n_jobs
        self.preds = preds
        self.verbose = verbose
        self.adaptive = adaptive

    # subclasses supply the candidate enumeration
    def _get_param_iterator(self):
        raise NotImplementedError

    # ------------------------------------------------------------------
    def fit(self, X, y=None, groups=None, checkpoint_dir=None, **fit_params):
        """``checkpoint_dir`` (or env ``SKDIST_CHECKPOINT_DIR``) opts
        into durable search checkpointing: completed (candidate x
        fold) results are journaled there, keyed by the structural
        grid signature, and a re-run of the SAME search after a
        process kill resumes past its finished tasks."""
        from sklearn.model_selection import check_cv

        from ..data import is_chunked

        check_error_score(self.error_score)
        check_adaptive(self.adaptive)
        if is_chunked(X) and y is None:
            # out-of-core input: the dataset carries its own labels
            # (O(n) host bytes — bounded by design); splitters, class
            # discovery, and scoring below all read this host vector
            y = X.load_y()
        # per-fit adaptive bookkeeping (consumed below, deleted before
        # the artifact is finalized)
        self._adaptive_engaged_ = False
        self._rung_killed_gids_ = {}
        check_estimator_backend(self, self.verbose)
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        estimator = self.estimator
        is_classifier = getattr(estimator, "_estimator_type", None) == "classifier"
        cv = check_cv(self.cv, y, classifier=is_classifier)
        n_splits = cv.get_n_splits(X, y, groups)
        candidate_params = list(self._get_param_iterator())
        n_candidates = len(candidate_params)
        if self.verbose:
            print(
                f"Fitting {n_splits} folds for each of {n_candidates} "
                f"candidates, totalling {n_candidates * n_splits} fits"
            )
        # splitters index rows, not features: chunked X is presented to
        # them as an (n, 0) stand-in (0 bytes) — fold membership is a
        # function of n/y/groups alone for every sklearn splitter
        split_X = (
            np.empty((len(X), 0), dtype=np.float32) if is_chunked(X)
            else X
        )
        splits = list(cv.split(split_X, y, groups))

        scorers, multimetric = check_multimetric_scoring(estimator, self.scoring)
        self.multimetric_ = multimetric
        refit_metric = self._refit_metric(scorers, multimetric)

        ckpt_dir = faults.resolve_checkpoint_dir(checkpoint_dir)
        checkpoint = None
        if ckpt_dir is not None:
            # ChunkedDataset input journals too: faults.data_digest
            # routes to the dataset's content_digest() (meta +
            # head/tail block samples), so the structural signature is
            # as stable across a kill+resume as the resident one
            checkpoint = faults.SearchCheckpoint(
                ckpt_dir,
                _checkpoint_signature(
                    self, estimator, candidate_params, splits, X, y,
                    fit_params,
                ),
            )
        try:
            out = self._run_search_tasks(
                backend, estimator, X, y, candidate_params, splits,
                scorers, fit_params, checkpoint=checkpoint,
            )
        finally:
            if checkpoint is not None:
                checkpoint.close()

        if self.adaptive is not None and not self._adaptive_engaged_:
            warn_not_engaged("the search")

        results = self._format_results(
            candidate_params, scorers, n_splits, out
        )
        if self.adaptive is not None:
            # rung_ column: rung at which each candidate died (-1 = ran
            # to completion); killed candidates' scores carry
            # error_score per _apply_rung_retirement
            results["rung_"] = rung_per_candidate(
                n_candidates, n_splits, self._rung_killed_gids_
            )
        del self._adaptive_engaged_, self._rung_killed_gids_
        self.cv_results_ = results
        self.scorer_ = scorers if multimetric else scorers["score"]
        self.n_splits_ = n_splits

        # best_* are exposed for refit=True or any single-metric run
        # (sklearn semantics; reference search.py:538-541)
        if self.refit or not multimetric:
            if np.all(np.isnan(results[f"mean_test_{refit_metric}"])):
                # mirror the eliminate / multi-model contract: never
                # silently return candidate 0 with best_score_=NaN
                raise RuntimeError(
                    "All candidate fits failed (every "
                    f"mean_test_{refit_metric} is NaN)."
                )
            self.best_index_ = int(results[f"rank_test_{refit_metric}"].argmin())
            self.best_params_ = candidate_params[self.best_index_]
            self.best_score_ = results[f"mean_test_{refit_metric}"][self.best_index_]
        if self.refit:
            best = clone(estimator).set_params(**self.best_params_)
            refit_start = time.perf_counter()
            if y is not None:
                best.fit(X, y, **fit_params)
            else:
                best.fit(X, **fit_params)
            self.refit_time_ = time.perf_counter() - refit_start
            self.best_estimator_ = best
            if self.preds:
                self.preds_ = self._out_of_fold_preds(
                    estimator, X, y, splits, fit_params
                )
        # detach from the user's template before stripping runtime
        # handles (the reference mutates the template via `del
        # estimator.sc`, search.py:568-570 — a footgun we avoid: the
        # user's own estimator object keeps its backend)
        self.estimator = clone(self.estimator)
        strip_runtime(self)
        return self

    def _refit_metric(self, scorers, multimetric):
        if multimetric:
            if not isinstance(self.refit, str) or self.refit not in scorers:
                if self.refit:
                    raise ValueError(
                        "For multi-metric scoring, refit must be the name "
                        "of the scorer used to find the best parameters."
                    )
            return self.refit if isinstance(self.refit, str) else None
        return "score"

    # ------------------------------------------------------------------
    def _run_search_tasks(self, backend, estimator, X, y, candidate_params,
                          splits, scorers, fit_params, checkpoint=None):
        """Dispatch (candidate × fold) tasks; returns a list of per-task
        score dicts in task order (candidate-major, split fastest).
        With a ``checkpoint``, journaled tasks are restored instead of
        re-fit and fresh completions are journaled as they land."""
        from ..data import is_chunked

        if is_chunked(X):
            # out-of-core input has exactly one execution path: the
            # streamed device drivers. Anything unsupported raises with
            # a remedy — there is no host fallback that could hold X.
            return self._run_streamed_search(
                backend, estimator, X, y, candidate_params, splits,
                fit_params, checkpoint=checkpoint,
            )
        n_splits = len(splits)
        batched = None
        # the batched device path handles the one array-valued fit
        # param with device semantics — full-length sample_weight
        # (fold masks compose with it multiplicatively); anything else
        # routes to the generic host path, where the per-task
        # error_score contract handles failures. ONE definition of the
        # contract, shared with the OvR/OvO batched paths.
        sw, sw_ok = full_length_sample_weight(fit_params, num_samples(X))
        if sw_ok:
            batched = self._try_batched(
                backend, estimator, X, y, candidate_params, splits,
                sample_weight=sw, checkpoint=checkpoint,
            )
        if batched is not None:
            return batched

        warm = self._try_host_linear_warm(
            backend, estimator, X, y, candidate_params, splits, scorers,
            fit_params, checkpoint=checkpoint,
        )
        if warm is not None:
            return warm

        # generic host fan-out (reference joblib path, search.py:388-409)
        tasks = [
            (cand_idx * n_splits + s, params, train, test)
            for cand_idx, params in enumerate(candidate_params)
            for s, (train, test) in enumerate(splits)
        ]
        out = [None] * len(tasks)
        if checkpoint is not None and checkpoint.completed:
            todo = []
            for task in tasks:
                row = checkpoint.completed.get(task[0])
                if row is not None:
                    row = dict(row)
                    # rows journaled as adaptive rung kills restore as
                    # kills here too (a resumed search may downgrade to
                    # this path); the tag must not leak into the score
                    # rows — aggregate_score_dicts needs uniform keys
                    rk = row.pop("rung_killed", None)
                    if rk is not None and hasattr(
                            self, "_rung_killed_gids_"):
                        self._rung_killed_gids_[task[0]] = int(rk)
                    out[task[0]] = row
                else:
                    todo.append(task)
        else:
            todo = tasks

        def run_one(task):
            tid, params, train, test = task
            r = _fit_and_score(
                estimator, X, y, scorers, train, test, params,
                fit_params=fit_params, error_score=self.error_score,
                return_train_score=self.return_train_score,
            )
            if checkpoint is not None:
                checkpoint.record(tid, r)
            return r

        for task, r in zip(
            todo, backend.run_tasks(run_one, todo, verbose=self.verbose)
        ):
            out[task[0]] = r
        return out

    def _try_host_linear_warm(self, backend, estimator, X, y,
                              candidate_params, splits, scorers,
                              fit_params, checkpoint=None):
        """Warm C-path runner for host-engine linear fits; None → the
        plain generic fan-out applies.

        When the estimator resolves to the f64 host engine, candidates
        that differ only in ``C`` form a regularisation path: within
        one fold, fits run in ascending-C order and each fit starts
        from the previous optimum (``_warm_w0`` → ``_w_opt64``
        chaining through ``models/host_linear.py``) — the previous
        solution of a convex objective is a near-free init, so the
        whole grid costs little more than its hardest fit (round-4
        VERDICT task 3). Init-independence is what makes this safe:
        a tol-converged optimum is the same from any start, so scores
        match cold fits to solver tolerance. Cap-limited candidates
        are fit cold twice over: the engine refuses to seed the chain
        from a fit that stopped on ``max_iter`` (it returns no
        optimum), AND a warm-seeded fit that itself stops on the cap
        is REFIT cold before its score is recorded — a capped
        trajectory depends on its seed, so recording the warm run
        would make the score depend on which other C values share the
        grid (ADVICE r05 #1). Per-task
        semantics (slicing, scorers, error_score)
        are exactly ``_fit_and_score``'s — the same function runs each
        task, only construction and ordering differ."""
        if not prefers_host_engine(backend, estimator):
            return None
        if not getattr(estimator, "_host_warm_startable", False):
            return None
        if checkpoint is not None and checkpoint.completed:
            # resuming mid-grid would splice journaled results into
            # warm chains whose seeds then depend on which tasks
            # happened to survive the kill; the generic per-task path
            # resumes cleanly (warm chaining is a speed path, not a
            # semantics path — cold per-task fits score identically to
            # solver tolerance)
            return None
        from ..models.linear import hyper_float

        n_splits = len(splits)
        out = [None] * (len(candidate_params) * n_splits)
        paths = {}
        for idx, cand in enumerate(candidate_params):
            key = tuple(sorted(
                (k, repr(v)) for k, v in cand.items() if k != "C"
            ))
            paths.setdefault(key, []).append(idx)
        for idxs in paths.values():
            idxs.sort(key=lambda i: float(hyper_float(
                candidate_params[i].get("C", estimator.C)
            )))

        # only fits WITHIN one (path, fold) chain are order-dependent;
        # the chains themselves are independent backend tasks, so the
        # backend's thread fan-out still applies (round-5 review)
        chains = [
            (idxs, train, test, s)
            for idxs in paths.values()
            for s, (train, test) in enumerate(splits)
        ]

        def fit_one(i, train, test, w0):
            est = clone(estimator)
            if candidate_params[i]:
                est.set_params(**candidate_params[i])
            if w0 is not None:
                est._warm_w0 = w0
            r = _fit_and_score(
                estimator, X, y, scorers, train, test, None,
                fit_params=fit_params,
                error_score=self.error_score,
                return_train_score=self.return_train_score,
                est_instance=est, return_estimator=True,
            )
            fitted = r.pop("estimator", None)
            return r, getattr(fitted, "_w_opt64", None)

        def run_chain(chain):
            idxs, train, test, s = chain
            results = []
            w_prev = None
            for i in idxs:
                r, w_opt = fit_one(i, train, test, w_prev)
                if w_prev is not None and w_opt is None:
                    # the warm-seeded fit stopped on max_iter (the
                    # engine returned no converged optimum): its
                    # trajectory — and therefore its recorded score —
                    # depends on the seed, i.e. on which OTHER C values
                    # happen to share the grid. Refit this candidate
                    # cold so every recorded result is grid-independent
                    # and reproducible outside the search (ADVICE r05
                    # #1); the chain already restarts cold from here.
                    r, w_opt = fit_one(i, train, test, None)
                w_prev = w_opt
                results.append((i, r))
            return results

        for chain, results in zip(
            chains,
            backend.run_tasks(run_chain, chains, verbose=self.verbose),
        ):
            s = chain[3]
            for i, r in results:
                out[i * n_splits + s] = r
                if checkpoint is not None:
                    checkpoint.record(i * n_splits + s, r)
        return out

    def _try_batched(self, backend, estimator, X, y, candidate_params, splits,
                     sample_weight=None, checkpoint=None):
        """Attempt the batched device path; None → fall back to generic."""
        if not hasattr(type(estimator), "_build_fit_kernel"):
            return None
        if any("engine" in cand for cand in candidate_params):
            # a searchable 'engine' must be HONOURED per candidate, and
            # the batched path compiles one engine for the whole bucket
            # — prefers_host_engine inspects only the base estimator, so
            # a {'engine': ['host', 'xla']} grid would silently run the
            # host bucket through the XLA kernel (ADVICE r05 #2). The
            # generic path clones + set_params per task, so each fit
            # resolves its own engine correctly.
            return None
        scorer_specs = _resolve_device_scoring(estimator, self.scoring)
        if scorer_specs is None:
            return None
        # binary-only metrics must match sklearn's label semantics, else
        # the host path (which raises/handles like sklearn) takes over
        if any(m in BINARY_ONLY_SCORERS for _, m, *_ in scorer_specs):
            classes = np.unique(y) if y is not None else None
            if not all(
                device_scorer_compatible(m, classes)
                for _, m, *_ in scorer_specs
            ):
                return None
        buckets = _candidate_buckets(estimator, candidate_params)
        if buckets is None:
            return None
        needs_proba = any(kind == "proba" for *_, kind in scorer_specs)
        if needs_proba and not hasattr(type(estimator), "_build_proba_kernel"):
            return None

        from ..models.linear import (
            _freeze, annotate_round_kernel_mode, extract_aux,
            fit_would_pack, hyper_float, prepare_fit_X,
        )
        import jax.numpy as jnp

        if prefers_host_engine(backend, estimator) and (
                not fit_would_pack(X, estimator)
                or getattr(estimator, "engine", None) == "host"):
            # a host backend whose estimator resolves to the f64 BLAS
            # host engine (engine='auto' on a CPU platform): the host
            # fan-out runs that engine per task — the analogue of the
            # reference's sc=None == sklearn path — instead of paying
            # XLA-CPU prices for the batched program (round-4 VERDICT
            # weak #6). Packed input has no host form: under 'auto' it
            # stays on the batched path (densifying it to reach scipy
            # would reintroduce the host-RAM blowup the sparse plane
            # removes); an EXPLICIT engine='host' pin still wins and
            # routes to the host fan-out. fit_would_pack decides from
            # indptr alone, so this bail runs BEFORE prepare_fit_X's
            # dense f32 copy is paid for host-routed input.
            return None
        try:
            # packable sparse input stays PACKED end to end: shared X
            # ships as the (idx, val) pair, the fit problems run the
            # O(nnz) contractions, and the finalize scoring runs the
            # polymorphic decision kernels on the same packed tree
            X_arr = prepare_fit_X(X, estimator)
        except Exception:
            return None

        n = X_arr.shape[0]
        n_splits = len(splits)
        train_masks = np.zeros((n_splits, n), dtype=np.float32)
        test_masks = np.zeros((n_splits, n), dtype=np.float32)
        for i, (train, test) in enumerate(splits):
            train_masks[i, train] = 1.0
            test_masks[i, test] = 1.0

        n_candidates = len(candidate_params)
        n_tasks_total = n_candidates * n_splits
        out = [None] * n_tasks_total
        est_cls = type(estimator)
        hyper_names = list(getattr(est_cls, "_hyper_names", ()))
        # adaptive (ASHA) bookkeeping: lanes killed by a rung in THIS
        # fit vs kills restored from a resumed journal (already mapped
        # to error_score when they were journaled)
        adaptive = getattr(self, "adaptive", None)
        killed_gids = {}
        restored_killed = {}
        any_dispatched = False
        y_classes = (
            np.unique(y) if adaptive is not None and y is not None else None
        )

        for static_overrides, cand_indices in buckets.values():
            bucket_est = clone(estimator)
            if static_overrides:
                bucket_est.set_params(**static_overrides)
            try:
                data, meta = bucket_est._prep_fit_data(
                    X_arr, y, sample_weight
                )
            except Exception:
                # estimator-level input validation failures must flow
                # through the host path so the error_score contract
                # (raise vs numeric substitute) applies per task
                return None
            static_cfg = bucket_est._static_config(meta)
            static = _freeze(static_cfg)
            kernel_key = _cv_kernel_key(
                est_cls, meta, static, scorer_specs, self.return_train_score
            )
            kernel = _cached_cv_kernel(
                est_cls, meta, static, scorer_specs,
                self.return_train_score, key=kernel_key,
            )
            # all leaves stay host-staged: batched_map performs the one
            # sharded placement (through the reuse-broadcast cache when
            # enabled — data["X"] is the SAME host array across buckets,
            # so multi-bucket grids re-place it for free on cache hits)
            shared = {
                "X": data["X"],
                "y": data["y"],
                "sw": data["sw"],
                "aux": extract_aux(data),
                "train_masks": train_masks,
                "test_masks": test_masks,
            }
            # stack task axis: bucket candidates × folds, split fastest.
            # gids carries each lane's GLOBAL task id — the durable
            # identity the checkpoint journal keys on; journaled tasks
            # are restored from the journal and leave the task axis.
            task_hyper = {name: [] for name in hyper_names}
            split_ids = []
            gids = []
            for cand_idx in cand_indices:
                cand = candidate_params[cand_idx]
                for s in range(n_splits):
                    gid = cand_idx * n_splits + s
                    if (checkpoint is not None
                            and gid in checkpoint.completed):
                        row = dict(checkpoint.completed[gid])
                        # a journaled rung kill restores AS a kill: the
                        # row already carries its error_score values,
                        # and the tag feeds the rung_ column
                        rk = row.pop("rung_killed", None)
                        if rk is not None:
                            restored_killed[gid] = int(rk)
                        out[gid] = row
                        continue
                    for name in hyper_names:
                        task_hyper[name].append(float(hyper_float(
                            cand.get(name, getattr(bucket_est, name))
                        )))
                    split_ids.append(s)
                    gids.append(gid)
            if not gids:
                continue  # whole bucket restored from the journal
            any_dispatched = True
            gids = np.asarray(gids, dtype=np.int64)
            task_args = {
                "hyper": {
                    k: np.asarray(v, dtype=np.float32)
                    for k, v in task_hyper.items()
                },
                "split": np.asarray(split_ids, dtype=np.int32),
            }
            specs = row_sharded_specs(backend, shared, _CV_SAMPLE_AXES)
            n_bucket = len(split_ids)
            # convergence-compacted path: iteration-sliced solvers +
            # live-task compaction, for families that support sliced
            # fits on buckets big enough to span several rounds
            n_slice = iterative_fit_supported(
                backend, est_cls, n_bucket, static_cfg.get("max_iter")
            )
            inv = None
            disp_gids = gids
            if n_slice is not None:
                # cost-ordered round packing (iterative path only: the
                # classic fused program is order-insensitive, and
                # keeping it untouched pins its bitwise behaviour)
                order = _cost_order(
                    est_cls, task_args["hyper"], task_args["split"]
                )
                if order is not None:
                    task_args = {
                        "hyper": {
                            k: v[order]
                            for k, v in task_args["hyper"].items()
                        },
                        "split": task_args["split"][order],
                    }
                    inv = np.argsort(order)
                    disp_gids = gids[order]
                # adaptive rung evaluator: resolve the rung metric to a
                # device scorer (None → warn-and-exhaustive via the
                # engaged flag in fit) and group each candidate's fold
                # lanes so they live and die together
                rung_ctrl = None
                rung_spec = None
                if adaptive is not None:
                    rung_spec = resolve_rung_scorer(
                        adaptive.metric, scorer_specs, self.refit,
                        y_classes, est_cls=est_cls,
                    )
                    if rung_spec is not None:
                        rung_ctrl = RungController(
                            adaptive.eta, adaptive.min_slices,
                            groups=disp_gids // n_splits,
                        )
                spec, iter_key = _cv_iterative_spec(
                    est_cls, meta, static, scorer_specs,
                    self.return_train_score, n_slice,
                    fallback=kernel, fallback_key=kernel_key,
                    rung_spec=rung_spec,
                )
                round_size = (
                    None if self.partitions in ("auto", None)
                    else parse_partitions(self.partitions, n_bucket)
                )
                scores, round_timings = backend.batched_map_iterative(
                    spec, task_args, shared, round_size=round_size,
                    shared_specs=specs, return_timings=True,
                    cache_key=iter_key,
                    on_round=self._round_journal(
                        checkpoint, disp_gids, rung_ctrl=rung_ctrl
                    ),
                    rung=rung_ctrl,
                )
                if rung_ctrl is not None:
                    # engaged only if the compacted slice loop actually
                    # ran the rungs — a backend downgrade (multi-process
                    # mesh, OOM/fault fallback) deactivates the
                    # controller, and fit's could-not-engage warning
                    # must fire for it
                    if rung_ctrl.active:
                        self._adaptive_engaged_ = True
                    # controller ids are dispatch-order task-axis
                    # indices; disp_gids maps them back to global
                    # (candidate x fold) ids
                    for disp_idx, r in rung_ctrl.killed.items():
                        killed_gids[int(disp_gids[disp_idx])] = int(r)
            else:
                round_size = parse_partitions(self.partitions, n_bucket)
                scores, round_timings = backend.batched_map(
                    kernel, task_args, shared, round_size=round_size,
                    shared_specs=specs,
                    return_timings=True, cache_key=kernel_key,
                    on_round=self._round_journal(checkpoint, disp_gids),
                )
            annotate_round_kernel_mode(backend, meta)
            # per-task fit_time = its round's measured wall / tasks in
            # that round (fit+score run fused in one kernel, so the
            # whole round wall is recorded as fit_time; score_time is
            # structurally 0 on the batched path). Honest per-round
            # measurement, not a uniform smear over the whole search.
            per_task_time = np.concatenate([
                np.full(keep, wall / max(keep, 1))
                for wall, keep in round_timings
            ]) if round_timings else np.zeros(len(split_ids))
            if inv is not None:
                # undo the cost permutation BEFORE unpacking so
                # cv_results_ rows keep candidate order (round packing
                # is a scheduler detail, invisible in the artifact)
                scores = {k: np.asarray(v)[inv] for k, v in scores.items()}
                per_task_time = per_task_time[inv]
            # unpack into global task order (gids maps the bucket's
            # task axis — minus journal-restored lanes — back to
            # (candidate x fold) ids)
            for t, gid in enumerate(gids):
                out[gid] = {k: float(v[t]) for k, v in scores.items()}
                out[gid]["fit_time"] = float(per_task_time[t])
                out[gid]["score_time"] = 0.0
        # adaptive rung kills map to error_score rows (one warning, the
        # rung recorded for the rung_ column and re-journaled so a
        # resume restores the kill); the lane quarantine then handles
        # genuinely diverged lanes, skipping the killed rows so they
        # are neither double-reported nor raised on
        _apply_rung_retirement(
            out, killed_gids, self.error_score, checkpoint=checkpoint
        )
        if adaptive is not None and not any_dispatched:
            # every task restored from the journal: the resumed results
            # ARE the journaled adaptive race — nothing fell back, so
            # the could-not-engage warning must not fire
            self._adaptive_engaged_ = True
        self._rung_killed_gids_ = {**restored_killed, **killed_gids}
        _quarantine_nonfinite(
            out, self.error_score, exempt=set(self._rung_killed_gids_)
        )
        return out

    def _run_streamed_search(self, backend, estimator, dataset, y,
                             candidate_params, splits, fit_params,
                             checkpoint=None):
        """The out-of-core CV search: (candidate × fold) tasks fit
        through the family's streamed driver (``models/streaming``) —
        fold selection is an O(n) fold-id vector sliced per block and
        composed into the fit weights on device — then one streamed
        scoring pass accumulates each task's decomposable metric
        statistics. Everything X-sized stays on disk; per-task results
        feed the ordinary ``_format_results`` schema.

        With a ``checkpoint`` (grid signature keyed on the dataset's
        ``content_digest``), journaled tasks restore instead of
        re-fitting — whole (candidate, fold) lanes drop out of the
        streamed task batch — and fresh completions journal as each
        bucket's scoring pass lands."""
        import jax.numpy as jnp

        from ..models.linear import _freeze, hyper_float
        from ..models.streaming import stream_fit_tasks, stream_scores

        if self.preds:
            raise ValueError(
                "preds=True needs resident out-of-fold predictions; "
                "not supported with ChunkedDataset input"
            )
        est_cls = type(estimator)
        if getattr(est_cls, "_stream_fit_kind", None) is None:
            raise ValueError(
                f"{est_cls.__name__} has no streamed fit driver; "
                "ChunkedDataset search supports the linear families "
                "(LogisticRegression, LinearSVC, SGDClassifier, the "
                "Ridge family) and the boosting pair "
                "(DistHistGradientBoostingClassifier/Regressor). "
                "Materialise the dataset for other estimators."
            )
        if getattr(estimator, "engine", None) == "host":
            raise ValueError(
                "engine='host' cannot fit a ChunkedDataset (the f64 "
                "host engine needs X resident); use engine='auto'/'xla'"
            )
        scorer_specs = _resolve_stream_scoring(estimator, self.scoring, y)
        n = dataset.n_rows
        n_splits = len(splits)
        # adaptive (ASHA) bookkeeping, the streamed mirror of the
        # batched path's: rungs fire at block-pass boundaries inside
        # the streamed drivers (an L-BFGS iteration / SGD epoch =
        # one whole-dataset pass), scored with one extra pass of
        # decomposable sufficient statistics over the already-resident
        # blocks — never a host gather of predictions
        adaptive = getattr(self, "adaptive", None)
        killed_gids = {}
        restored_killed = {}
        any_dispatched = False
        y_classes = (
            np.unique(y) if adaptive is not None and y is not None else None
        )
        sw_param, sw_ok = full_length_sample_weight(fit_params, n)
        extra = [k for k in fit_params if k != "sample_weight"]
        if not sw_ok or extra:
            raise ValueError(
                "streamed search supports only a full-length "
                f"sample_weight fit param; got {sorted(fit_params)}"
            )
        sw = sw_param if sw_param is not None else dataset.load_sw()
        fold_id = _partition_fold_ids(splits, n)
        buckets = _candidate_buckets(estimator, candidate_params)
        if buckets is None:
            raise ValueError(
                "streamed search candidates may only vary the "
                "estimator's batchable hypers "
                f"({getattr(est_cls, '_hyper_names', ())}) and declared "
                f"statics ({getattr(est_cls, '_static_names', ())})"
            )
        out = [None] * (len(candidate_params) * n_splits)
        restored = set()
        if checkpoint is not None and checkpoint.completed:
            for gid, row in checkpoint.completed.items():
                if 0 <= gid < len(out):
                    row = dict(row)
                    # a journaled rung kill restores AS a kill: the row
                    # already carries its error_score values, and the
                    # tag (stripped for aggregate_score_dicts' uniform
                    # keys) feeds the rung_ column on resume
                    rk = row.pop("rung_killed", None)
                    if rk is not None:
                        restored_killed[gid] = int(rk)
                    out[gid] = row
                    restored.add(gid)
        hyper_names = list(getattr(est_cls, "_hyper_names", ()))
        if est_cls._stream_fit_kind == "gram" and "alpha" not in hyper_names:
            hyper_names.append("alpha")  # LinearRegression's fixed 0.0

        def derive(block, task):
            # fold masking by weights, the batched path's idiom: user
            # sample_weight weights the FIT; scoring uses raw masks
            fit_w = block["sw"] * (
                block["fold"] != task["split"]
            ).astype(jnp.float32)
            return block["X"], block["y"], fit_w, task["hyper"]

        # scoring weights are raw fold masks (sklearn scorers called
        # without sample_weight). Tail-padding rows carry fold id -1:
        # that never EQUALS a split id (test mask safe by construction)
        # but it does DIFFER from every split id, so the train mask
        # must exclude it explicitly — a padded zero row would
        # otherwise score as a correct class-0 hit
        weight_fns = {
            "test": lambda block, task: (
                block["fold"] == task["split"]
            ).astype(jnp.float32),
        }
        if self.return_train_score:
            weight_fns["train"] = lambda block, task: (
                (block["fold"] != task["split"]) & (block["fold"] >= 0)
            ).astype(jnp.float32)

        for static_overrides, cand_indices in buckets.values():
            bucket_est = clone(estimator)
            if static_overrides:
                bucket_est.set_params(**static_overrides)
            task_hyper = {name: [] for name in hyper_names}
            split_ids, gids = [], []
            for cand_idx in cand_indices:
                cand = candidate_params[cand_idx]
                for s in range(n_splits):
                    gid = cand_idx * n_splits + s
                    if gid in restored:
                        # journaled by a killed run of the same
                        # signature: the whole lane drops out of the
                        # streamed fit/score batch
                        continue
                    for name in hyper_names:
                        task_hyper[name].append(float(hyper_float(
                            cand.get(name, getattr(bucket_est, name))
                        )))
                    split_ids.append(s)
                    gids.append(gid)
            if not gids:
                continue
            any_dispatched = True
            gids_arr = np.asarray(gids, dtype=np.int64)
            y_enc, sw_arr, meta = bucket_est._prep_stream_fit(
                dataset, y, sw
            )
            static_cfg = bucket_est._static_config(meta)
            static = _freeze(static_cfg)
            task_args = {
                "hyper": {
                    k: np.asarray(v, dtype=np.float32)
                    for k, v in task_hyper.items()
                },
                "split": np.asarray(split_ids, dtype=np.int32),
            }
            row_arrays = {"y": y_enc, "sw": sw_arr, "fold": fold_id}
            # adaptive rung evaluator: resolve the rung metric to a
            # decomposable streamed scorer (None → warn-and-exhaustive
            # via the engaged flag in fit) and group each candidate's
            # fold lanes so they live and die together. The gram
            # driver's direct solve has no pass boundaries — adaptive
            # over it stays exhaustive by construction.
            rung_ctrl = None
            rung_pair = None
            if adaptive is not None and est_cls._stream_fit_kind != "gram":
                rung_pair = resolve_stream_rung(
                    adaptive.metric, scorer_specs, self.refit,
                    y_classes, est_cls=est_cls,
                )
                if rung_pair is not None:
                    rung_ctrl = RungController(
                        adaptive.eta, adaptive.min_slices,
                        groups=gids_arr // n_splits,
                    )
            rung_hook = None
            if rung_ctrl is not None:
                rung_weight = {"test": weight_fns["test"]}

                def rung_hook(pass_idx, live_ids, make_params,
                              _ctrl=rung_ctrl, _pair=rung_pair,
                              _ta=task_args, _meta=meta, _static=static,
                              _rw=rung_weight):
                    # min_slices is the rung cadence in whole-dataset
                    # block passes on this path
                    if not _ctrl.due(pass_idx):
                        return np.empty(0, np.int64)
                    live_tasks = {
                        "hyper": {
                            k: v[live_ids]
                            for k, v in _ta["hyper"].items()
                        },
                        "split": _ta["split"][live_ids],
                    }
                    # one extra pass of sufficient statistics over the
                    # already-resident blocks; stats=None continues the
                    # fit's live accounting dict (backend.last_round_stats)
                    sc = stream_scores(
                        backend, est_cls, _meta, _static, dataset,
                        row_arrays, live_tasks, make_params(),
                        [_pair], _rw, key_extra=("cv", "rung"),
                    )
                    return _ctrl.decide(
                        live_ids, sc["test_rung"], pass_idx
                    )

            t0 = time.perf_counter()
            # key_extra distinguishes this fold-masked derive from the
            # plain single-fit derive in the structural compile keys —
            # same family/static/meta, different program
            params = stream_fit_tasks(
                backend, est_cls, meta, static, dataset, row_arrays,
                task_args, derive=derive, key_extra=("cv",),
                rung_hook=rung_hook,
            )
            fit_wall = time.perf_counter() - t0
            if rung_ctrl is not None:
                if rung_ctrl.active:
                    self._adaptive_engaged_ = True
                # controller ids are the bucket's task-axis indices;
                # gids_arr maps them back to global (candidate × fold)
                for lid, r in rung_ctrl.killed.items():
                    killed_gids[int(gids_arr[lid])] = int(r)
                if rung_ctrl.history:
                    stats_live = backend.last_round_stats
                    stats_live["rung_survivors"] = ",".join(
                        str(int(h["n_live"] - h["n_killed"]))
                        for h in rung_ctrl.history
                    )
            stats = backend.last_round_stats
            t0 = time.perf_counter()
            scores = stream_scores(
                backend, est_cls, meta, static, dataset, row_arrays,
                task_args, params, scorer_specs, weight_fns,
                stats=stats, key_extra=("cv",),
            )
            score_wall = time.perf_counter() - t0
            per_fit = fit_wall / max(len(gids), 1)
            per_score = score_wall / max(len(gids), 1)
            for t, gid in enumerate(gids):
                row = {k: float(v[t]) for k, v in scores.items()}
                row["fit_time"] = per_fit
                row["score_time"] = per_score
                out[gid] = row
                # rung-killed lanes are NOT journaled here: their rows
                # carry a kill-time carry's raw scores, and a crash
                # before _apply_rung_retirement's corrective tagged
                # record would resume them as legitimately completed
                if checkpoint is not None and gid not in killed_gids:
                    checkpoint.record(gid, row)
        # adaptive rung kills map to error_score rows (one warning, the
        # rung recorded for the rung_ column and journaled ONCE tagged
        # rung_killed so a resume restores the kill); the lane
        # quarantine then handles genuinely diverged lanes, skipping
        # the killed rows so they are neither double-reported nor
        # raised on
        _apply_rung_retirement(
            out, killed_gids, self.error_score, checkpoint=checkpoint,
            context="streamed",
        )
        if adaptive is not None and not any_dispatched:
            # every task restored from the journal: the resumed results
            # ARE the journaled adaptive race — nothing fell back, so
            # the could-not-engage warning must not fire
            self._adaptive_engaged_ = True
        self._rung_killed_gids_ = {**restored_killed, **killed_gids}
        _quarantine_nonfinite(
            out, self.error_score, context="streamed",
            exempt=set(self._rung_killed_gids_),
        )
        return out

    @staticmethod
    def _round_journal(checkpoint, disp_gids, rung_ctrl=None):
        """``on_round`` callback journaling each gathered round's score
        rows under their global task ids (``disp_gids`` is in DISPATCH
        order — the cost permutation, when active). Times are journaled
        as 0.0: per-round walls are only attributable after the whole
        call, and a resumed task's fit cost was paid by the killed
        process anyway. None checkpoint → no callback (zero overhead).

        Rung-killed lanes are SKIPPED here: their finalize rows carry a
        half-trained carry's raw scores, and journaling those would let
        a crash before :func:`_apply_rung_retirement`'s corrective
        ``rung_killed``-tagged record resume them as legitimately
        completed rows (the kill map is final by the time the finalize
        phase — the only phase that fires ``on_round`` on the compacted
        path — gathers). An unjournaled kill simply re-runs on resume.
        """
        if checkpoint is None:
            return None

        def journal(start, round_out):
            keys = list(round_out)
            n = len(np.asarray(round_out[keys[0]]))
            for i in range(n):
                if rung_ctrl is not None and (start + i) in rung_ctrl.killed:
                    continue
                row = {k: float(np.asarray(round_out[k])[i]) for k in keys}
                row["fit_time"] = 0.0
                row["score_time"] = 0.0
                checkpoint.record(int(disp_gids[start + i]), row)

        return journal

    # ------------------------------------------------------------------
    def _format_results(self, candidate_params, scorers, n_splits, out):
        """sklearn-schema cv_results_ (reference search.py:457-533)."""
        n_candidates = len(candidate_params)
        agg = aggregate_score_dicts(out)
        results = {}

        def _store(key_name, array, weights=None, splits=False, rank=False):
            array = np.asarray(array, dtype=np.float64).reshape(
                n_candidates, n_splits
            )
            if splits:
                for i in range(n_splits):
                    results[f"split{i}_{key_name}"] = array[:, i]
            means = np.average(array, axis=1, weights=weights)
            results[f"mean_{key_name}"] = means
            stds = np.sqrt(
                np.average((array - means[:, None]) ** 2, axis=1, weights=weights)
            )
            results[f"std_{key_name}"] = stds
            if rank:
                results[f"rank_{key_name}"] = np.asarray(
                    rankdata(-_nan_as_worst(means), method="min"),
                    dtype=np.int32,
                )

        _store("fit_time", agg["fit_time"])
        _store("score_time", agg["score_time"])

        param_results = {}
        for cand_idx, params in enumerate(candidate_params):
            for name, value in params.items():
                key = f"param_{name}"
                if key not in param_results:
                    param_results[key] = MaskedArray(
                        np.empty(n_candidates, dtype=object), mask=True
                    )
                param_results[key][cand_idx] = value
        results.update(param_results)
        results["params"] = candidate_params

        scorer_names = (
            scorers.keys() if isinstance(scorers, dict) else ["score"]
        )
        for name in scorer_names:
            _store(f"test_{name}", agg[f"test_{name}"], splits=True, rank=True)
            if self.return_train_score:
                _store(f"train_{name}", agg[f"train_{name}"], splits=True)
        return results

    def _out_of_fold_preds(self, estimator, X, y, splits, fit_params):
        """Out-of-fold predict_proba at the best params, falling back to
        predict for estimators without probabilities (reference
        search.py:551-560 wraps predict_proba in try/except predict)."""
        preds = []
        for train, test in splits:
            est = clone(estimator).set_params(**self.best_params_)
            X_train, y_train = safe_split(est, X, y, train)
            X_test, _ = safe_split(est, X, y, test, train)
            est.fit(X_train, y_train, **index_fit_params(X, fit_params, train))
            try:
                preds.append(est.predict_proba(X_test))
            except (AttributeError, NotImplementedError):
                preds.append(est.predict(X_test))
        if preds and np.ndim(preds[0]) == 1:
            # predict fallback yields 1D fold slices; vstack would fail
            # on unequal fold sizes (latent reference bug — not kept)
            return np.concatenate(preds)
        return np.vstack(preds)

    # ------------------------------------------------------------------
    # post-fit delegation (reference search.py:875-908 used
    # if_delegate_has_method; we delegate dynamically)
    def _check_refit(self, method):
        if not self.refit:
            raise AttributeError(
                f"{method} is not available: refit=False. "
            )

    @property
    def classes_(self):
        self._check_refit("classes_")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.classes_

    def predict(self, X):
        self._check_refit("predict")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.predict(X)

    def predict_proba(self, X):
        self._check_refit("predict_proba")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.predict_proba(X)

    def predict_log_proba(self, X):
        self._check_refit("predict_log_proba")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.predict_log_proba(X)

    def decision_function(self, X):
        self._check_refit("decision_function")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.decision_function(X)

    def transform(self, X):
        self._check_refit("transform")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.transform(X)

    def inverse_transform(self, Xt):
        self._check_refit("inverse_transform")
        check_is_fitted(self, "best_estimator_")
        return self.best_estimator_.inverse_transform(Xt)

    def score(self, X, y=None):
        check_is_fitted(self, "best_estimator_")
        if self.scorer_ is None:
            raise ValueError("No scorer available")
        scorer = (
            self.scorer_[self.refit] if self.multimetric_ else self.scorer_
        )
        return scorer(self.best_estimator_, X, y)


class DistGridSearchCV(DistBaseSearchCV):
    """Exhaustive grid search with distributed fits (reference
    search.py:584-645).

    Same contract as sklearn's GridSearchCV; ``backend`` plays the role
    of sk-dist's ``sc`` (``backend=None`` = local, the sc=None analogue).
    """

    def __init__(self, estimator, param_grid, backend=None, partitions="auto",
                 cv=5, scoring=None, refit=True, return_train_score=False,
                 error_score=np.nan, n_jobs=None, preds=False, verbose=0,
                 adaptive=None):
        super().__init__(
            estimator, backend=backend, partitions=partitions, cv=cv,
            scoring=scoring, refit=refit,
            return_train_score=return_train_score, error_score=error_score,
            n_jobs=n_jobs, preds=preds, verbose=verbose, adaptive=adaptive,
        )
        self.param_grid = param_grid

    def _get_param_iterator(self):
        from sklearn.model_selection import ParameterGrid

        return ParameterGrid(self.param_grid)


class DistRandomizedSearchCV(DistBaseSearchCV):
    """Randomized search over param distributions (reference
    search.py:648-714)."""

    def __init__(self, estimator, param_distributions, backend=None,
                 partitions="auto", n_iter=10, random_state=None, cv=5,
                 scoring=None, refit=True, return_train_score=False,
                 error_score=np.nan, n_jobs=None, preds=False, verbose=0,
                 adaptive=None):
        super().__init__(
            estimator, backend=backend, partitions=partitions, cv=cv,
            scoring=scoring, refit=refit,
            return_train_score=return_train_score, error_score=error_score,
            n_jobs=n_jobs, preds=preds, verbose=verbose, adaptive=adaptive,
        )
        self.param_distributions = param_distributions
        self.n_iter = n_iter
        self.random_state = random_state

    def _get_param_iterator(self):
        from sklearn.model_selection import ParameterSampler

        n_iter = check_n_iter(self.n_iter, self.param_distributions)
        return ParameterSampler(
            self.param_distributions, n_iter, random_state=self.random_state
        )


# ---------------------------------------------------------------------------
# DistMultiModelSearch (reference search.py:717-908)
# ---------------------------------------------------------------------------

def _sample_one(n_iter, param_distributions, random_state=None):
    """Sample param sets for one model (reference search.py:60-68)."""
    from sklearn.model_selection import ParameterSampler

    return list(
        ParameterSampler(
            param_distributions,
            n_iter=check_n_iter(n_iter, param_distributions),
            random_state=random_state,
        )
    )


def _raw_sampler(models, n_params=None, n=None, random_state=None):
    """Sample param sets for every model (reference search.py:71-90).
    Returns dicts {model_index, params_index, param_set}."""
    if n_params is None:
        if n is None:
            raise ValueError("Must supply either 'n_params' or 'n'")
        n_params = [n] * len(models)
    param_sets = []
    for index in range(len(models)):
        sampler = _sample_one(
            n_params[index], models[index][2], random_state=random_state
        )
        for sample_index, sample in enumerate(sampler):
            param_sets.append({
                "model_index": index,
                "params_index": sample_index,
                "param_set": sample,
            })
    return param_sets


def _validate_models(models):
    """Input validation (reference validation.py:32-96)."""
    if not models:
        raise ValueError("models must be a non-empty list of tuples")
    names = [m[0] for m in models]
    if len(set(names)) != len(names):
        raise ValueError(f"Duplicate model names: {names}")
    for m in models:
        if len(m) != 3:
            raise ValueError(
                "each model must be ('name', estimator, param_dict)"
            )
        name, est, params = m
        if not isinstance(name, str):
            raise ValueError(f"model name must be str, got {name!r}")
        if not hasattr(est, "fit"):
            raise ValueError(f"estimator {est!r} has no fit method")
        if not isinstance(params, dict):
            raise ValueError(f"param set must be dict, got {params!r}")
    return list(models)


class DistMultiModelSearch(BaseEstimator):
    """Randomized search across heterogeneous model families
    (reference search.py:717-908): ``models`` is a list of
    ``(name, estimator, param_distributions)`` tuples; ``n`` param sets
    are sampled per model, each scored by CV, and the winning
    (model, params) combination refit.

    Per-model execution reuses the grid-search scheduler, so a JAX
    estimator's candidates run as one batched device program while a
    host estimator in the same `models` list fans out over threads.
    """

    def __init__(self, models, backend=None, partitions="auto", n=5, cv=5,
                 scoring=None, random_state=None, verbose=0, refit=True,
                 n_jobs=None, adaptive=None):
        self.models = models
        self.backend = backend
        self.partitions = partitions
        self.n = n
        self.cv = cv
        self.scoring = scoring
        self.random_state = random_state
        self.verbose = verbose
        self.refit = refit
        self.n_jobs = n_jobs
        self.adaptive = adaptive

    def fit(self, X, y=None, groups=None, **fit_params):
        from sklearn.model_selection import check_cv

        check_adaptive(self.adaptive)
        check_estimator_backend(self, self.verbose)
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        models = _validate_models(self.models)
        is_classifier = (
            getattr(models[0][1], "_estimator_type", None) == "classifier"
        )
        cv = check_cv(self.cv, y, classifier=is_classifier)
        splits = list(cv.split(X, y, groups))
        n_splits = len(splits)
        param_sets = _raw_sampler(models, n=self.n,
                                  random_state=self.random_state)

        # evaluate model-by-model through the shared scheduler: each
        # model's candidates batch on device when possible; per-model
        # results come back in the FULL sklearn schema via the shared
        # _format_results (per-split columns, mean/std, fit/score
        # times, masked param arrays)
        per_model = []
        adaptive_engaged = False
        for index, (name, estimator, _dists) in enumerate(models):
            cands = [p["param_set"] for p in param_sets
                     if p["model_index"] == index]
            if not cands:
                continue
            scorers, multimetric = check_multimetric_scoring(
                estimator, self.scoring
            )
            if multimetric:
                raise ValueError(
                    "DistMultiModelSearch supports single-metric scoring"
                )
            # each model family races its own rungs (candidate sets of
            # different families are not score-comparable mid-solve);
            # the shim rides the exact grid-search scheduler, adaptive
            # included
            shim = DistBaseSearchCV(
                estimator, partitions=self.partitions, cv=self.cv,
                scoring=self.scoring, error_score=np.nan,
                n_jobs=self.n_jobs, verbose=self.verbose,
                adaptive=self.adaptive,
            )
            out = shim._run_search_tasks(
                backend, estimator, X, y, cands, splits, scorers, fit_params
            )
            full = shim._format_results(cands, scorers, n_splits, out)
            if self.adaptive is not None:
                full["rung_"] = rung_per_candidate(
                    len(cands), n_splits,
                    getattr(shim, "_rung_killed_gids_", {}),
                )
                adaptive_engaged |= getattr(
                    shim, "_adaptive_engaged_", False
                )
            per_model.append((index, name, cands, full))

        if self.adaptive is not None and not adaptive_engaged:
            warn_not_engaged("the multi-model search")
        results = self._merge_model_results(per_model, n_splits)
        score_vals = np.asarray(results["mean_test_score"], dtype=float)
        if score_vals.size == 0 or np.all(np.isnan(score_vals)):
            raise RuntimeError(
                "All candidate fits failed (every score is NaN)."
            )
        if self.verbose:
            for index, name, cands, full in per_model:
                seg = np.asarray(full["mean_test_score"], dtype=float)
                best = (
                    float(np.nanmax(seg)) if not np.all(np.isnan(seg))
                    else float("nan")
                )
                print(f"model_index={index} ({name}): "
                      f"best score {best:.6f}")
        best_index = int(np.nanargmax(score_vals))
        self.best_index_ = best_index
        self.best_model_index_ = int(results["model_index"][best_index])
        self.best_model_name_ = models[self.best_model_index_][0]
        self.best_params_ = results["params"][best_index]
        self.best_score_ = float(score_vals[best_index])
        # the reference set worst_score_ = best_score_ (a known bug,
        # search.py:836-837); we record the actual worst
        self.worst_score_ = float(np.nanmin(score_vals))
        self.cv_results_ = results
        self.n_splits_ = n_splits

        if self.refit:
            best = clone(models[self.best_model_index_][1])
            best.set_params(**self.best_params_)
            if y is not None:
                best.fit(X, y, **fit_params)
            else:
                best.fit(X, **fit_params)
            self.best_estimator_ = best
        self.models = [
            (name, clone(est), dists) for name, est, dists in self.models
        ]
        strip_runtime(self)
        return self

    @staticmethod
    def _merge_model_results(per_model, n_splits):
        """Stack the per-model ``_format_results`` dicts into ONE
        cross-model cv_results_ (sklearn schema + ``model_name`` /
        ``model_index``): numeric columns concatenate in model order,
        ``param_*`` masked arrays take the union of parameter names
        (masked where a model lacks the param), and ``rank_test_score``
        re-ranks across ALL models' candidates."""
        n_total = sum(len(cands) for _, _, cands, _ in per_model)
        num_keys = [
            "mean_fit_time", "std_fit_time", "mean_score_time",
            "std_score_time", "mean_test_score", "std_test_score",
        ] + [f"split{i}_test_score" for i in range(n_splits)]
        results = {
            key: np.concatenate([
                np.asarray(full[key], dtype=np.float64)
                for _, _, _, full in per_model
            ]) if per_model else np.empty(0)
            for key in num_keys
        }
        param_cols = {}
        params_list, names, model_idx = [], [], []
        offset = 0
        for index, name, cands, full in per_model:
            m = len(cands)
            for key, arr in full.items():
                if not key.startswith("param_"):
                    continue
                col = param_cols.get(key)
                if col is None:
                    col = MaskedArray(
                        np.empty(n_total, dtype=object), mask=True
                    )
                    param_cols[key] = col
                for j in range(m):
                    if not np.ma.getmaskarray(arr)[j]:
                        col[offset + j] = arr[j]
            params_list.extend(full["params"])
            names.extend([name] * m)
            model_idx.extend([index] * m)
            offset += m
        results.update(param_cols)
        results["params"] = params_list
        results["model_name"] = names
        results["model_index"] = model_idx
        if any("rung_" in full for _, _, _, full in per_model):
            results["rung_"] = np.concatenate([
                np.asarray(
                    full.get("rung_", np.full(len(cands), -1, np.int32)),
                    dtype=np.int32,
                )
                for _, _, cands, full in per_model
            ])
        # method="min" for sklearn-style integer ranks on ties (the base
        # search already did this; reference search.py:481-484)
        results["rank_test_score"] = np.asarray(
            rankdata(
                -_nan_as_worst(
                    np.asarray(results["mean_test_score"], dtype=float)
                ),
                method="min",
            ),
            dtype=np.int32,
        ) if n_total else np.empty(0, dtype=np.int32)
        return results

    # -- post-fit delegation -------------------------------------------
    def _check_is_fitted(self):
        if not self.refit:
            raise AttributeError(
                f"This {type(self).__name__} instance was initialized with "
                "refit=False; predict-side methods need refit=True."
            )
        check_is_fitted(self, "best_estimator_")

    def predict(self, X):
        self._check_is_fitted()
        return self.best_estimator_.predict(X)

    def predict_proba(self, X):
        self._check_is_fitted()
        return self.best_estimator_.predict_proba(X)

    def predict_log_proba(self, X):
        self._check_is_fitted()
        return self.best_estimator_.predict_log_proba(X)

    def decision_function(self, X):
        self._check_is_fitted()
        return self.best_estimator_.decision_function(X)

    def transform(self, X):
        self._check_is_fitted()
        return self.best_estimator_.transform(X)

    def inverse_transform(self, Xt):
        self._check_is_fitted()
        return self.best_estimator_.inverse_transform(Xt)

    @property
    def classes_(self):
        self._check_is_fitted()
        return self.best_estimator_.classes_
