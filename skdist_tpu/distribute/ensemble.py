"""
Distributed forests (reference ``/root/reference/skdist/distribute/
ensemble.py:154-716``).

The reference's concrete classes are diamond-inheritance shims that add
``sc``/``partitions`` to sklearn forests and swap the per-tree loop for
``sc.parallelize(states).map(_build_trees).collect()``
(ensemble.py:304-322). Here the same shape holds, one level down: the
Dist* classes add ``backend``/``partitions`` to the skdist_tpu forest
kernels and route the tree axis through ``backend.batched_map``, so
trees shard over the TPU mesh in rounds instead of Spark executors.
Post-fit, the backend handle is stripped so the artifact pickles clean
(the reference's ``del self.sc``, ensemble.py:335).
"""

import numpy as np

from ..base import strip_runtime
from ..models.forest import (
    ExtraTreesClassifier,
    ExtraTreesRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    RandomTreesEmbedding,
)
from ..parallel import parse_partitions, resolve_backend
from ..utils.validation import check_estimator_backend, safe_indexing

__all__ = [
    "DistRandomForestClassifier",
    "DistRandomForestRegressor",
    "DistExtraTreesClassifier",
    "DistExtraTreesRegressor",
    "DistRandomTreesEmbedding",
    "get_oof",
    "get_single_oof",
]


def get_single_oof(clf, X, y, train_index, test_index):
    """Fit on the train index, predict_proba on the test index
    (reference ensemble.py:112-127)."""
    X_train = safe_indexing(X, train_index)
    X_test = safe_indexing(X, test_index)
    y = np.asarray(y)
    clf.fit(X_train, y[train_index])
    return test_index, clf.predict_proba(X_test)


def get_oof(clf, X, y, n_splits=5):
    """Out-of-fold probabilities + final full fit (reference
    ensemble.py:130-151)."""
    from sklearn.model_selection import KFold

    y = np.asarray(y)
    oof_train = np.zeros((y.shape[0], len(np.unique(y))))
    # KFold.split only needs len(X); pass X as-is so ragged lists work
    for train_index, test_index in KFold(n_splits=n_splits).split(X):
        test_index, proba = get_single_oof(
            clf, X, y, train_index, test_index
        )
        oof_train[test_index] = proba
    clf.fit(X, y)
    return clf, oof_train


class _DistForestMixin:
    """Adds backend/partitions routing to a forest class: the host
    class's ``fit`` calls ``_resolve_fit_backend`` for its
    ``batched_map`` dispatch."""

    def _resolve_fit_backend(self):
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        n_more = self.n_estimators - (
            int(self._trees["feat"].shape[0])
            if self.warm_start and hasattr(self, "_trees")
            else 0
        )
        round_size = parse_partitions(self.partitions, max(n_more, 1))
        return backend, round_size

    def fit(self, X, y=None, sample_weight=None):
        check_estimator_backend(self, self.verbose)
        super().fit(X, y, sample_weight=sample_weight)
        strip_runtime(self)
        return self


class DistRandomForestClassifier(_DistForestMixin, RandomForestClassifier):
    """Reference ensemble.py:365-421."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features="sqrt",
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=True, oob_score=False,
                 class_weight=None, warm_start=False,
                 random_state=None, n_jobs=None, verbose=0):
        RandomForestClassifier.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, class_weight=class_weight,
            warm_start=warm_start, random_state=random_state, n_jobs=n_jobs,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistRandomForestRegressor(_DistForestMixin, RandomForestRegressor):
    """Reference ensemble.py:505-559."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features=1.0,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=True, oob_score=False,
                 warm_start=False, random_state=None, n_jobs=None, verbose=0):
        RandomForestRegressor.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistExtraTreesClassifier(_DistForestMixin, ExtraTreesClassifier):
    """Reference ensemble.py:424-480."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features="sqrt",
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=False, oob_score=False,
                 class_weight=None, warm_start=False,
                 random_state=None, n_jobs=None, verbose=0):
        ExtraTreesClassifier.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, class_weight=class_weight,
            warm_start=warm_start, random_state=random_state, n_jobs=n_jobs,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistExtraTreesRegressor(_DistForestMixin, ExtraTreesRegressor):
    """Reference ensemble.py:562-616."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features=1.0,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=False, oob_score=False,
                 warm_start=False, random_state=None, n_jobs=None, verbose=0):
        ExtraTreesRegressor.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistRandomTreesEmbedding(_DistForestMixin, RandomTreesEmbedding):
    """Reference ensemble.py:619-716."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=5, n_bins=32, min_samples_split=2,
                 min_samples_leaf=1, min_impurity_decrease=0.0,
                 sparse_output=True, warm_start=False, random_state=None,
                 n_jobs=None, verbose=0):
        RandomTreesEmbedding.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease,
            sparse_output=sparse_output, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose

    def fit_transform(self, X, y=None, sample_weight=None):
        return self.fit(X, y, sample_weight=sample_weight).transform(X)
