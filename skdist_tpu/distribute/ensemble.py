"""
Distributed forests (reference ``/root/reference/skdist/distribute/
ensemble.py:154-716``).

The reference's concrete classes are diamond-inheritance shims that add
``sc``/``partitions`` to sklearn forests and swap the per-tree loop for
``sc.parallelize(states).map(_build_trees).collect()``
(ensemble.py:304-322). Here the same shape holds, one level down: the
Dist* classes add ``backend``/``partitions`` to the skdist_tpu forest
kernels and route the tree axis through ``backend.batched_map``, so
trees shard over the TPU mesh in rounds instead of Spark executors.
With the default LocalBackend (the ``sc=None`` analogue) on a platform
whose calibration names it, fits run the host C engine instead
(``models/native_forest.py`` — measured faster than sklearn's Cython
trees, ``models/hist_calib.json``); both engines produce the same
stacked-tree artifact, so predict/OOB/pickle are engine-agnostic.
Post-fit, the backend handle is stripped so the artifact pickles clean
(the reference's ``del self.sc``, ensemble.py:335).
"""

import numpy as np

from ..base import BaseEstimator, strip_runtime
from ..models.forest import (
    ExtraTreesClassifier,
    ExtraTreesRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    RandomTreesEmbedding,
)
from ..parallel import parse_partitions, resolve_backend
from ..utils.validation import check_estimator_backend, safe_indexing

__all__ = [
    "DistForestClassifier",
    "DistForestRegressor",
    "DistRandomForestClassifier",
    "DistRandomForestRegressor",
    "DistExtraTreesClassifier",
    "DistExtraTreesRegressor",
    "DistRandomTreesEmbedding",
    "get_oof",
    "get_single_oof",
]


def get_single_oof(clf, X, y, train_index, test_index):
    """Fit on the train index, predict_proba on the test index
    (reference ensemble.py:112-127)."""
    X_train = safe_indexing(X, train_index)
    X_test = safe_indexing(X, test_index)
    y = np.asarray(y)
    clf.fit(X_train, y[train_index])
    return test_index, clf.predict_proba(X_test)


def get_oof(clf, X, y, n_splits=5):
    """Out-of-fold probabilities + final full fit (reference
    ensemble.py:130-151)."""
    from sklearn.model_selection import KFold

    y = np.asarray(y)
    oof_train = np.zeros((y.shape[0], len(np.unique(y))))
    # KFold.split only needs len(X); pass X as-is so ragged lists work
    for train_index, test_index in KFold(n_splits=n_splits).split(X):
        test_index, proba = get_single_oof(
            clf, X, y, train_index, test_index
        )
        oof_train[test_index] = proba
    clf.fit(X, y)
    return clf, oof_train


class _DistForestMixin:
    """Adds backend/partitions routing to a forest class: the host
    class's ``fit`` calls ``_resolve_fit_backend`` for its
    ``batched_map`` dispatch."""

    def _resolve_fit_backend(self):
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        n_more = self.n_estimators - (
            int(self._trees["feat"].shape[0])
            if self.warm_start and hasattr(self, "_trees")
            else 0
        )
        round_size = parse_partitions(self.partitions, max(n_more, 1))
        return backend, round_size

    def fit(self, X, y=None, sample_weight=None):
        check_estimator_backend(self, self.verbose)
        super().fit(X, y, sample_weight=sample_weight)
        strip_runtime(self)
        return self


class DistRandomForestClassifier(_DistForestMixin, RandomForestClassifier):
    """Reference ensemble.py:365-421."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features="sqrt",
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=True, oob_score=False,
                 class_weight=None, warm_start=False,
                 random_state=None, n_jobs=None, verbose=0,
                 hist_mode="auto"):
        RandomForestClassifier.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, class_weight=class_weight,
            warm_start=warm_start, random_state=random_state, n_jobs=n_jobs,
            hist_mode=hist_mode,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistRandomForestRegressor(_DistForestMixin, RandomForestRegressor):
    """Reference ensemble.py:505-559."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features=1.0,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=True, oob_score=False,
                 warm_start=False, random_state=None, n_jobs=None, verbose=0,
                 hist_mode="auto"):
        RandomForestRegressor.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs, hist_mode=hist_mode,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistExtraTreesClassifier(_DistForestMixin, ExtraTreesClassifier):
    """Reference ensemble.py:424-480."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features="sqrt",
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=False, oob_score=False,
                 class_weight=None, warm_start=False,
                 random_state=None, n_jobs=None, verbose=0,
                 hist_mode="auto"):
        ExtraTreesClassifier.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, class_weight=class_weight,
            warm_start=warm_start, random_state=random_state, n_jobs=n_jobs,
            hist_mode=hist_mode,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistExtraTreesRegressor(_DistForestMixin, ExtraTreesRegressor):
    """Reference ensemble.py:562-616."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=8, n_bins=32, max_features=1.0,
                 min_samples_split=2, min_samples_leaf=1,
                 min_impurity_decrease=0.0, bootstrap=False, oob_score=False,
                 warm_start=False, random_state=None, n_jobs=None, verbose=0,
                 hist_mode="auto"):
        ExtraTreesRegressor.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, max_features=max_features,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease, bootstrap=bootstrap,
            oob_score=oob_score, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs, hist_mode=hist_mode,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose


class DistRandomTreesEmbedding(_DistForestMixin, RandomTreesEmbedding):
    """Reference ensemble.py:619-716."""

    def __init__(self, n_estimators=100, backend=None, partitions="auto",
                 max_depth=5, n_bins=32, min_samples_split=2,
                 min_samples_leaf=1, min_impurity_decrease=0.0,
                 sparse_output=True, warm_start=False, random_state=None,
                 n_jobs=None, verbose=0, hist_mode="auto"):
        RandomTreesEmbedding.__init__(
            self, n_estimators=n_estimators, max_depth=max_depth,
            n_bins=n_bins, min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_impurity_decrease=min_impurity_decrease,
            sparse_output=sparse_output, warm_start=warm_start,
            random_state=random_state, n_jobs=n_jobs, hist_mode=hist_mode,
        )
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose

    def fit_transform(self, X, y=None, sample_weight=None):
        return self.fit(X, y, sample_weight=sample_weight).transform(X)


# ---------------------------------------------------------------------------
# bring-your-own-tree intermediates (reference DistForestClassifier /
# DistForestRegressor, ensemble.py:343-363 and 483-504): a forest over an
# ARBITRARY sklearn-style base estimator. The Dist* classes above are the
# TPU-native fast path over this package's histogram-tree kernels; these
# two keep the reference's public extension point — any estimator with
# fit/predict(_proba) fans out one-task-per-tree on the host backend.
# ---------------------------------------------------------------------------

class _DistBaseEstimatorForest(BaseEstimator):
    def __init__(self, base_estimator, backend=None, partitions="auto",
                 n_estimators=100, bootstrap=True, random_state=None,
                 n_jobs=None, verbose=0):
        self.base_estimator = base_estimator
        self.backend = backend
        self.partitions = partitions
        self.n_estimators = n_estimators
        self.bootstrap = bootstrap
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.verbose = verbose

    def fit(self, X, y, **fit_params):
        from sklearn.base import clone as sk_clone

        check_estimator_backend(self, self.verbose)
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        n = len(X) if not hasattr(X, "shape") else X.shape[0]
        y_arr = np.asarray(y)
        self._set_fit_targets(y_arr)
        rng = np.random.RandomState(self.random_state)
        seeds = rng.randint(np.iinfo(np.int32).max, size=self.n_estimators)
        supports_weight = True
        try:
            import inspect

            supports_weight = (
                "sample_weight"
                in inspect.signature(self.base_estimator.fit).parameters
            )
        except (TypeError, ValueError):
            pass
        bootstrap = self.bootstrap
        fit_params = dict(fit_params)
        # a user-supplied full-length sample_weight composes with the
        # bootstrap weights multiplicatively (sklearn forest semantics)
        user_weight = fit_params.pop("sample_weight", None)
        if user_weight is not None:
            user_weight = np.asarray(user_weight, dtype=np.float64)

        def build_one(seed):
            est = sk_clone(self.base_estimator)
            if "random_state" in est.get_params():
                est.set_params(random_state=int(seed))
            if not bootstrap:
                if user_weight is not None and supports_weight:
                    est.fit(X, y_arr, sample_weight=user_weight,
                            **fit_params)
                else:
                    est.fit(X, y_arr, **fit_params)
                return est
            r = np.random.RandomState(seed)
            idx = r.randint(0, n, n)
            if supports_weight:
                # the reference's bootstrap: bincount weights over the
                # full X (ensemble.py:88-104), not a row resample
                sw = np.bincount(idx, minlength=n).astype(np.float64)
                if user_weight is not None:
                    sw = sw * user_weight
                est.fit(X, y_arr, sample_weight=sw, **fit_params)
            else:
                est.fit(safe_indexing(X, idx), y_arr[idx], **fit_params)
            return est

        # partitions bounds per-round fan-out exactly as in the batched
        # classes (the reference's numSlices knob)
        round_size = parse_partitions(self.partitions, len(seeds))
        self.estimators_ = []
        for start in range(0, len(seeds), round_size):
            self.estimators_.extend(backend.run_tasks(
                build_one, seeds[start:start + round_size],
                verbose=self.verbose,
            ))
        self.n_features_in_ = X.shape[1] if hasattr(X, "shape") else None
        strip_runtime(self)
        return self

    def __len__(self):
        return len(self.estimators_)

    def __getitem__(self, index):
        return self.estimators_[index]


class DistForestClassifier(_DistBaseEstimatorForest):
    """Forest of cloned classifier ``base_estimator``s with majority
    soft-vote aggregation (reference ensemble.py:343-363)."""

    _estimator_type = "classifier"

    def _set_fit_targets(self, y_arr):
        self.classes_ = np.unique(y_arr)

    def predict_proba(self, X):
        agg = np.zeros((X.shape[0] if hasattr(X, "shape") else len(X),
                        len(self.classes_)))
        for est in self.estimators_:
            if hasattr(est, "predict_proba"):
                proba = np.asarray(est.predict_proba(X))
                cols = np.searchsorted(self.classes_, est.classes_)
                agg[:, cols] += proba
            else:  # hard-vote fallback for probability-free bases
                preds = np.searchsorted(self.classes_, est.predict(X))
                agg[np.arange(len(preds)), preds] += 1.0
        return agg / len(self.estimators_)

    def predict(self, X):
        return self.classes_[np.argmax(self.predict_proba(X), axis=1)]

    def score(self, X, y):
        return float(np.mean(self.predict(X) == np.asarray(y)))


class DistForestRegressor(_DistBaseEstimatorForest):
    """Forest of cloned regressor ``base_estimator``s with mean
    aggregation (reference ensemble.py:483-504)."""

    _estimator_type = "regressor"

    def _set_fit_targets(self, y_arr):
        pass

    def predict(self, X):
        return np.mean(
            [np.asarray(est.predict(X)) for est in self.estimators_], axis=0
        )

    def score(self, X, y):
        y = np.asarray(y, dtype=np.float64)
        resid = y - self.predict(X)
        denom = np.sum((y - y.mean()) ** 2)
        return float(1.0 - np.sum(resid ** 2) / denom) if denom else 0.0
