"""
Distributed one-vs-rest / one-vs-one multiclass strategies.

Re-design of the reference (``/root/reference/skdist/distribute/
multiclass.py:195-475``). The reference ships one Spark task per class
column (OvR, multiclass.py:316-331) or per class pair (OvO,
multiclass.py:440-459), each running ``_fit_binary`` (109-152) with
optional negative down-sampling (``_negatives_mask``, 76-106), a
constant-class fallback (175-192), and nested-search unwrapping
(``_use_best_estimator``, 65-73).

TPU-first design:

- **batched path** (JAX base estimators): the class (or class-pair)
  axis becomes the vmapped task axis of ONE compiled binary-fit
  program. Per-task label vectors are derived *on device* from the
  shared label matrix (``y_bin = Y[:, c]``); OvO's per-pair row subsets
  — shape-dynamic in the reference — become 0/1 sample-weight masks
  (SURVEY §7.3 hard part 1). Negative down-sampling is EXACT: per-class
  keep masks with the host path's target arithmetic and RandomState
  draw are precomputed on host and ride the task axis, so both paths
  of one estimator share sampling semantics.
- **generic path**: any sklearn-compatible estimator, one host task per
  class/pair with exact reference semantics (exact down-sampling,
  ConstantPredictor fallback, best_estimator_ unwrapping).

After fit both paths expose the same artifacts: ``estimators_`` (plain
picklable per-class estimators), ``classes_``, and sklearn-compatible
``predict`` / ``predict_proba`` / ``decision_function``.
"""

import warnings

import numpy as np

from ..base import BaseEstimator, ClassifierMixin, clone, strip_runtime
from ..parallel import (
    faults,
    iterative_fit_supported,
    parse_partitions,
    prefers_host_engine,
    resolve_backend,
)
from ..utils.validation import (
    check_estimator_backend,
    check_is_fitted,
    full_length_sample_weight,
    safe_split,
)

__all__ = ["DistOneVsRestClassifier", "DistOneVsOneClassifier"]


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _n_rows(X):
    return X.shape[0] if hasattr(X, "shape") else len(X)


def _warn_nonfinite_lanes(stacked, describe, what):
    """Lane-quarantine guard over a batched multiclass fit's stacked
    params (coef/intercept leaves): a non-finite lane means that
    sub-problem's solve diverged. Unlike the CV search there is no
    ``error_score`` contract to map onto — the host path would carry
    the same NaN params silently — so the guard makes the failure LOUD
    (a ``FitFailedWarning`` naming the affected classes/pairs) instead
    of letting predict-side argmax over NaN columns pick silently.
    ``describe(lane_index) -> str`` labels a poisoned lane.
    ``SKDIST_FAULT_GUARD=0`` disables."""
    if not faults.guard_enabled():
        return
    bad = faults.nonfinite_lanes(stacked)
    if bad is None or not bad.any():
        return
    from .search import FitFailedWarning

    idxs = np.where(bad)[0]
    names = ", ".join(describe(int(i)) for i in idxs[:5])
    if len(idxs) > 5:
        names += ", ..."
    faults.record("lanes_quarantined", int(bad.sum()))
    warnings.warn(
        f"{int(bad.sum())} batched {what} fit(s) produced non-finite "
        f"parameters (diverged lanes: {names}); their predictions "
        "will be unreliable. Check hyperparameters / data scaling.",
        FitFailedWarning,
    )


class _ConstantPredictor(BaseEstimator):
    """Degenerate single-class column fallback (reference
    multiclass.py:175-192)."""

    def fit(self, X, y):
        self.y_ = np.asarray(y).ravel()[:1]
        return self

    def predict(self, X):
        return np.repeat(self.y_, _n_rows(X))

    def decision_function(self, X):
        return np.repeat(float(2 * self.y_[0] - 1), _n_rows(X))

    def predict_proba(self, X):
        p = float(self.y_[0])
        return np.repeat([[1.0 - p, p]], _n_rows(X), axis=0)


def _use_best_estimator(est):
    """Unwrap a fitted nested SearchCV to its best_estimator_, carrying
    cv_results_ along as strings (reference multiclass.py:65-73).

    Only search-style wrappers are unwrapped. A fitted
    DistFeatureEliminator also exposes ``best_estimator_``, but its
    inner model was refit on the masked feature subset — unwrapping it
    would feed full-width X to a reduced-width model at predict time,
    so eliminators (marked by ``best_features_``) stay wrapped."""
    if not hasattr(est, "best_estimator_") or hasattr(est, "best_features_"):
        return est
    inner = est.best_estimator_
    if hasattr(est, "cv_results_"):
        import pandas as pd

        df = pd.DataFrame(est.cv_results_)
        inner.cv_results_ = {c: df[c].astype(str).tolist() for c in df.columns}
    return inner


def _negatives_mask(X, y, max_negatives=None, random_state=None, method="ratio"):
    """Exact negative down-sampling (reference multiclass.py:76-106):
    ratio = fraction of negatives kept; multiplier = mult*n_pos kept."""
    if max_negatives is None:
        return X, y
    pos_mask = np.asarray(y) == 1
    n_pos = int(pos_mask.sum())
    n_neg = int((~pos_mask).sum())
    if method == "ratio":
        target = max_negatives if isinstance(max_negatives, int) else int(
            round(max_negatives * n_neg)
        )
    elif method == "multiplier":
        target = int(max_negatives * n_pos)
    else:
        raise ValueError("Unknown method. Options are 'ratio' or 'multiplier'.")
    if target >= n_neg:
        return X, y
    rng = np.random.RandomState(random_state)
    neg_idx = np.where(~pos_mask)[0]
    keep_neg = rng.choice(neg_idx, size=target, replace=False)
    keep = np.concatenate([np.where(pos_mask)[0], keep_neg])
    rng.shuffle(keep)
    Xs = X[keep] if hasattr(X, "shape") else [X[i] for i in keep]
    return Xs, np.asarray(y)[keep]


def _fit_binary(estimator, X, y, fit_params=None, classes=None,
                max_negatives=None, random_state=None, method="ratio"):
    """Host-path single binary fit (reference multiclass.py:109-152)."""
    fit_params = fit_params or {}
    unique_y = np.unique(y)
    if len(unique_y) == 1:
        if classes is not None:
            c = 0 if unique_y[0] in (-1, 0) else 1
            warnings.warn(
                f"Label {classes[c]} is present in all training examples."
            )
        return _ConstantPredictor().fit(X, y)
    est = clone(estimator)
    Xs, ys = _negatives_mask(
        X, y, max_negatives=max_negatives, random_state=random_state,
        method=method,
    )
    est.fit(Xs, ys, **fit_params)
    return _use_best_estimator(est)


def _label_matrix(y, classes=None):
    """y (labels / sequences-of-labels / binary indicator matrix) →
    (Y, classes, multilabel). Y is int32 (n, k).

    Only *sequences of label collections* are multilabel; 1-D object
    arrays of scalar labels (e.g. strings) are ordinary multiclass —
    iterating a string as characters is never intended."""
    if _is_sequence_of_seqs(y):
        from sklearn.preprocessing import MultiLabelBinarizer

        mlb = MultiLabelBinarizer()
        Y = mlb.fit_transform(y)
        return Y.astype(np.int32), mlb.classes_, True
    y = np.asarray(y)
    if y.ndim == 2 and y.shape[1] == 1:
        # column vector of labels, as sklearn ravels (with a warning)
        warnings.warn(
            "A column-vector y was passed; ravelling to 1-D labels.",
        )
        y = y.ravel()
    if y.ndim == 2:
        # binary indicator matrix — validate it actually is one
        if not np.isin(np.unique(y), (0, 1)).all():
            raise ValueError(
                "2-D y must be a binary indicator matrix (values 0/1); "
                "got other values. For multiclass labels pass 1-D y."
            )
        classes = np.arange(y.shape[1]) if classes is None else classes
        return y.astype(np.int32), np.asarray(classes), True
    classes, y_idx = np.unique(y, return_inverse=True)
    Y = np.zeros((len(y), len(classes)), dtype=np.int32)
    Y[np.arange(len(y)), y_idx] = 1
    return Y, classes, False


def _is_sequence_of_seqs(y):
    try:
        first = y[0] if not hasattr(y, "iloc") else y.iloc[0]
    except (TypeError, IndexError, KeyError):
        return False
    return isinstance(first, (list, tuple, set, frozenset))


def _binary_prep(est, X_arr):
    """(X_dev, meta, aux) for the {0,1} binary sub-problems of any
    estimator implementing the batched-fit contract: calls the
    estimator's own _prep_fit_data with a synthetic two-class y so
    data-dependent context (tree bin edges etc.) is built exactly as a
    real binary fit would build it; X stays host-staged and is placed
    (and, with reuse_broadcast, cached) once by the backend's
    batched_map. Returns (None,)*3 if prep fails or the
    estimator is not a classifier (no 'classes' meta) — those take the
    generic host path."""
    if getattr(est, "_estimator_type", None) != "classifier":
        # non-classifier base: no binary batched form — bail before
        # paying any host->device transfer (duck-typed so sklearn's
        # ClassifierMixin qualifies too)
        return None, None, None
    try:
        data, meta = est._prep_fit_data(
            X_arr, np.arange(len(X_arr), dtype=np.int64) % 2, None
        )
    except Exception as exc:
        warnings.warn(
            f"batched binary prep failed ({type(exc).__name__}: {exc}); "
            "falling back to the per-task host path"
        )
        return None, None, None
    if "classes" not in meta:
        return None, None, None
    from ..models.linear import extract_aux

    return data["X"], meta, extract_aux(data)


def _binary_confidence(est, X):
    """Signed margin for a fitted binary estimator: 1-D decisions pass
    through; two-column decisions (e.g. naive Bayes per-class
    log-likelihoods) become their difference; otherwise proba-0.5."""
    if hasattr(est, "decision_function"):
        dec = np.asarray(est.decision_function(X))
        if dec.ndim == 1:
            return dec
        if dec.ndim == 2 and dec.shape[1] == 1:
            return dec[:, 0]
        if dec.ndim == 2 and dec.shape[1] == 2:
            return dec[:, 1] - dec[:, 0]
    return np.asarray(est.predict_proba(X))[:, 1] - 0.5


def _iterative_fit_spec(est_cls, meta, static, n_slice, derive,
                        fallback_kernel, fallback_key, key,
                        outputs=None, rung_score=None):
    """Wrap an estimator's iteration-sliced fit kernels for the
    convergence-compacted backend entry point — the ONE
    ``batched_map_iterative`` spec builder shared by the CV search,
    OvR/OvO, and the feature eliminator. ``derive(shared, task) ->
    (X, y, w, hyper, aux)`` supplies the per-task sub-problem (CV
    fold-masked weights, OvR class column, OvO pair mask, eliminate's
    feature-masked X); ``key`` must bake in everything ``derive`` /
    ``outputs`` / ``rung_score`` depend on beyond (est_cls, static,
    meta). Returns an ``IterativeKernelSpec`` whose kernels are
    memoised on ``key``.

    ``outputs(params, shared, task)`` optionally post-processes the
    finalized fit params into the spec's outputs (the CV search scores
    them on the fold masks here); None returns the raw params (the
    OvR/OvO per-class artifact). ``rung_score(params, shared, task) ->
    scalar`` additionally equips the spec with the adaptive (ASHA)
    rung evaluator: params are shaped from the LIVE carry through the
    family's ``score_params`` kernel (``solvers.carry_iterate``
    contract — the current iterate is a valid model at every slice
    boundary), then scored; the backend compiles it as a fourth jit
    entry so carries never leave the device."""
    from ..models.linear import maybe_exact_matmuls
    from ..parallel import IterativeKernelSpec, compile_cache

    def build():
        ks = est_cls._build_fit_slice_kernels(meta, static, n_slice)
        f_init = maybe_exact_matmuls(est_cls, ks["init"])
        f_step = maybe_exact_matmuls(est_cls, ks["step"])
        f_fin = maybe_exact_matmuls(est_cls, ks["finalize"])

        def init(shared, task):
            X, y, w, hyper, aux = derive(shared, task)
            return f_init(X, y, w, hyper, aux)

        def step(shared, task, carry):
            X, y, w, hyper, aux = derive(shared, task)
            return f_step(X, y, w, hyper, carry, aux)

        def finalize(shared, task, carry):
            X, y, w, hyper, aux = derive(shared, task)
            params = f_fin(X, y, w, hyper, carry, aux)
            if outputs is None:
                return params
            return outputs(params, shared, task)

        parts = {"init": init, "step": step, "finalize": finalize,
                 "keys": ks["finalize_keys"]}
        if rung_score is not None:
            f_live = maybe_exact_matmuls(
                est_cls, ks.get("score_params", ks["finalize"])
            )

            def score(shared, task, carry):
                X, y, w, hyper, aux = derive(shared, task)
                params = f_live(X, y, w, hyper, carry, aux)
                return rung_score(params, shared, task)

            parts["score"] = score
        return parts

    parts = compile_cache.kernel_memo(("spec",) + tuple(key), build)
    return IterativeKernelSpec(
        parts["init"], parts["step"], parts["finalize"], parts["keys"],
        fallback=fallback_kernel, fallback_cache_key=fallback_key,
        score=parts.get("score"),
    )


def _make_fitted_binary(base, params_slice, meta, static_names=None):
    """Materialise a fitted JAX binary estimator from a kernel params
    slice (the batched path's per-class artifact)."""
    est = clone(base)
    est._params = params_slice
    est._meta = meta
    est.n_features_in_ = meta["n_features"]
    est.classes_ = meta["classes"]
    return est


# ---------------------------------------------------------------------------
# OvR
# ---------------------------------------------------------------------------

class DistOneVsRestClassifier(BaseEstimator, ClassifierMixin):
    """One-vs-rest with class-axis fan-out (reference multiclass.py:195-362).

    Parameters mirror the reference: ``max_negatives``/``method``/
    ``random_state`` control negative down-sampling per binary problem,
    ``norm`` optionally L1/L2-normalises stacked probabilities
    (reference multiclass.py:337-362), ``backend`` replaces ``sc``.
    """

    def __init__(self, estimator, backend=None, partitions="auto",
                 max_negatives=None, method="ratio", norm=None,
                 random_state=None, n_jobs=None, verbose=0):
        self.estimator = estimator
        self.backend = backend
        self.partitions = partitions
        self.max_negatives = max_negatives
        self.method = method
        self.norm = norm
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.verbose = verbose

    def fit(self, X, y=None, **fit_params):
        check_estimator_backend(self, self.verbose)
        if self.method not in ("ratio", "multiplier"):
            raise ValueError(
                "Unknown method. Options are 'ratio' or 'multiplier'."
            )
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        from ..data import is_chunked

        if is_chunked(X):
            return self._fit_streamed(backend, X, y, fit_params)
        if y is None:
            raise TypeError(
                "fit requires y (only a ChunkedDataset carries labels)"
            )
        Y, classes, multilabel = _label_matrix(y)
        self.classes_ = classes
        self.multilabel_ = multilabel
        # 2-class non-multilabel: ONE binary estimator on the positive
        # column, like the reference's LabelBinarizer (which emits a
        # single column for binary y); the negative class is derived on
        # the predict side as the complement. Fitting both complementary
        # columns would double the work and break [1-p, p] semantics.
        self.binary_ = (not multilabel) and Y.shape[1] == 2
        if self.binary_:
            Y = Y[:, 1:]
        n_classes = Y.shape[1]

        done = None
        sw, sw_ok = full_length_sample_weight(fit_params, _n_rows(X))
        if sw_ok:
            done = self._try_batched(backend, X, Y, sample_weight=sw)
        if done is None:
            self._fit_generic(backend, X, Y, fit_params)
        self.estimator = clone(self.estimator)
        strip_runtime(self)
        return self

    # -- streamed out-of-core path --------------------------------------
    def _fit_streamed(self, backend, dataset, y, fit_params):
        """OvR over a ChunkedDataset: the class axis is the task axis
        of ONE streamed fit — every class's binary problem consumes the
        same block stream (labels binarised ON DEVICE per task from the
        encoded label vector), so the data is read once per solver
        pass regardless of the class count. No host fallback exists
        for out-of-core input, so unsupported configurations raise
        with the resident-path remedy."""
        import jax.numpy as jnp

        from ..models.linear import (
            _annotate_stream_meta, _freeze, hyper_float,
        )
        from ..models.streaming import stream_fit_tasks

        est = self.estimator
        est_cls = type(est)
        if getattr(est_cls, "_stream_fit_kind", None) is None:
            raise ValueError(
                f"{est_cls.__name__} has no streamed fit driver; "
                "ChunkedDataset OvR supports the linear families"
            )
        if self.max_negatives is not None:
            raise ValueError(
                "max_negatives down-sampling needs per-class row draws "
                "over resident X; not supported with ChunkedDataset "
                "input"
            )
        if getattr(est, "class_weight", None) is not None:
            raise ValueError(
                "class_weight does not map onto the streamed {0,1} "
                "binary sub-problems; fit with resident X for "
                "class-weighted OvR"
            )
        if getattr(est, "engine", None) == "host":
            raise ValueError(
                "engine='host' cannot fit a ChunkedDataset; use "
                "engine='auto'/'xla'"
            )
        if y is None:
            y = dataset.load_y()
        y = np.asarray(y)
        if y.ndim != 1 and not (y.ndim == 2 and y.shape[1] == 1):
            raise ValueError(
                "multilabel y is not supported with ChunkedDataset "
                "input (pass 1-D multiclass labels)"
            )
        y = y.reshape(-1)
        sw, sw_ok = full_length_sample_weight(fit_params, dataset.n_rows)
        if not sw_ok:
            raise ValueError(
                "streamed OvR supports only a full-length sample_weight "
                f"fit param; got {sorted(fit_params)}"
            )
        if sw is None:
            sw = dataset.load_sw()
        classes, y_enc = np.unique(y, return_inverse=True)
        y_enc = y_enc.astype(np.int32)
        self.classes_ = classes
        self.multilabel_ = False
        k = len(classes)
        self.binary_ = k == 2
        from ..models.linear import prepare_sample_weight

        sw_arr = prepare_sample_weight(sw, dataset.n_rows)
        # binary sub-problem meta: classes {0, 1} exactly like the
        # resident batched path's _binary_prep
        meta = _annotate_stream_meta({
            "n_features": dataset.n_features,
            "classes": np.arange(2, dtype=np.int64),
            "n_classes": 2,
            "cw_arr": None,
        }, dataset)
        static = _freeze(est._static_config(meta))
        # task axis = class columns (the positive column only for
        # binary y, mirroring the resident reduction)
        task_cls = np.array([1], np.int32) if self.binary_ else \
            np.arange(k, dtype=np.int32)
        counts = np.bincount(y_enc, minlength=k)
        n = dataset.n_rows
        degenerate = (counts == 0) | (counts == n)
        live = np.asarray(
            [c for c in task_cls if not degenerate[c]], np.int32
        )
        estimators = [None] * len(task_cls)
        if live.size:
            hyper = {
                name: np.full(
                    live.size, float(hyper_float(getattr(est, name))),
                    np.float32,
                )
                for name in est_cls._hyper_names
            }
            if est_cls._stream_fit_kind == "gram" and "alpha" not in hyper:
                hyper["alpha"] = np.full(
                    live.size, float(hyper_float(est.alpha)), np.float32
                )
            task_args = {"hyper": hyper, "cls": live}

            def derive(block, task):
                yb = (block["y"] == task["cls"]).astype(jnp.int32)
                return block["X"], yb, block["sw"], task["hyper"]

            params = stream_fit_tasks(
                backend, est_cls, meta, static, dataset,
                {"y": y_enc, "sw": sw_arr}, task_args, derive=derive,
                key_extra=("ovr",),
            )
            _warn_nonfinite_lanes(
                params,
                lambda i: f"class {classes[live[i]]!r}",
                "one-vs-rest",
            )
            for pos, cls_idx in enumerate(live):
                sl = {
                    key: np.asarray(v)[pos] for key, v in params.items()
                }
                col = int(np.where(task_cls == cls_idx)[0][0])
                estimators[col] = _make_fitted_binary(est, sl, meta)
        for col, cls_idx in enumerate(task_cls):
            if not degenerate[cls_idx]:
                continue
            warnings.warn(
                f"Label {self._col_label(col)} is present in "
                f"{'all' if counts[cls_idx] == n else 'no'} training "
                "examples."
            )
            cp = _ConstantPredictor()
            cp.y_ = np.array([1 if counts[cls_idx] == n else 0])
            estimators[col] = cp
        self.estimators_ = estimators
        self.estimator = clone(self.estimator)
        strip_runtime(self)
        return self

    # -- batched device path -------------------------------------------
    def _try_batched(self, backend, X, Y, sample_weight=None):
        est = self.estimator
        if not hasattr(type(est), "_build_fit_kernel"):
            return None
        # dict class_weight is keyed by original labels, which do not
        # map onto the {0,1} binary sub-problems -> generic path
        if isinstance(getattr(est, "class_weight", None), dict):
            return None
        from ..models.linear import _freeze, fit_would_pack, prepare_fit_X
        import jax
        import jax.numpy as jnp

        if prefers_host_engine(backend, est) and (
                not fit_would_pack(X, est)
                or getattr(est, "engine", None) == "host"):
            # the estimator resolves to its f64 host engine on this
            # host backend: the generic per-task path below runs that
            # engine, instead of the XLA-CPU batched program (shared
            # gate with search/eliminate — round-5 review). Packed
            # input has no host form and stays batched under 'auto';
            # an EXPLICIT engine='host' pin still routes to the host
            # per-task path. fit_would_pack is indptr-only, so the
            # bail costs nothing before prepare_fit_X's dense copy.
            return None
        try:
            # the BASELINE config-3 shape (hashed-text OvR): packable
            # sparse X ships packed and every class column's binary fit
            # runs the O(nnz) contractions on the one shared pair
            X_arr = prepare_fit_X(X, est)
        except Exception:
            return None
        n, d = X_arr.shape
        n_classes = Y.shape[1]

        # degenerate (single-valued) columns get ConstantPredictor on host
        col_sums = Y.sum(axis=0)
        degenerate = (col_sums == 0) | (col_sums == n)
        live = np.where(~degenerate)[0]

        X_dev, meta, aux = _binary_prep(est, X_arr)
        if meta is None:
            return None
        from ..models.linear import maybe_exact_matmuls

        static = _freeze(est._static_config(meta))
        fit_kernel = maybe_exact_matmuls(
            type(est), type(est)._build_fit_kernel(meta, static)
        )
        from ..models.linear import hyper_float

        hyper = {
            k: hyper_float(getattr(est, k)) for k in type(est)._hyper_names
        }
        max_negatives = self.max_negatives
        use_masks = max_negatives is not None

        def kernel(shared, task):
            y_bin = shared["Y"][:, task["cls"]]
            w = shared["sw"]
            if use_masks:
                # EXACT down-sampling: per-class keep masks are
                # precomputed on host with the same target arithmetic
                # and RandomState draw as the host path's
                # _negatives_mask (reference multiclass.py:76-106) and
                # ride the task axis — zero-weighting a row is
                # equivalent to dropping it for the weighted solvers.
                # (Replaces the round-2 Bernoulli approximation, whose
                # sampling semantics silently differed from the host
                # path of the same estimator.) Masks ship as uint8 and
                # widen on device.
                w = w * task["keep"].astype(jnp.float32)
            return fit_kernel(
                shared["X"], y_bin, w, shared["hyper"], shared["aux"]
            )

        shared = {
            "X": X_dev,
            "Y": jnp.asarray(Y),
            # the per-class kernels already weight by shared["sw"]: a
            # caller's full-length sample_weight drops straight in (the
            # keep masks compose with it multiplicatively below)
            "sw": (
                jnp.ones(n, jnp.float32) if sample_weight is None
                else jnp.asarray(sample_weight, jnp.float32)
            ),
            "hyper": {k: jnp.asarray(v) for k, v in hyper.items()},
            "aux": aux,
        }
        estimators = [None] * n_classes
        if live.size:
            from ..models.linear import _meta_signature
            from ..parallel import row_sharded_specs, structural_key

            # the per-fit closure is fully determined by (estimator
            # class, static config, meta signature, masking choice) —
            # the structural key lets repeated OvR fits reuse one
            # traced/compiled program despite the fresh closure
            kernel_key = structural_key(
                "ovr", type(est), static, _meta_signature(meta), use_masks
            )
            specs = row_sharded_specs(
                backend, shared, {"X": 0, "Y": 0, "sw": 0}
            )
            round_size = parse_partitions(self.partitions, int(live.size))
            # Down-sampling masks are (n_live, n)-shaped; at 1000-class
            # OvR on millions of rows co-materialising all of them on
            # host is TB-scale nonsense (round-3 VERDICT weak #7). The
            # masks for each dispatch span are built just-in-time, with
            # the span sized so one span's mask block stays inside the
            # host budget; per-class masks draw a fresh
            # RandomState(random_state), so spanning cannot change the
            # sampled sets.
            span_rows = (
                self._mask_span_rows(n) if use_masks else int(live.size)
            )
            if span_rows < int(live.size):
                # keep one round shape across the per-span dispatches
                # below (round-4 advisor: a shrunken tail round_size
                # meant an extra XLA compile for the final span): the
                # round never exceeds the memory-bounded span, and the
                # span is sized as a multiple of the round
                round_size = min(round_size, span_rows)
                span_rows -= span_rows % round_size
            spans = [
                (lo, min(lo + span_rows, int(live.size)))
                for lo in range(0, int(live.size), span_rows)
            ]
            # convergence-compacted path (the same backend entry point
            # the CV search uses): classes converge at different rates,
            # so the class-axis fan-out compacts exactly like a grid —
            # single-span only (the span machinery re-dispatches with a
            # pinned round shape the slice loop doesn't need)
            n_slice = (
                iterative_fit_supported(
                    backend, type(est), int(live.size),
                    getattr(est, "max_iter", None),
                )
                if len(spans) == 1 else None
            )
            parts = []
            if n_slice is not None:

                def derive(shared, task):
                    y_bin = shared["Y"][:, task["cls"]]
                    w = shared["sw"]
                    if use_masks:
                        w = w * task["keep"].astype(jnp.float32)
                    return (shared["X"], y_bin, w, shared["hyper"],
                            shared["aux"])

                iter_key = structural_key(
                    "ovr_iter", type(est), static, _meta_signature(meta),
                    use_masks, int(n_slice),
                )
                spec = _iterative_fit_spec(
                    type(est), meta, static, n_slice, derive, kernel,
                    kernel_key, iter_key,
                )
                task_args = {"cls": live.astype(np.int32)}
                if use_masks:
                    task_args["keep"] = self._exact_keep_masks(Y, live)
                parts.append(backend.batched_map_iterative(
                    spec, task_args, shared,
                    round_size=(
                        None if self.partitions in ("auto", None)
                        else round_size
                    ),
                    shared_specs=specs, cache_key=iter_key,
                ))
            else:
                for lo, hi in spans:
                    task_args = {"cls": live[lo:hi].astype(np.int32)}
                    if use_masks:
                        task_args["keep"] = self._exact_keep_masks(
                            Y, live[lo:hi]
                        )
                    parts.append(backend.batched_map(
                        kernel, task_args, shared, round_size=round_size,
                        shared_specs=specs, pad_to_round=len(spans) > 1,
                        cache_key=kernel_key,
                    ))
            stacked = parts[0] if len(parts) == 1 else (
                jax.tree_util.tree_map(
                    lambda *xs: np.concatenate(xs, axis=0), *parts
                )
            )
            from ..models.linear import annotate_round_kernel_mode

            annotate_round_kernel_mode(backend, meta)
            _warn_nonfinite_lanes(
                stacked,
                lambda i: f"class {self._col_label(live[i])!r}",
                "one-vs-rest",
            )
            for pos_idx, cls_idx in enumerate(live):
                params = jax.tree_util.tree_map(lambda a: a[pos_idx], stacked)
                estimators[cls_idx] = _make_fitted_binary(est, params, meta)
        for cls_idx in np.where(degenerate)[0]:
            warnings.warn(
                f"Label {self._col_label(cls_idx)} is present in "
                f"{'all' if col_sums[cls_idx] == n else 'no'} training examples."
            )
            cp = _ConstantPredictor()
            cp.y_ = np.array([1 if col_sums[cls_idx] == n else 0])
            estimators[cls_idx] = cp
        self.estimators_ = estimators
        return True

    def _mask_span_rows(self, n):
        """Class count per dispatch span so one span's (rows, n) uint8
        mask block fits in 1/8 of the host budget (several blocks can
        be alive at once: the span under construction plus blocks
        pinned by in-flight device transfers)."""
        from ..utils.meminfo import densify_budget_bytes

        budget, _ = densify_budget_bytes()
        if budget is None:
            return 1 << 30  # unknown budget: single span, as before
        return max(1, int(budget // 8) // max(int(n), 1))

    def _exact_keep_masks(self, Y, live):
        """(n_live, n) uint8 keep weights mirroring ``_negatives_mask``:
        per class, all positives kept plus an EXACT uniform
        without-replacement draw of the target number of negatives,
        from a fresh RandomState(random_state) per class — the same
        construction the host path performs per binary fit. uint8 (the
        kernel widens on device) keeps the block 4× smaller than f32;
        callers bound ``live`` via :meth:`_mask_span_rows` so the block
        never exceeds the host budget."""
        n = Y.shape[0]
        keep = np.ones((live.size, n), dtype=np.uint8)
        for i, cls in enumerate(live):
            y_bin = np.asarray(Y[:, cls])
            pos_mask = y_bin == 1
            n_pos = int(pos_mask.sum())
            n_neg = n - n_pos
            if self.method == "ratio":
                target = (
                    self.max_negatives
                    if isinstance(self.max_negatives, int)
                    else int(round(self.max_negatives * n_neg))
                )
            elif self.method == "multiplier":
                target = int(self.max_negatives * n_pos)
            else:
                raise ValueError(
                    "Unknown method. Options are 'ratio' or 'multiplier'."
                )
            if target >= n_neg:
                continue
            rng = np.random.RandomState(self.random_state)
            neg_idx = np.where(~pos_mask)[0]
            keep_neg = rng.choice(neg_idx, size=target, replace=False)
            mask = np.zeros(n, dtype=np.uint8)
            mask[pos_mask] = 1
            mask[keep_neg] = 1
            keep[i] = mask
        return keep

    def _col_label(self, col_idx):
        """Original class label for column ``col_idx`` of the (possibly
        binary-reduced) label matrix."""
        if getattr(self, "binary_", False):
            return self.classes_[col_idx + 1]
        return self.classes_[col_idx]

    # -- generic host path ---------------------------------------------
    def _fit_generic(self, backend, X, Y, fit_params):
        est = self.estimator

        def run_one(cls_idx):
            label = self._col_label(cls_idx)
            return _fit_binary(
                est, X, Y[:, cls_idx], fit_params,
                classes=[f"not-{label}", label],
                max_negatives=self.max_negatives,
                random_state=self.random_state, method=self.method,
            )

        self.estimators_ = backend.run_tasks(
            run_one, range(Y.shape[1]), verbose=self.verbose
        )

    # -- predict side ---------------------------------------------------
    def _per_class_scores(self, X, want_proba):
        check_is_fitted(self, "estimators_")
        cols = []
        for est in self.estimators_:
            if want_proba:
                cols.append(np.asarray(est.predict_proba(X))[:, 1])
            else:
                cols.append(_binary_confidence(est, X))
        return np.column_stack(cols)

    def _expanded_scores(self, X, want_proba):
        """Per-class score matrix over ``classes_`` — for the binary
        single-estimator case the negative column is the derived
        complement ([1-p, p] / [-s, s])."""
        scores = self._per_class_scores(X, want_proba)
        if getattr(self, "binary_", False):
            col = scores[:, 0]
            scores = (
                np.column_stack([1.0 - col, col]) if want_proba
                else np.column_stack([-col, col])
            )
        return scores

    def predict_proba(self, X):
        """Stacked per-class positive probabilities; optionally
        normalised (reference multiclass.py:337-362)."""
        scores = self._expanded_scores(X, want_proba=True)
        if self.norm:
            from sklearn.preprocessing import normalize

            scores = normalize(scores, norm=self.norm)
        return scores

    def decision_function(self, X):
        scores = self._per_class_scores(X, want_proba=False)
        if getattr(self, "binary_", False):
            # sklearn's binary OvR contract: 1-D confidences for the
            # positive class
            return scores[:, 0]
        return scores

    def predict(self, X):
        if self.multilabel_:
            proba_like = self._per_class_scores(
                X, want_proba=self._has_proba()
            )
            thresh = 0.5 if self._has_proba() else 0.0
            return (proba_like > thresh).astype(np.int32)
        scores = self._expanded_scores(X, want_proba=self._has_proba())
        return self.classes_[np.argmax(scores, axis=1)]

    def _has_proba(self):
        return all(hasattr(e, "predict_proba") for e in self.estimators_)

    @property
    def n_classes_(self):
        return len(self.classes_)


# ---------------------------------------------------------------------------
# OvO
# ---------------------------------------------------------------------------

class DistOneVsOneClassifier(BaseEstimator, ClassifierMixin):
    """One-vs-one with pair-axis fan-out (reference multiclass.py:365-475).

    Pairs (i, j), i<j; positive class is j (reference
    ``_fit_ovo_binary``, multiclass.py:155-172). The batched path masks
    rows by weight instead of slicing — the shape-dynamic part of the
    reference that XLA can't express directly.
    """

    def __init__(self, estimator, backend=None, partitions="auto",
                 n_jobs=None, verbose=0):
        self.estimator = estimator
        self.backend = backend
        self.partitions = partitions
        self.n_jobs = n_jobs
        self.verbose = verbose

    def fit(self, X, y=None, **fit_params):
        check_estimator_backend(self, self.verbose)
        from ..data import is_chunked

        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        if is_chunked(X):
            return self._fit_streamed(backend, X, y, fit_params)
        if y is None:
            raise ValueError(
                "y is required for resident input (only ChunkedDataset "
                "input carries its own labels)"
            )
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        k = len(self.classes_)
        self.pairs_ = [(i, j) for i in range(k) for j in range(i + 1, k)]

        done = None
        sw, sw_ok = full_length_sample_weight(fit_params, _n_rows(X))
        if sw_ok:
            done = self._try_batched(backend, X, y, sample_weight=sw)
        if done is None:
            self._fit_generic(backend, X, y, fit_params)
        self.estimator = clone(self.estimator)
        strip_runtime(self)
        return self

    # -- streamed out-of-core path --------------------------------------
    def _fit_streamed(self, backend, dataset, y, fit_params):
        """OvO over a ChunkedDataset: the PAIR axis rides the task axis
        of ONE streamed fit — every block is read once per solver pass
        for ALL ``k·(k-1)/2`` pairs, with pair membership composed on
        device as a weight mask (``in_pair × sample_weight``, the
        resident batched path's idiom) and labels binarised per task
        (positive class ``j``). No host fallback exists for out-of-core
        input, so unsupported configurations raise with the
        resident-path remedy."""
        import jax.numpy as jnp

        from ..models.linear import (
            _annotate_stream_meta, _freeze, hyper_float,
            prepare_sample_weight,
        )
        from ..models.streaming import stream_fit_tasks

        est = self.estimator
        est_cls = type(est)
        if getattr(est_cls, "_stream_fit_kind", None) is None:
            raise ValueError(
                f"{est_cls.__name__} has no streamed fit driver; "
                "ChunkedDataset OvO supports the linear families"
            )
        if getattr(est, "class_weight", None) is not None:
            raise ValueError(
                "class_weight does not map onto the streamed {0,1} "
                "binary sub-problems; fit with resident X for "
                "class-weighted OvO"
            )
        if getattr(est, "engine", None) == "host":
            raise ValueError(
                "engine='host' cannot fit a ChunkedDataset; use "
                "engine='auto'/'xla'"
            )
        if y is None:
            y = dataset.load_y()
        y = np.asarray(y)
        if y.ndim != 1 and not (y.ndim == 2 and y.shape[1] == 1):
            raise ValueError(
                "OvO needs 1-D multiclass labels; got y with shape "
                f"{y.shape}"
            )
        y = y.reshape(-1)
        sw, sw_ok = full_length_sample_weight(fit_params, dataset.n_rows)
        if not sw_ok:
            raise ValueError(
                "streamed OvO supports only a full-length sample_weight "
                f"fit param; got {sorted(fit_params)}"
            )
        if sw is None:
            sw = dataset.load_sw()
        self.classes_ = np.unique(y)
        k = len(self.classes_)
        self.pairs_ = [(i, j) for i in range(k) for j in range(i + 1, k)]
        y_idx = np.searchsorted(self.classes_, y).astype(np.int32)
        sw_arr = prepare_sample_weight(sw, dataset.n_rows)
        # binary sub-problem meta: classes {0, 1} exactly like the
        # resident batched path's _binary_prep
        meta = _annotate_stream_meta({
            "n_features": dataset.n_features,
            "classes": np.arange(2, dtype=np.int64),
            "n_classes": 2,
            "cw_arr": None,
        }, dataset)
        static = _freeze(est._static_config(meta))
        n_pairs = len(self.pairs_)
        hyper = {
            name: np.full(
                n_pairs, float(hyper_float(getattr(est, name))),
                np.float32,
            )
            for name in est_cls._hyper_names
        }
        if est_cls._stream_fit_kind == "gram" and "alpha" not in hyper:
            hyper["alpha"] = np.full(
                n_pairs, float(hyper_float(est.alpha)), np.float32
            )
        task_args = {
            "hyper": hyper,
            "i": np.asarray([p[0] for p in self.pairs_], np.int32),
            "j": np.asarray([p[1] for p in self.pairs_], np.int32),
        }

        def derive(block, task):
            yi = block["y"]
            in_pair = (yi == task["i"]) | (yi == task["j"])
            yb = (yi == task["j"]).astype(jnp.int32)
            # pair membership composes multiplicatively with the
            # caller's weights; block tail-padding rows carry zero
            # weight and fall out of every pair
            w = in_pair.astype(jnp.float32) * block["sw"]
            return block["X"], yb, w, task["hyper"]

        params = stream_fit_tasks(
            backend, est_cls, meta, static, dataset,
            {"y": y_idx, "sw": sw_arr}, task_args, derive=derive,
            key_extra=("ovo",),
        )
        _warn_nonfinite_lanes(
            params,
            lambda t: "pair (%r, %r)" % (
                self.classes_[self.pairs_[t][0]],
                self.classes_[self.pairs_[t][1]],
            ),
            "one-vs-one",
        )
        self.estimators_ = [
            _make_fitted_binary(
                est,
                {key: np.asarray(v)[t] for key, v in params.items()},
                meta,
            )
            for t in range(n_pairs)
        ]
        self.estimator = clone(self.estimator)
        strip_runtime(self)
        return self

    def _try_batched(self, backend, X, y, sample_weight=None):
        est = self.estimator
        if not hasattr(type(est), "_build_fit_kernel"):
            return None
        # dict class_weight is keyed by original labels, which do not
        # map onto the {0,1} binary sub-problems -> generic path
        if isinstance(getattr(est, "class_weight", None), dict):
            return None
        from ..models.linear import _freeze, fit_would_pack, prepare_fit_X
        import jax
        import jax.numpy as jnp

        if prefers_host_engine(backend, est) and (
                not fit_would_pack(X, est)
                or getattr(est, "engine", None) == "host"):
            # the estimator resolves to its f64 host engine on this
            # host backend: the generic per-task path below runs that
            # engine, instead of the XLA-CPU batched program (shared
            # gate with search/eliminate — round-5 review). Packed
            # input has no host form and stays batched under 'auto';
            # an EXPLICIT engine='host' pin still routes to the host
            # per-task path. fit_would_pack is indptr-only, so the
            # bail costs nothing before prepare_fit_X's dense copy.
            return None
        try:
            X_arr = prepare_fit_X(X, est)
        except Exception:
            return None
        y_idx = np.searchsorted(self.classes_, y).astype(np.int32)
        X_dev, meta, aux = _binary_prep(est, X_arr)
        if meta is None:
            return None
        from ..models.linear import maybe_exact_matmuls

        static = _freeze(est._static_config(meta))
        fit_kernel = maybe_exact_matmuls(
            type(est), type(est)._build_fit_kernel(meta, static)
        )
        from ..models.linear import hyper_float

        hyper = {
            k_: hyper_float(getattr(est, k_))
            for k_ in type(est)._hyper_names
        }

        def kernel(shared, task):
            yi = shared["y"]
            in_pair = (yi == task["i"]) | (yi == task["j"])
            y_bin = (yi == task["j"]).astype(jnp.int32)
            # pair membership composes multiplicatively with the
            # caller's per-sample weights (ones when absent), mirroring
            # search.py's fold-mask x sample_weight contract
            w = in_pair.astype(jnp.float32) * shared["sw"]
            return fit_kernel(
                shared["X"], y_bin, w, shared["hyper"], shared["aux"]
            )

        n = X_arr.shape[0]
        shared = {
            "X": X_dev,
            "y": jnp.asarray(y_idx),
            "sw": (
                jnp.ones(n, jnp.float32) if sample_weight is None
                else jnp.asarray(sample_weight, jnp.float32)
            ),
            "hyper": {k_: jnp.asarray(v) for k_, v in hyper.items()},
            "aux": aux,
        }
        task_args = {
            "i": np.asarray([p[0] for p in self.pairs_], dtype=np.int32),
            "j": np.asarray([p[1] for p in self.pairs_], dtype=np.int32),
        }
        from ..models.linear import _meta_signature
        from ..parallel import row_sharded_specs, structural_key

        specs = row_sharded_specs(
            backend, shared, {"X": 0, "y": 0, "sw": 0}
        )
        kernel_key = structural_key(
            "ovo", type(est), static, _meta_signature(meta)
        )
        # convergence-compacted path: class pairs converge at different
        # rates (same backend entry point as the CV search / OvR)
        n_slice = iterative_fit_supported(
            backend, type(est), len(self.pairs_),
            getattr(est, "max_iter", None),
        )
        if n_slice is not None:

            def derive(shared, task):
                yi = shared["y"]
                in_pair = (yi == task["i"]) | (yi == task["j"])
                y_bin = (yi == task["j"]).astype(jnp.int32)
                w = in_pair.astype(jnp.float32) * shared["sw"]
                return shared["X"], y_bin, w, shared["hyper"], shared["aux"]

            iter_key = structural_key(
                "ovo_iter", type(est), static, _meta_signature(meta),
                int(n_slice),
            )
            spec = _iterative_fit_spec(
                type(est), meta, static, n_slice, derive, kernel,
                kernel_key, iter_key,
            )
            stacked = backend.batched_map_iterative(
                spec, task_args, shared,
                round_size=(
                    None if self.partitions in ("auto", None)
                    else parse_partitions(self.partitions, len(self.pairs_))
                ),
                shared_specs=specs, cache_key=iter_key,
            )
        else:
            stacked = backend.batched_map(
                kernel, task_args, shared,
                round_size=parse_partitions(
                    self.partitions, len(self.pairs_)
                ),
                shared_specs=specs,
                cache_key=kernel_key,
            )
        from ..models.linear import annotate_round_kernel_mode

        annotate_round_kernel_mode(backend, meta)
        _warn_nonfinite_lanes(
            stacked,
            lambda t: "pair (%r, %r)" % (
                self.classes_[self.pairs_[t][0]],
                self.classes_[self.pairs_[t][1]],
            ),
            "one-vs-one",
        )
        self.estimators_ = [
            _make_fitted_binary(
                est, jax.tree_util.tree_map(lambda a: a[t], stacked), meta
            )
            for t in range(len(self.pairs_))
        ]
        return True

    def _fit_generic(self, backend, X, y, fit_params):
        est = self.estimator
        y_idx = np.searchsorted(self.classes_, y)
        n = _n_rows(X)

        def run_one(pair):
            i, j = pair
            cond = (y_idx == i) | (y_idx == j)
            idx = np.where(cond)[0]
            X_sub, _ = safe_split(est, X, None, idx)
            y_bin = (y_idx[idx] == j).astype(np.int32)
            fp = fit_params
            sw = fp.get("sample_weight") if fp else None
            if sw is not None:
                sw_arr = np.asarray(sw)
                if sw_arr.ndim == 2 and sw_arr.shape[1] == 1:
                    # flatten (n, 1) columns BEFORE slicing, like the
                    # shared device-path contract — a sliced (k, 1)
                    # weight would fail sklearn's 1-D validation
                    sw_arr = sw_arr.ravel()
                if sw_arr.shape[:1] == (n,):
                    # full-length per-sample weights follow the pair's
                    # row subset (the host mirror of the device path's
                    # membership-mask x sample_weight composition;
                    # passing them unsliced would length-mismatch the
                    # sliced X)
                    fp = dict(fp, sample_weight=sw_arr[idx])
            return _fit_binary(est, X_sub, y_bin, fp, classes=[i, j])

        self.estimators_ = backend.run_tasks(
            run_one, self.pairs_, verbose=self.verbose
        )

    def decision_function(self, X):
        """sklearn-style OvO aggregation: votes plus a bounded
        sum-of-confidences tie-break."""
        check_is_fitted(self, "estimators_")
        n = _n_rows(X)
        k = len(self.classes_)
        votes = np.zeros((n, k))
        sum_conf = np.zeros((n, k))
        for (i, j), est in zip(self.pairs_, self.estimators_):
            conf = _binary_confidence(est, X).reshape(n)
            votes[:, i] += conf < 0
            votes[:, j] += conf >= 0
            sum_conf[:, i] -= conf
            sum_conf[:, j] += conf
        return votes + sum_conf / (3 * (np.abs(sum_conf) + 1))

    def predict(self, X):
        return self.classes_[np.argmax(self.decision_function(X), axis=1)]

    @property
    def n_classes_(self):
        return len(self.classes_)
