"""
Distributed feature elimination (reference ``/root/reference/skdist/
distribute/eliminate.py:47-246``).

One-shot parallel approximation of sklearn's RFECV: rank features by an
initial full fit's ``coef_``/``feature_importances_``
(eliminate.py:141-157), build nested removal sets by ``step``
(159-163), score every (feature_set × cv_fold) combination in parallel,
keep the best-scoring set and refit on it (221-236).

TPU-first: a removal set is a 0/1 *column mask*. For JAX estimators,
``X·mask`` inside the kernel is exactly equivalent to dropping the
columns (a zeroed feature's optimal weight is 0 under any L2 penalty; a
constant feature is never split by a tree), so the whole
(feature_set × fold) grid runs as ONE vmapped XLA program with the mask
riding the task axis — no per-task data copies at all, where the
reference re-broadcasts X and slices columns per executor task
(eliminate.py:23-38,188-210).
"""

import warnings
from itertools import product

import numpy as np

from ..base import BaseEstimator, clone, strip_runtime
from ..metrics import (
    BINARY_ONLY_SCORERS,
    aggregate_score_dicts,
    check_multimetric_scoring,
    device_scorer_compatible,
    resolve_rung_scorer,
)
from ..parallel import (
    RungController,
    iterative_fit_supported,
    parse_partitions,
    prefers_host_engine,
    resolve_backend,
)
from ..utils.validation import check_estimator_backend, check_is_fitted
from .adaptive import RungKilledWarning, check_adaptive, warn_not_engaged
from .search import _fit_and_score, _resolve_device_scoring

__all__ = ["DistFeatureEliminator"]


def _drop_col(X, cols):
    """Column-drop across container types (reference eliminate.py:23-27)."""
    if len(cols) == 0:
        return X
    keep = np.setdiff1d(np.arange(X.shape[1]), cols)
    if hasattr(X, "iloc"):
        return X.iloc[:, keep]
    if hasattr(X, "tocsc"):
        return X.tocsc()[:, keep].tocsr()
    return X[:, keep]


class DistFeatureEliminator(BaseEstimator):
    """Reference eliminate.py:47-246; ``backend`` replaces ``sc``."""

    def __init__(self, estimator, backend=None, partitions="auto",
                 min_features_to_select=None, step=1, cv=5, scoring=None,
                 verbose=False, n_jobs=None, mask=True, adaptive=None):
        self.estimator = estimator
        self.backend = backend
        self.partitions = partitions
        self.min_features_to_select = min_features_to_select
        self.step = step
        self.cv = cv
        self.scoring = scoring
        self.verbose = verbose
        self.n_jobs = n_jobs
        self.mask = mask
        # adaptive=HalvingSpec(...): feature sets ride the SAME ASHA
        # rungs as the CV search — every K slices the live (set x fold)
        # lanes are scored on device and the bottom 1-1/eta sets
        # killed; killed sets score NaN (never selected) and rung_
        # records where each set died
        self.adaptive = adaptive

    # ------------------------------------------------------------------
    def fit(self, X, y=None, groups=None, **fit_params):
        from sklearn.model_selection import check_cv
        from sklearn.utils import safe_sqr

        check_adaptive(self.adaptive)
        self._adaptive_engaged_ = False
        self._rung_per_set_ = None
        check_estimator_backend(self, self.verbose)
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        X_arr = np.asarray(X) if not hasattr(X, "iloc") else X
        n_features = X_arr.shape[1]
        if n_features < 2:
            raise ValueError("X must have at least 2 features")
        is_classifier = (
            getattr(self.estimator, "_estimator_type", None) == "classifier"
        )
        cv = check_cv(self.cv, y, classifier=is_classifier)
        splits = list(cv.split(X_arr, y, groups))

        min_keep = (
            n_features // 2
            if self.min_features_to_select is None
            else self.min_features_to_select
        )
        step = (
            int(max(1, self.step * n_features))
            if 0.0 < self.step < 1.0
            else int(self.step)
        )
        if step <= 0:
            raise ValueError("Step must be >0")

        # initial full fit on the driver ranks the features
        initial = clone(self.estimator)
        initial.fit(X_arr, y, **fit_params)
        coefs = getattr(initial, "coef_", None)
        if coefs is None:
            coefs = getattr(initial, "feature_importances_", None)
        if coefs is None:
            raise RuntimeError(
                'The estimator does not expose "coef_" or '
                '"feature_importances_" attributes'
            )
        coefs = np.asarray(coefs)
        ranks = (
            np.argsort(safe_sqr(coefs).sum(axis=0))
            if coefs.ndim > 1
            else np.argsort(safe_sqr(coefs))
        )
        ranks = np.ravel(ranks)[: n_features - min_keep]

        features_to_remove = [np.array([], dtype=int)]
        removed = 0
        while removed < n_features - min_keep:
            removed += step
            features_to_remove.append(ranks[:removed])

        scores = self._score_feature_sets(
            backend, X_arr, y, splits, features_to_remove, fit_params
        )
        self.scores_ = scores
        if self.adaptive is not None:
            if not self._adaptive_engaged_:
                warn_not_engaged("the eliminator")
            # rung at which each feature set died (-1 = completed)
            self.rung_ = (
                self._rung_per_set_
                if self._rung_per_set_ is not None
                else np.full(len(features_to_remove), -1, np.int32)
            )
        del self._adaptive_engaged_, self._rung_per_set_
        # NaN (failed folds under error_score=np.nan) must never win:
        # np.argmax treats NaN as the maximum. Rank NaN sets as -inf;
        # refuse to pick when every set failed.
        sel = np.asarray(scores, dtype=np.float64)
        if np.all(np.isnan(sel)):
            raise RuntimeError(
                "All feature-set fits failed (every CV score is NaN); "
                "cannot select best_features_."
            )
        sel = np.where(np.isnan(sel), -np.inf, sel)
        # ties break toward the smaller feature set (sets are ordered by
        # increasing removal, so take the LAST argmax)
        best = int(len(sel) - 1 - np.argmax(sel[::-1]))
        self.best_score_ = float(scores[best])
        self.best_features_ = np.setdiff1d(
            np.arange(n_features), features_to_remove[best]
        )
        self.n_features_ = len(self.best_features_)

        final = clone(self.estimator)
        final.fit(self._apply_mask(X_arr), y, **fit_params)
        self.estimator_ = final
        self.estimator = clone(self.estimator)
        strip_runtime(self)
        return self

    def _score_feature_sets(self, backend, X, y, splits, features_to_remove,
                            fit_params):
        """Mean CV score per feature set; batched on device when the
        estimator + scoring allow, generic otherwise."""
        n_sets = len(features_to_remove)
        n_splits = len(splits)
        out = None
        if not fit_params:
            out = self._try_batched(
                backend, X, y, splits, features_to_remove
            )
        if out is None:
            scorers, multimetric = check_multimetric_scoring(
                self.estimator, self.scoring
            )
            if multimetric:
                raise ValueError(
                    "DistFeatureEliminator supports single-metric scoring"
                )
            tasks = list(product(range(n_sets), range(n_splits)))

            def run_one(task):
                set_idx, split_idx = task
                train, test = splits[split_idx]
                Xs = _drop_col(X, features_to_remove[set_idx])
                return _fit_and_score(
                    self.estimator, Xs, y, scorers, train, test, {},
                    fit_params=fit_params,
                )["test_score"]

            flat = backend.run_tasks(run_one, tasks, verbose=self.verbose)
            out = np.asarray(flat, dtype=np.float64).reshape(
                n_sets, n_splits
            )
        return out.mean(axis=1)

    def _try_batched(self, backend, X, y, splits, features_to_remove):
        est = self.estimator
        if not hasattr(type(est), "_build_fit_kernel"):
            return None
        if prefers_host_engine(backend, est):
            # the estimator resolves to its f64 host engine on this
            # host backend: the generic per-task path below runs that
            # engine, instead of the XLA-CPU batched program (shared
            # gate with search/eliminate — round-5 review)
            return None
        scorer_specs = _resolve_device_scoring(est, self.scoring)
        if scorer_specs is None:
            return None
        if any(m in BINARY_ONLY_SCORERS for _, m, *_ in scorer_specs):
            if not all(
                device_scorer_compatible(m, np.unique(y))
                for _, m, *_ in scorer_specs
            ):
                return None
        from ..models.linear import as_dense_f32, _freeze, extract_aux
        from ..parallel import structural_key
        from .search import _cached_cv_kernel, _cv_kernel_key
        import jax.numpy as jnp

        try:
            X_arr = as_dense_f32(X)
        except Exception:
            return None
        n, d = X_arr.shape
        n_splits = len(splits)
        train_masks = np.zeros((n_splits, n), dtype=np.float32)
        test_masks = np.zeros((n_splits, n), dtype=np.float32)
        for i, (train, test) in enumerate(splits):
            train_masks[i, train] = 1.0
            test_masks[i, test] = 1.0

        n_sets = len(features_to_remove)
        fmasks = np.ones((n_sets, d), dtype=np.float32)
        for i, rem in enumerate(features_to_remove):
            fmasks[i, rem] = 0.0

        data, meta = est._prep_fit_data(X_arr, y, None)
        static_cfg = est._static_config(meta)
        static = _freeze(static_cfg)
        base_key = _cv_kernel_key(type(est), meta, static, scorer_specs,
                                  False)
        base_kernel = _cached_cv_kernel(
            type(est), meta, static, scorer_specs, False, key=base_key
        )
        from ..models.linear import hyper_float

        hyper = {
            k: hyper_float(getattr(est, k)) for k in type(est)._hyper_names
        }
        n_tasks = n_sets * n_splits
        round_size = parse_partitions(self.partitions, n_tasks)
        from ..parallel import row_sharded_specs

        n_slice = iterative_fit_supported(
            backend, type(est), n_tasks, static_cfg.get("max_iter")
        )
        if n_slice is not None:
            # convergence-compacted (and, with adaptive=, ASHA-rung)
            # execution: the (feature_set x fold) axis rides the SAME
            # batched_map_iterative entry point as the CV search, with
            # the column mask as a task leaf (mask_x) and the
            # estimator's fixed hypers broadcast onto the task axis so
            # the shared CV slice kernels apply verbatim
            return self._try_batched_iterative(
                backend, est, meta, static, static_cfg, scorer_specs,
                base_kernel, base_key, data, hyper, train_masks,
                test_masks, fmasks, n_sets, n_splits, n_slice,
                round_size, np.unique(y) if y is not None else None,
            )

        def kernel(shared, task):
            masked = dict(shared)
            masked["X"] = shared["X"] * task["fmask"]
            return base_kernel(
                masked, {"hyper": shared["hyper"], "split": task["split"]}
            )

        shared = {
            "X": data["X"],
            "y": data["y"],
            "sw": data["sw"],
            "aux": extract_aux(data),
            "hyper": {k: jnp.asarray(v) for k, v in hyper.items()},
            "train_masks": jnp.asarray(train_masks),
            "test_masks": jnp.asarray(test_masks),
        }
        task_args = {
            "fmask": np.repeat(fmasks, n_splits, axis=0),
            "split": np.tile(
                np.arange(n_splits, dtype=np.int32), n_sets
            ),
        }
        scores = backend.batched_map(
            kernel, task_args, shared, round_size=round_size,
            shared_specs=row_sharded_specs(backend, shared, {
                "X": 0, "y": 0, "sw": 0,
                "train_masks": 1, "test_masks": 1,
            }),
            # the closure is rebuilt per fit but is fully determined by
            # the base cv kernel it wraps; the structural key lets the
            # jit/AOT caches see through the fresh closure identity
            cache_key=structural_key("eliminate", type(est), base_key),
        )
        return np.asarray(
            scores["test_score"], dtype=np.float64
        ).reshape(n_sets, n_splits)

    def _try_batched_iterative(self, backend, est, meta, static,
                               static_cfg, scorer_specs, base_kernel,
                               base_key, data, hyper, train_masks,
                               test_masks, fmasks, n_sets, n_splits,
                               n_slice, round_size, classes):
        """Iteration-sliced (set x fold) scoring through the shared
        ``_iterative_fit_spec``/``_cv_iterative_spec`` entry point,
        optionally racing the sets on ASHA rungs. Killed sets score NaN
        (the NaN-proof selection below never picks them) and their
        rungs land in ``rung_``."""
        from ..models.linear import extract_aux
        from ..parallel import row_sharded_specs, structural_key
        from .search import _cv_iterative_spec

        est_cls = type(est)
        n_tasks = n_sets * n_splits
        task_args = {
            "fmask": np.repeat(fmasks, n_splits, axis=0),
            "split": np.tile(np.arange(n_splits, dtype=np.int32), n_sets),
            # fixed hypers broadcast per task so the CV slice kernels
            # (which read task["hyper"]) apply without a special case
            "hyper": {
                k: np.full(n_tasks, float(v), dtype=np.float32)
                for k, v in hyper.items()
            },
        }
        shared = {
            "X": data["X"],
            "y": data["y"],
            "sw": data["sw"],
            "aux": extract_aux(data),
            "train_masks": train_masks,
            "test_masks": test_masks,
        }

        def fb_kernel(shared, task):
            masked = dict(shared)
            masked["X"] = shared["X"] * task["fmask"]
            return base_kernel(
                masked, {"hyper": task["hyper"], "split": task["split"]}
            )

        fb_key = structural_key("eliminate_iter_fb", est_cls, base_key)
        rung_ctrl = None
        rung_spec = None
        if self.adaptive is not None:
            rung_spec = resolve_rung_scorer(
                self.adaptive.metric, scorer_specs, True, classes,
                est_cls=est_cls,
            )
            if rung_spec is not None:
                rung_ctrl = RungController(
                    self.adaptive.eta, self.adaptive.min_slices,
                    # group = feature set: a set's fold lanes live and
                    # die together on their mean rung score
                    groups=np.repeat(np.arange(n_sets), n_splits),
                )
        spec, iter_key = _cv_iterative_spec(
            est_cls, meta, static, scorer_specs, False, n_slice,
            fallback=fb_kernel, fallback_key=fb_key,
            rung_spec=rung_spec, mask_x=True,
        )
        scores = backend.batched_map_iterative(
            spec, task_args, shared,
            round_size=(
                None if self.partitions in ("auto", None) else round_size
            ),
            shared_specs=row_sharded_specs(backend, shared, {
                "X": 0, "y": 0, "sw": 0,
                "train_masks": 1, "test_masks": 1,
            }),
            cache_key=iter_key, rung=rung_ctrl,
        )
        flat = np.asarray(scores["test_score"], dtype=np.float64)
        if rung_ctrl is not None and rung_ctrl.active:
            # engaged only if the compacted slice loop actually ran the
            # rungs — a backend downgrade (multi-process mesh, OOM/
            # fault fallback) deactivates the controller and fit's
            # could-not-engage warning must fire
            self._adaptive_engaged_ = True
        if rung_ctrl is not None and rung_ctrl.killed:
            rungs = np.full(n_sets, -1, np.int32)
            for lane, r in rung_ctrl.killed.items():
                flat[lane] = np.nan
                s = int(lane) // n_splits
                rungs[s] = max(rungs[s], int(r))
            self._rung_per_set_ = rungs
            warnings.warn(
                f"{len(rung_ctrl.killed)} of {n_tasks} feature-set "
                "fits were retired early by adaptive successive "
                "halving; their sets score NaN and rung_ records "
                "where each died.",
                RungKilledWarning,
            )
        return flat.reshape(n_sets, n_splits)

    # ------------------------------------------------------------------
    def _apply_mask(self, X):
        """Column-select to the best feature set (reference
        eliminate.py:241-246)."""
        if not self.mask:
            return X
        if hasattr(X, "iloc"):
            return X.iloc[:, self.best_features_]
        if hasattr(X, "tocsc"):
            return X.tocsc()[:, self.best_features_].tocsr()
        return np.asarray(X)[:, self.best_features_]

    @property
    def best_estimator_(self):
        """Alias for the refit model — the reference exposes the refit
        result as ``best_estimator_`` (eliminate.py:236), and ported
        user code reads that name."""
        check_is_fitted(self, "estimator_")
        return self.estimator_

    def predict(self, X):
        check_is_fitted(self, "estimator_")
        return self.estimator_.predict(self._apply_mask(X))

    def predict_proba(self, X):
        check_is_fitted(self, "estimator_")
        return self.estimator_.predict_proba(self._apply_mask(X))

    def predict_log_proba(self, X):
        check_is_fitted(self, "estimator_")
        return self.estimator_.predict_log_proba(self._apply_mask(X))

    def decision_function(self, X):
        check_is_fitted(self, "estimator_")
        return self.estimator_.decision_function(self._apply_mask(X))

    def score(self, X, y=None):
        check_is_fitted(self, "estimator_")
        return self.estimator_.score(self._apply_mask(X), y)

    @property
    def classes_(self):
        check_is_fitted(self, "estimator_")
        return self.estimator_.classes_
