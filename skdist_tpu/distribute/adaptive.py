"""
Adaptive (ASHA-style) search configuration.

:class:`HalvingSpec` is the user-facing knob of quality-based lane
retirement on the convergence-compacted backend (asynchronous
successive halving — Li et al., *A System for Massively Parallel
Hyperparameter Tuning*, MLSys 2020; Hyperband — Li et al., JMLR 2018):

    DistGridSearchCV(est, grid, backend=backend,
                     adaptive=HalvingSpec(eta=3, min_slices=1))

Every ``min_slices`` iteration slices the scheduler scores ALL live
carries on the held-out validation fold *on device* (the
representation-polymorphic decision/proba kernels + the
``DEVICE_SCORERS`` rung kernel run as a fourth jit entry next to
init/step/finalize — carries never leave HBM; only an ``(n_lanes,)``
score vector joins the existing flags-only D2H), then kills the bottom
``1 - 1/eta`` of live candidates through the ordinary done-flag/
compaction path, so freed rounds collapse immediately. A candidate's
CV-fold lanes are grouped: they live and die together on their mean
rung score, which keeps ``cv_results_`` rows whole.

Killed candidates map to sklearn-compatible rows via the lane-
quarantine ``error_score`` semantics (a numeric ``error_score``
substitutes, the default ``np.nan`` ranks them last) with ONE
:class:`RungKilledWarning`, and ``cv_results_["rung_"]`` records the
rung at which each candidate died (``-1`` = ran to completion).

``eta=float('inf')`` scores every rung but kills nothing — the
parity-pinned observe-only mode: its ``cv_results_`` is byte-identical
to ``adaptive=None``.

The same spec drives the STREAMED search (``fit(ChunkedDataset, ...)``)
through the out-of-core drivers' pass-boundary rung seam: rungs fire at
whole-dataset block-pass boundaries (an L-BFGS iteration / SGD epoch),
scored with one extra pass of decomposable ``STREAM_SCORERS``
sufficient statistics over the already-resident blocks, and killed
candidates' task-tree lanes compact out of the streamed batch — later
passes stream the same bytes through fewer programs.
"""

import math
import warnings

__all__ = ["HalvingSpec", "RungKilledWarning", "check_adaptive",
           "warn_not_engaged"]


class RungKilledWarning(RuntimeWarning):
    """A batch of candidates was retired early by an adaptive rung and
    recorded at ``error_score`` (the adaptive analogue of
    ``FitFailedWarning`` — same row semantics, different cause: the
    fits were healthy, the scheduler judged them not worth finishing).
    """


class HalvingSpec:
    """Configuration of adaptive successive-halving search.

    Parameters
    ----------
    eta : float, default 3
        Reduction factor: each rung keeps the top ``ceil(live / eta)``
        candidates and kills the rest. Must be > 1; ``float('inf')``
        scores rungs but never kills (observe-only, bitwise-identical
        results to ``adaptive=None``).
    min_slices : int, default 1
        Rung cadence in iteration slices: a rung fires after every
        ``min_slices`` slices of the compacted loop (the slice size
        itself is ``SKDIST_SLICE_ITERS`` / ~1/8 of ``max_iter`` — see
        ``parallel.resolve_slice_iters``), so the first rung decision
        happens after ``min_slices * slice_iters`` iterations. On the
        streamed (ChunkedDataset) path the cadence unit is whole-
        dataset BLOCK PASSES instead: a rung fires after every
        ``min_slices`` passes (an L-BFGS iteration / SGD epoch).
    metric : str, default 'auto'
        Device scorer used for rung decisions. ``'auto'`` follows the
        search's refit metric. Must resolve to a ``DEVICE_SCORERS``
        kernel (resident path) or a decomposable ``STREAM_SCORERS``
        kernel (streamed path) compatible with the label set; when it
        cannot (host-only scorers, incompatible binary metrics),
        adaptive search WARNS and falls back to exhaustive execution —
        it never gathers per-rung predictions host-side.
    """

    def __init__(self, eta=3, min_slices=1, metric="auto"):
        eta = float(eta)
        if not eta > 1.0 or math.isnan(eta):
            raise ValueError(
                f"HalvingSpec eta must be > 1 (got {eta!r}); use "
                "float('inf') for the observe-only mode"
            )
        min_slices = int(min_slices)
        if min_slices < 1:
            raise ValueError(
                f"HalvingSpec min_slices must be >= 1 (got {min_slices!r})"
            )
        if not isinstance(metric, str):
            raise ValueError(
                "HalvingSpec metric must be a scorer name or 'auto' "
                f"(got {metric!r})"
            )
        self.eta = eta
        self.min_slices = min_slices
        self.metric = metric

    def get_params(self, deep=False):
        """sklearn-style param introspection — what the durable-
        checkpoint structural signature canonicalizes, so resuming an
        adaptive search with a changed eta/cadence/metric starts fresh
        instead of restoring rows a different race produced."""
        return {
            "eta": self.eta, "min_slices": self.min_slices,
            "metric": self.metric,
        }

    def __repr__(self):
        return (
            f"HalvingSpec(eta={self.eta!r}, min_slices={self.min_slices!r},"
            f" metric={self.metric!r})"
        )


def check_adaptive(adaptive):
    """Shared fit()-entry validation of the ``adaptive`` constructor
    param (search, multimodel, eliminator)."""
    if adaptive is not None and not isinstance(adaptive, HalvingSpec):
        raise ValueError(
            "adaptive must be None or a HalvingSpec(...); got "
            f"{adaptive!r}"
        )


def warn_not_engaged(context):
    """The shared could-not-engage warning: adaptive search fell back
    to EXHAUSTIVE execution (it never gathers per-rung predictions for
    a host scorer) — loudly, so a user counting on the speedup learns
    why it did not happen. ``context`` names the caller's task axis,
    e.g. "the search" or "the eliminator"."""
    warnings.warn(
        f"adaptive=HalvingSpec(...) could not engage: {context} did "
        "not run the compacted iterative device path end to end "
        "(host-only scorer, host-engine estimator, a non-sliceable "
        "family, a grid below the compaction threshold, or a backend "
        "downgrade to the exhaustive fallback). Ran exhaustive "
        "scoring instead.",
        UserWarning,
    )


def rung_per_candidate(n_candidates, n_splits, killed_gids):
    """Fold the per-lane kill record into the per-candidate ``rung_``
    column: the rung at which the candidate's lanes were killed (max
    over folds, for the degenerate case of folds dying at different
    rungs), ``-1`` for candidates that ran to completion."""
    import numpy as np

    rungs = np.full(n_candidates, -1, dtype=np.int32)
    for gid, rung in killed_gids.items():
        c = int(gid) // n_splits
        if 0 <= c < n_candidates:
            rungs[c] = max(rungs[c], int(rung))
    return rungs
