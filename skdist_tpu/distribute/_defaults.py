"""
Default encoder registry for ``Encoderizer`` type inference
(reference ``/root/reference/skdist/distribute/_defaults.py:28-204``).

Registry shape matches the reference: size ('small'/'medium'/'large') ×
encoder type ('string_vectorizer'/'onehotencoder'/'multihotencoder'/
'numeric'/'dict') → factory producing [(step_name, pipeline), ...].
Sizes differ in text handling: small = word 1-2grams; medium adds
char_wb 3-4grams; large = word 1-3 + char_wb 2-5 (reference
_defaults.py:91-198).

TPU-first divergence: hashed text widths are bounded per size (2^12 /
2^13 / 2^14 instead of the reference's 2^20) because downstream JAX
kernels densify their inputs — HBM-resident dense matrices need sane
widths. Raise via a custom ``config`` if you want sklearn-style widths.
"""

from sklearn.feature_extraction import DictVectorizer
from sklearn.feature_extraction.text import CountVectorizer
from sklearn.feature_selection import VarianceThreshold
from sklearn.impute import SimpleImputer
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import StandardScaler

from ..preprocessing import (
    FeatureCast,
    HashingVectorizerChunked,
    ImputeNull,
    MultihotEncoder,
    SelectField,
)

__all__ = ["_default_encoders"]


def tokenizer(x):
    """Identity tokenizer (pre-tokenised categorical values)."""
    return x


def dict_encoder(c):
    return [(
        f"{c}_dict_encoder",
        Pipeline([
            ("var", SelectField(cols=[c], single_dimension=True)),
            ("fillna", ImputeNull({})),
            ("vec", DictVectorizer()),
        ]),
    )]


def onehot_encoder(c):
    return [(
        f"{c}_onehot",
        Pipeline([
            ("var", SelectField(cols=[c], single_dimension=True)),
            ("cast", FeatureCast(cast_type=str)),
            ("fillna", ImputeNull("")),
            ("vec", CountVectorizer(
                token_pattern=None, tokenizer=tokenizer, binary=True,
                decode_error="ignore",
            )),
        ]),
    )]


def multihot_encoder(c):
    return [(
        f"{c}_multihot",
        Pipeline([
            ("var", SelectField(cols=[c], single_dimension=True)),
            ("fillna", ImputeNull([])),
            ("vec", MultihotEncoder()),
        ]),
    )]


def numeric_encoder(c):
    return [(
        f"{c}_scaler",
        Pipeline([
            ("var", SelectField(cols=[c])),
            ("imputer", SimpleImputer(strategy="median")),
            ("scaler", StandardScaler(copy=False)),
        ]),
    )]


def _text_vec(c, suffix, analyzer, ngram_range, n_features):
    return (
        f"{c}_{suffix}",
        Pipeline([
            ("var", SelectField(cols=[c], single_dimension=True)),
            ("fillna", ImputeNull(" ")),
            ("vec", HashingVectorizerChunked(
                ngram_range=ngram_range, analyzer=analyzer,
                n_features=n_features, alternate_sign=False,
                decode_error="ignore",
            )),
            ("var_thresh", VarianceThreshold()),
        ]),
    )


def _string_small(c):
    return [_text_vec(c, "word_vec", "word", (1, 2), 2**12)]


def _string_medium(c):
    return [
        _text_vec(c, "word_vec", "word", (1, 3), 2**13),
        _text_vec(c, "char_vec", "char_wb", (3, 4), 2**13),
    ]


def _string_large(c):
    return [
        _text_vec(c, "word_vec", "word", (1, 3), 2**14),
        _text_vec(c, "char_vec", "char_wb", (2, 5), 2**14),
    ]


def _size_registry(string_vectorizer):
    return {
        "string_vectorizer": string_vectorizer,
        "onehotencoder": onehot_encoder,
        "multihotencoder": multihot_encoder,
        "numeric": numeric_encoder,
        "dict": dict_encoder,
    }


_default_encoders = {
    "small": _size_registry(_string_small),
    "medium": _size_registry(_string_medium),
    "large": _size_registry(_string_large),
}
