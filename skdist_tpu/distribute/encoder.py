"""
Encoderizer: mixed-type feature encoding with per-transformer fan-out
(reference ``/root/reference/skdist/distribute/encoder.py:33-411``).

A FeatureUnion-style encoder that accepts pandas / dict / numpy / list
input, infers a per-column transformer pipeline from dtype and
cardinality (or takes an explicit ``config``), fits each transformer as
one task — the reference's per-transformer Spark tasks
(encoder.py:137-153) become ``backend.run_tasks`` host tasks here
(featurisation is host-side text/sparse work; the TPU's job starts at
the resulting matrix) — records per-transformer output widths, maps
feature index → origin step, and can ``extract`` a fitted slice of
itself.
"""

import ast
import copy as _copy

import numpy as np
from pandas import DataFrame
from scipy import sparse

from ..base import BaseEstimator, TransformerMixin, clone, strip_runtime
from ..parallel import resolve_backend
from ..utils.validation import check_is_fitted

__all__ = ["Encoderizer", "EncoderizerExtractor"]


class Encoderizer(BaseEstimator, TransformerMixin):
    """Flexible-input feature encoder with inferred or configured
    per-column pipelines (reference encoder.py:33-387)."""

    def __init__(self, transformer_list=None, transformer_weights=None,
                 n_jobs=None, size="small", config=None, col_names=None,
                 backend=None, partitions="auto", verbose=0):
        self.transformer_list = transformer_list
        self.transformer_weights = transformer_weights
        self.n_jobs = n_jobs
        self.size = size
        self.config = config
        self.col_names = col_names
        self.backend = backend
        self.partitions = partitions
        self.verbose = verbose

    # ------------------------------------------------------------------
    def fit(self, X, y=None):
        backend = resolve_backend(self.backend, n_jobs=self.n_jobs)
        X = self._process_input(X)
        # the constructor param is never mutated (sklearn contract:
        # clone(fitted) must reproduce the unfitted config — VERDICT
        # weak #6): transformers are CLONED, fit, and stored in the
        # fitted-state `transformer_list_`
        templates = self.transformer_list
        if templates is None:
            templates = self._infer_transformers(X)
        templates = list(templates)
        if not templates:
            raise ValueError("No transformers to fit (all columns null?)")

        def fit_one(item):
            name, trans = item
            t = clone(trans, safe=False)
            return t.fit(X, y) if y is not None else t.fit(X)

        fitted = backend.run_tasks(
            fit_one,
            [(name, trans) for name, trans in templates],
            verbose=self.verbose,
        )
        self.transformer_list_ = [
            (name, fit_t)
            for (name, _), fit_t in zip(templates, fitted)
        ]
        self._feature_indices(X)
        strip_runtime(self)
        return self

    @property
    def _steps(self):
        """Fitted (name, transformer) pairs when fit has run, else the
        constructor's template list — so ``step_names`` answers both
        pre- and post-fit, as before."""
        fitted = getattr(self, "transformer_list_", None)
        return fitted if fitted is not None else (self.transformer_list or [])

    def transform(self, X):
        check_is_fitted(self, "transformer_lengths")
        from ..data import is_chunked

        if is_chunked(X):
            return self._transform_chunked(X)
        X = self._process_input(X, fit=False)
        weights = self.transformer_weights or {}
        Xs = []
        for name, trans in self.transformer_list_:
            out = trans.transform(X)
            w = weights.get(name)
            if w is not None:
                out = out * w
            Xs.append(out)
        if not Xs:
            return np.zeros((X.shape[0], 0))
        if any(sparse.issparse(f) for f in Xs):
            return sparse.hstack(Xs).tocsr()
        return np.hstack([np.asarray(f) for f in Xs])

    def _transform_chunked(self, dataset):
        """ChunkedDataset pass-through: encode block by block, lazily —
        the returned dataset's readers run this fitted encoder over
        each raw block at stream time, so the feature-encoding step
        never densifies (or even materialises) the whole input. Encoded
        blocks are dense float32 (block-LOCAL densification of sparse
        transformer output is bounded by block_rows); y/sample_weight
        ride through untouched."""
        fields = list(self.fields_)
        out_width = int(np.sum(self.transformer_lengths))

        def encode(block, start, stop):
            raw = block["X"]
            if hasattr(raw, "toarray"):
                raw = raw.toarray()
            frame = DataFrame(np.asarray(raw), columns=fields)
            enc = self.transform(frame)
            if sparse.issparse(enc):
                enc = enc.toarray()
            return {"X": np.ascontiguousarray(enc, dtype=np.float32)}

        return dataset.map_blocks(encode, n_features=out_width)

    def fit_transform(self, X, y=None, **fit_params):
        return self.fit(X, y).transform(X)

    # ------------------------------------------------------------------
    def extract(self, step_names):
        """Fitted copy holding only the requested steps (reference
        encoder.py:88-110)."""
        check_is_fitted(self, "transformer_lengths")
        enc = _copy.copy(self)
        keep = [i for i, n in enumerate(self.step_names) if n in step_names]
        enc.transformer_list_ = [self.transformer_list_[i] for i in keep]
        enc.transformer_lengths = [self.transformer_lengths[i] for i in keep]
        return enc

    def feature_origin(self, index, mask=None):
        """Step name owning transformed-feature ``index`` (reference
        encoder.py:209-230)."""
        cumulative = np.cumsum(self.transformer_lengths)
        if mask is not None:
            cumulative = np.array([mask[x - 1] for x in cumulative])
        return self.step_names[int(np.argmax(cumulative > index))]

    @property
    def step_names(self):
        return [name for name, _ in self._steps]

    # ------------------------------------------------------------------
    def _process_input(self, X, fit=True):
        """pandas / dict / numpy / list / spark-like → DataFrame
        (reference encoder.py:237-266)."""
        if isinstance(X, DataFrame):
            out = X
        elif isinstance(X, dict):
            try:
                out = DataFrame.from_dict(X, orient="columns")
            except Exception as exc:
                raise ValueError("Cannot parse input") from exc
        elif isinstance(X, (np.ndarray, list)):
            if fit and self.col_names is None:
                raise ValueError("Must supply col_names with numpy array input")
            cols = self.col_names if fit else self.fields_
            out = DataFrame(X, columns=list(cols))
        elif hasattr(X, "toPandas"):  # pyspark-style DataFrame
            out = X.toPandas()
        else:
            raise ValueError("Cannot parse input")
        if fit:
            self.fields_ = list(out.columns)
        return out

    def _infer_transformers(self, X):
        from ._defaults import _default_encoders

        if self.config is not None:
            lst = [
                _default_encoders[self.size][v](c)
                for c, v in self.config.items()
            ]
        else:
            lst = [self._infer_column(c, X[c]) for c in X.columns]
        return [item for sub in lst if sub is not None for item in sub]

    @staticmethod
    def _first_non_null(col):
        vals = col.values
        for v in vals:
            if v is not None and not (isinstance(v, float) and np.isnan(v)):
                return v
        return None

    @classmethod
    def _container_kind(cls, col, col_name):
        """dict / list / tuple sniffing with the reference's
        string-that-parses guard (encoder.py:281-342)."""
        v = cls._first_non_null(col)
        if isinstance(v, str):
            try:
                parsed = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                return None
            kind = type(parsed).__name__
            if kind in ("dict", "list", "tuple"):
                raise ValueError(
                    f"Convert this column to {kind} before fitting: {col_name}"
                )
            return None
        if isinstance(v, dict):
            return "dict"
        if isinstance(v, (list, tuple)):
            return "container"
        return None

    def _infer_column(self, col_name, col, thresh=0.10):
        """Per-column encoder inference (reference encoder.py:344-377):
        dict → DictVectorizer; list/tuple → multihot; else numeric vs
        categorical (<10% unique) vs free text."""
        from ._defaults import _default_encoders

        registry = _default_encoders[self.size]
        if col.isnull().all():
            import warnings

            warnings.warn(f"Column is entirely null: {col_name}")
            return None
        kind = self._container_kind(col, col_name)
        if kind == "dict":
            return registry["dict"](col_name)
        if kind == "container":
            return registry["multihotencoder"](col_name)
        try:
            np.mean(col.values)
            is_numeric = True
        except Exception:
            is_numeric = False
        pct_unique = col.nunique() / float(len(col))
        is_categorical = pct_unique < thresh
        if not is_numeric and not is_categorical:
            return registry["string_vectorizer"](col_name)
        if is_numeric and not is_categorical:
            return registry["numeric"](col_name)
        return registry["onehotencoder"](col_name)

    def _feature_indices(self, X):
        """Record per-transformer output widths (reference
        encoder.py:379-387)."""
        lengths = []
        head = X.head(1)
        for _, trans in self.transformer_list_:
            out = trans.transform(head)
            lengths.append(
                len(out[0]) if isinstance(out, list) else out.shape[1]
            )
        self.transformer_lengths = lengths


class EncoderizerExtractor(BaseEstimator, TransformerMixin):
    """Pass-through slice of a fitted Encoderizer, for pipeline
    hyperparameter search (reference encoder.py:390-411)."""

    def __init__(self, encoderizer, step_names):
        self.encoderizer = encoderizer
        self.step_names = step_names

    def fit(self, X, y=None):
        return self

    def transform(self, X):
        return self.encoderizer.extract(self.step_names).transform(X)
