"""
Structured span tracing: one timeline for every plane of the framework.

Dapper-style nested spans (Sigelman et al., 2010) recorded into a
bounded in-process ring and exported as Chrome trace-event JSON —
loadable directly in Perfetto (``ui.perfetto.dev``) or
``chrome://tracing`` — so a whole search's dispatch structure
(``round_dispatch`` per device round, ``compile`` on every cache miss,
``block_feed`` per streamed block, ``flush`` per serving micro-batch,
``rung_eval`` per ASHA rung, ``replica_failover``/``replica_respawn``
on fleet events) reads as one picture instead of five subsystems' log
lines.

**Cost model.** Tracing is OFF by default and the off path is
allocation-free: ``span(name)`` returns a module-level no-op singleton
(no object construction, no ring append, no clock read) — the
``SKDIST_TRACE=0`` hot-path contract ``tests/test_obs.py`` pins with
an allocation spy. ``SKDIST_TRACE=1`` turns recording on; each span
costs two ``perf_counter`` reads and one ring append at exit.
Instrumentation sites are per-ROUND / per-BLOCK / per-FLUSH — never
per-task or per-row — so even traced overhead stays inside the
obs-smoke's 5% gate.

**Device-time attribution.** ``SKDIST_TRACE_JAX=1`` additionally
enters a ``jax.profiler.TraceAnnotation`` for every span, so a
chip-side profile capture (``jax.profiler.trace`` / XProf) attributes
device time to framework phases — the capture prerequisite of ROADMAP
item 5's chip legs. Off by default: the annotation has nonzero cost
even with no profiler session active.

**Bounding.** The ring holds the most recent ``SKDIST_TRACE_RING``
events (default 65536, ~15 MB of dicts at export time); older events
drop oldest-first, so a long-lived server can leave tracing on and
export a bounded tail on demand. Overflow is NOT silent: every evicted
event bills the ``trace.dropped_spans`` registry counter and the
export's ``otherData.dropped`` field, so a truncated trace is
detectable from both the exposition and the trace file itself.

**Cross-process context** (Dapper, Sigelman et al. 2010): a
(trace_id, span_id) pair rides :func:`new_context` /
:func:`use_context` / :func:`current_context`. While a context is
active, every recorded span allocates its own span id, re-points the
thread-local context for its duration (so nested spans — and spans on
threads that adopted the context — chain parent ids), and stamps
``trace_id``/``span_id``/``parent_id`` into its exported ``args``. A
request frame carries the context across a process boundary (the
procfleet wire protocol's ``_trace`` field); the worker adopts it, so
its ``flush``/``compile``/``bank_swap`` spans parent under the
router's span. :func:`stitch_traces` is the collector: it merges
per-process Chrome-trace rings (each exported on the WALL clock —
``clock="wall"`` — because each process's perf_counter epoch is
private) into one Perfetto-loadable file with named per-process
tracks and synthesized flow arrows (``ph: s/f``) from every
cross-process parent link.
"""

import json
import os
import threading
import time
import uuid
from collections import deque

__all__ = [
    "enabled",
    "set_enabled",
    "span",
    "instant",
    "events",
    "clear",
    "set_ring_size",
    "dropped",
    "new_context",
    "current_context",
    "use_context",
    "export_chrome_trace",
    "chrome_trace_events",
    "trace_part",
    "stitch_traces",
]


def _env_flag(name, default=False):
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw in ("1", "true", "yes", "on")


#: module-level enabled flag — ONE attribute read on the hot path
_ENABLED = _env_flag("SKDIST_TRACE")
_JAX_ANNOTATE = _env_flag("SKDIST_TRACE_JAX")

_RING_SIZE = int(os.environ.get("SKDIST_TRACE_RING", "") or 65536)
#: (name, ph, t_start_s, dur_s, thread_id, args_or_None) tuples;
#: deque.append is atomic under the GIL — no lock on the record path
_RING = deque(maxlen=_RING_SIZE)

#: perf_counter epoch the exported timestamps are relative to, so a
#: trace's ts values start near 0 instead of at host-uptime microseconds
_EPOCH = time.perf_counter()
#: the SAME instant on the wall clock: perf_counter is process-private,
#: so cross-process stitching exports ts relative to this shared clock
#: (clock="wall") — within one process t_wall = t_perf - _EPOCH +
#: _EPOCH_WALL, and wall clocks agree across same-host processes
_EPOCH_WALL = time.time()

#: events evicted from the ring since it was last (re)created — the
#: export's truncation marker; the cumulative count also lands on the
#: ``trace.dropped_spans`` registry counter. Plain int updated under
#: the GIL next to the deque append (exactness under racing writers is
#: not worth a lock on the record path; the counter's job is "did the
#: ring overflow", not byte accounting).
_DROPPED = 0
_DROPPED_COUNTER = None


def _note_drop():
    global _DROPPED, _DROPPED_COUNTER
    _DROPPED += 1
    c = _DROPPED_COUNTER
    if c is None:
        from . import metrics as _metrics

        c = _DROPPED_COUNTER = _metrics.counter(
            "trace.dropped_spans",
            help="trace events evicted from the bounded ring",
        )
    c.inc()


def _append(ev):
    if len(_RING) == _RING_SIZE:
        _note_drop()
    _RING.append(ev)


def dropped():
    """Events evicted from the ring since it was last (re)created."""
    return _DROPPED


def enabled():
    """Whether span recording is on (cached; see :func:`set_enabled`)."""
    return _ENABLED


def set_enabled(flag=None):
    """Turn tracing on/off at runtime (tests, smokes, a server's admin
    endpoint). ``None`` re-reads ``SKDIST_TRACE`` from the environment.
    Returns the new state."""
    global _ENABLED
    _ENABLED = _env_flag("SKDIST_TRACE") if flag is None else bool(flag)
    return _ENABLED


def set_ring_size(n):
    """Re-bound the event ring (drops current contents and resets the
    export-side ``dropped`` marker; the registry counter stays
    cumulative)."""
    global _RING, _RING_SIZE, _DROPPED
    _RING_SIZE = max(1, int(n))
    _RING = deque(maxlen=_RING_SIZE)
    _DROPPED = 0


def clear():
    global _DROPPED
    _RING.clear()
    _DROPPED = 0


# ---------------------------------------------------------------------------
# trace/span context (cross-process parenting)
# ---------------------------------------------------------------------------

_CTX = threading.local()


def _span_id():
    return uuid.uuid4().hex[:16]


def new_context():
    """A fresh root context: ``{"trace_id", "span_id"}`` (hex ids).
    The creator's ``span_id`` is the parent of everything recorded
    under the context — a router makes one per request and ships it in
    the request frame."""
    return {"trace_id": uuid.uuid4().hex[:16], "span_id": _span_id()}


def current_context():
    """This thread's active context dict, or None."""
    return getattr(_CTX, "ctx", None)


class _CtxScope:
    __slots__ = ("ctx", "prev")

    def __init__(self, ctx):
        self.ctx = ctx
        self.prev = None

    def __enter__(self):
        self.prev = getattr(_CTX, "ctx", None)
        if self.ctx is not None:
            _CTX.ctx = dict(self.ctx)
        return self

    def __exit__(self, *exc):
        _CTX.ctx = self.prev
        return False


def use_context(ctx):
    """Context manager adopting ``ctx`` (a dict from
    :func:`new_context`, possibly shipped from another process) as this
    thread's active trace context. ``None`` is a no-op scope, so call
    sites need no branch."""
    return _CtxScope(ctx)


def _annotation(name):
    """A live jax.profiler.TraceAnnotation, or None when the passthrough
    is off or jax is unavailable."""
    if not _JAX_ANNOTATE:
        return None
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    except Exception:
        return None


class _Span:
    """One live span: records a complete ('X') event at exit. Nesting
    needs no explicit depth bookkeeping — Perfetto derives it from the
    containment of each thread's ts/dur intervals. When a trace
    context is active the span additionally allocates its own span id,
    chains the thread context under itself for its duration, and
    stamps the ids into its exported args (cross-process parenting —
    module docstring); with no context active none of that work
    happens."""

    __slots__ = ("name", "args", "t0", "_ann", "_ids", "_prev_ctx")

    def __init__(self, name, args):
        self.name = name
        self.args = args
        self.t0 = 0.0
        self._ann = None
        self._ids = None
        self._prev_ctx = None

    def __enter__(self):
        ann = _annotation(self.name)
        if ann is not None:
            ann.__enter__()
            self._ann = ann
        ctx = getattr(_CTX, "ctx", None)
        if ctx is not None:
            sid = _span_id()
            self._ids = {
                "trace_id": ctx["trace_id"],
                "span_id": sid,
                "parent_id": ctx["span_id"],
            }
            self._prev_ctx = ctx
            _CTX.ctx = {"trace_id": ctx["trace_id"], "span_id": sid}
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        args = self.args
        if self._ids is not None:
            _CTX.ctx = self._prev_ctx
            args = dict(args) if args else {}
            args.update(self._ids)
        _append((
            self.name, "X", self.t0, t1 - self.t0,
            threading.get_ident(), args,
        ))
        return False


class _NoopSpan:
    """The disabled-path singleton: enter/exit do nothing and allocate
    nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name, args=None):
    """Context manager recording one nested span while tracing is on.

    ``args`` (an optional dict) lands in the exported event's ``args``
    — build it only when :func:`enabled` is true, or the allocation
    defeats the off-path zero-cost contract (which is also why this is
    a positional dict rather than ``**kwargs``: an empty kwargs dict
    would be allocated per call even when disabled)."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, args)


def instant(name, args=None):
    """Record a zero-duration instant event ('i' phase — rendered as a
    flag line in Perfetto): rung kills, lane retirements, elastic
    shrinks, replica failovers."""
    if not _ENABLED:
        return
    ctx = getattr(_CTX, "ctx", None)
    if ctx is not None:
        args = dict(args) if args else {}
        args.setdefault("trace_id", ctx["trace_id"])
        args.setdefault("parent_id", ctx["span_id"])
    _append((
        name, "i", time.perf_counter(), 0.0,
        threading.get_ident(), args,
    ))


def events():
    """The ring's current events as raw tuples (oldest first)."""
    return list(_RING)


def chrome_trace_events(clock="epoch", limit=None):
    """The ring rendered as Chrome trace-event dicts (the
    ``traceEvents`` array): complete events carry ``ph="X"`` with
    microsecond ``ts``/``dur``; instants carry ``ph="i"`` with thread
    scope. ``clock="epoch"`` (default) makes timestamps relative to the
    module's import epoch (single-process traces start near 0);
    ``clock="wall"`` rebases them onto the wall clock so rings from
    different processes of one host line up for :func:`stitch_traces`.
    ``limit`` renders only the ring's most recent N events — callers
    on a CADENCE (the flight recorder's per-second standing dump, the
    fleet's telemetry harvest) must bound this, or a full 64k ring
    costs ~15 MB of dicts per tick.
    """
    base = _EPOCH if clock == "epoch" else (_EPOCH - _EPOCH_WALL)
    pid = os.getpid()
    out = []
    ring = list(_RING)
    if limit is not None:
        ring = ring[-int(limit):]
    for name, ph, t0, dur, tid, args in ring:
        ev = {
            "name": name,
            "cat": "skdist",
            "ph": ph,
            "ts": (t0 - base) * 1e6,
            "pid": pid,
            "tid": tid,
        }
        if ph == "X":
            ev["dur"] = dur * 1e6
        else:
            ev["s"] = "t"
        if args:
            ev["args"] = dict(args)
        out.append(ev)
    return out


def export_chrome_trace(path=None, clock="epoch"):
    """Export the ring as a Chrome trace-event JSON object (and write
    it to ``path`` when given). The object form (``{"traceEvents":
    [...], "displayTimeUnit": "ms"}``) is what Perfetto's legacy JSON
    importer and ``chrome://tracing`` both load. ``otherData.dropped``
    counts events the bounded ring evicted — nonzero means the file is
    a truncated tail, not the whole story."""
    doc = {
        "traceEvents": chrome_trace_events(clock=clock),
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "skdist_tpu.obs.trace",
            "dropped": int(_DROPPED),
        },
    }
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    return doc


# ---------------------------------------------------------------------------
# the collector: stitch per-process rings into one trace
# ---------------------------------------------------------------------------

def trace_part(label=None, limit=None):
    """This process's ring as a stitchable part (wall-clock events +
    identity + truncation marker) — what the procfleet ``telemetry``
    harvest ships from each worker. ``limit`` bounds the shipped tail
    (the harvest runs on an interval; an unbounded full ring would
    cost ~15 MB of pickle per replica per tick with tracing on)."""
    n = len(_RING)
    shipped = n if limit is None else min(n, int(limit))
    return {
        "pid": os.getpid(),
        "label": label or f"pid {os.getpid()}",
        "dropped": int(_DROPPED) + (n - shipped),
        "events": chrome_trace_events(clock="wall", limit=limit),
    }


def stitch_traces(parts, path=None):
    """Merge per-process trace parts (:func:`trace_part` dicts) into
    ONE Perfetto-loadable Chrome trace document.

    - every part's events keep their own ``pid`` (overridden by the
      part's ``pid`` when the events lack one), so each process is its
      own track group, and a ``process_name`` metadata event names the
      track with the part's ``label`` (e.g. ``replica 1 (pid 4242)``);
    - parent links that cross a process boundary (a span whose
      ``args.parent_id`` was recorded in a DIFFERENT pid — the shipped
      request context) become Chrome flow arrows: an ``s`` event at
      the parent span and a matching ``f`` (``bp: "e"``) at the child,
      so Perfetto draws the router→worker causality;
    - ``otherData.dropped`` sums every part's eviction count.

    Events must have been exported on the wall clock
    (``chrome_trace_events(clock="wall")``); same-host processes share
    it, which is the procfleet deployment shape."""
    parts = list(parts)
    events = []
    dropped_total = 0
    span_home = {}  # span_id -> (pid, tid, ts) of the span that owns it
    for part in parts:
        pid = int(part.get("pid") or 0)
        dropped_total += int(part.get("dropped") or 0)
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": str(part.get("label") or pid)},
        })
        for ev in part.get("events", ()):
            ev = dict(ev)
            ev.setdefault("pid", pid)
            events.append(ev)
            args = ev.get("args") or {}
            sid = args.get("span_id")
            if sid:
                span_home[sid] = (ev["pid"], ev.get("tid", 0), ev["ts"])
    flows = []
    for ev in events:
        args = ev.get("args") or {}
        parent = args.get("parent_id")
        if not parent or parent not in span_home:
            continue
        ppid, ptid, pts = span_home[parent]
        if ppid == ev.get("pid"):
            continue  # same-process nesting: containment already shows it
        fid = args.get("span_id") or f"i{len(flows)}"
        flows.append({
            "name": "route", "cat": "skdist.flow", "ph": "s",
            "id": fid, "pid": ppid, "tid": ptid, "ts": pts,
        })
        flows.append({
            "name": "route", "cat": "skdist.flow", "ph": "f", "bp": "e",
            "id": fid, "pid": ev["pid"], "tid": ev.get("tid", 0),
            "ts": ev["ts"],
        })
    doc = {
        "traceEvents": events + flows,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "skdist_tpu.obs.trace.stitch",
            "dropped": dropped_total,
            "processes": len(parts),
        },
    }
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    return doc
