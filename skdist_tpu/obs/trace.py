"""
Structured span tracing: one timeline for every plane of the framework.

Dapper-style nested spans (Sigelman et al., 2010) recorded into a
bounded in-process ring and exported as Chrome trace-event JSON —
loadable directly in Perfetto (``ui.perfetto.dev``) or
``chrome://tracing`` — so a whole search's dispatch structure
(``round_dispatch`` per device round, ``compile`` on every cache miss,
``block_feed`` per streamed block, ``flush`` per serving micro-batch,
``rung_eval`` per ASHA rung, ``replica_failover``/``replica_respawn``
on fleet events) reads as one picture instead of five subsystems' log
lines.

**Cost model.** Tracing is OFF by default and the off path is
allocation-free: ``span(name)`` returns a module-level no-op singleton
(no object construction, no ring append, no clock read) — the
``SKDIST_TRACE=0`` hot-path contract ``tests/test_obs.py`` pins with
an allocation spy. ``SKDIST_TRACE=1`` turns recording on; each span
costs two ``perf_counter`` reads and one ring append at exit.
Instrumentation sites are per-ROUND / per-BLOCK / per-FLUSH — never
per-task or per-row — so even traced overhead stays inside the
obs-smoke's 5% gate.

**Device-time attribution.** ``SKDIST_TRACE_JAX=1`` additionally
enters a ``jax.profiler.TraceAnnotation`` for every span, so a
chip-side profile capture (``jax.profiler.trace`` / XProf) attributes
device time to framework phases — the capture prerequisite of ROADMAP
item 5's chip legs. Off by default: the annotation has nonzero cost
even with no profiler session active.

**Bounding.** The ring holds the most recent ``SKDIST_TRACE_RING``
events (default 65536, ~15 MB of dicts at export time); older events
drop oldest-first, so a long-lived server can leave tracing on and
export a bounded tail on demand.
"""

import json
import os
import threading
import time
from collections import deque

__all__ = [
    "enabled",
    "set_enabled",
    "span",
    "instant",
    "events",
    "clear",
    "set_ring_size",
    "export_chrome_trace",
    "chrome_trace_events",
]


def _env_flag(name, default=False):
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw in ("1", "true", "yes", "on")


#: module-level enabled flag — ONE attribute read on the hot path
_ENABLED = _env_flag("SKDIST_TRACE")
_JAX_ANNOTATE = _env_flag("SKDIST_TRACE_JAX")

_RING_SIZE = int(os.environ.get("SKDIST_TRACE_RING", "") or 65536)
#: (name, ph, t_start_s, dur_s, thread_id, args_or_None) tuples;
#: deque.append is atomic under the GIL — no lock on the record path
_RING = deque(maxlen=_RING_SIZE)

#: perf_counter epoch the exported timestamps are relative to, so a
#: trace's ts values start near 0 instead of at host-uptime microseconds
_EPOCH = time.perf_counter()


def enabled():
    """Whether span recording is on (cached; see :func:`set_enabled`)."""
    return _ENABLED


def set_enabled(flag=None):
    """Turn tracing on/off at runtime (tests, smokes, a server's admin
    endpoint). ``None`` re-reads ``SKDIST_TRACE`` from the environment.
    Returns the new state."""
    global _ENABLED
    _ENABLED = _env_flag("SKDIST_TRACE") if flag is None else bool(flag)
    return _ENABLED


def set_ring_size(n):
    """Re-bound the event ring (drops current contents)."""
    global _RING, _RING_SIZE
    _RING_SIZE = max(1, int(n))
    _RING = deque(maxlen=_RING_SIZE)


def clear():
    _RING.clear()


def _annotation(name):
    """A live jax.profiler.TraceAnnotation, or None when the passthrough
    is off or jax is unavailable."""
    if not _JAX_ANNOTATE:
        return None
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    except Exception:
        return None


class _Span:
    """One live span: records a complete ('X') event at exit. Nesting
    needs no explicit depth bookkeeping — Perfetto derives it from the
    containment of each thread's ts/dur intervals."""

    __slots__ = ("name", "args", "t0", "_ann")

    def __init__(self, name, args):
        self.name = name
        self.args = args
        self.t0 = 0.0
        self._ann = None

    def __enter__(self):
        ann = _annotation(self.name)
        if ann is not None:
            ann.__enter__()
            self._ann = ann
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        _RING.append((
            self.name, "X", self.t0, t1 - self.t0,
            threading.get_ident(), self.args,
        ))
        return False


class _NoopSpan:
    """The disabled-path singleton: enter/exit do nothing and allocate
    nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name, args=None):
    """Context manager recording one nested span while tracing is on.

    ``args`` (an optional dict) lands in the exported event's ``args``
    — build it only when :func:`enabled` is true, or the allocation
    defeats the off-path zero-cost contract (which is also why this is
    a positional dict rather than ``**kwargs``: an empty kwargs dict
    would be allocated per call even when disabled)."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, args)


def instant(name, args=None):
    """Record a zero-duration instant event ('i' phase — rendered as a
    flag line in Perfetto): rung kills, lane retirements, elastic
    shrinks, replica failovers."""
    if not _ENABLED:
        return
    _RING.append((
        name, "i", time.perf_counter(), 0.0,
        threading.get_ident(), args,
    ))


def events():
    """The ring's current events as raw tuples (oldest first)."""
    return list(_RING)


def chrome_trace_events():
    """The ring rendered as Chrome trace-event dicts (the
    ``traceEvents`` array): complete events carry ``ph="X"`` with
    microsecond ``ts``/``dur``; instants carry ``ph="i"`` with thread
    scope. Timestamps are relative to the module's import epoch."""
    pid = os.getpid()
    out = []
    for name, ph, t0, dur, tid, args in list(_RING):
        ev = {
            "name": name,
            "cat": "skdist",
            "ph": ph,
            "ts": (t0 - _EPOCH) * 1e6,
            "pid": pid,
            "tid": tid,
        }
        if ph == "X":
            ev["dur"] = dur * 1e6
        else:
            ev["s"] = "t"
        if args:
            ev["args"] = dict(args)
        out.append(ev)
    return out


def export_chrome_trace(path=None):
    """Export the ring as a Chrome trace-event JSON object (and write
    it to ``path`` when given). The object form (``{"traceEvents":
    [...], "displayTimeUnit": "ms"}``) is what Perfetto's legacy JSON
    importer and ``chrome://tracing`` both load."""
    doc = {
        "traceEvents": chrome_trace_events(),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "skdist_tpu.obs.trace"},
    }
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    return doc
