"""
Exporters over the metrics registry: Prometheus text exposition and a
JSON snapshot.

The registry (``obs.metrics``) is the store; this module is the read
side a scrape endpoint or a dump-to-disk debug path serves:

- :func:`prometheus_text` — the Prometheus text exposition format
  (version 0.0.4): one ``# TYPE`` header per family, one sample line
  per label child, counters suffixed ``_total``, histograms expanded
  to cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``. Metric
  names are sanitized (``compile.kernel_hits`` →
  ``skdist_compile_kernel_hits``) so the output parses under the
  official exposition grammar.
- :func:`json_snapshot` — the same content as nested plain dicts
  (JSON-serializable), for the serving fleet's stats endpoints and the
  bench/smoke capture files.
- :func:`fleet_text` / :func:`fleet_snapshot` — the serving-fleet
  views: the registry's serve.* families already carry ``replica`` and
  ``model`` (``name@version``) label dimensions (recorded by
  ``serve/stats.py``), so per-tenant dashboards are a label filter,
  not a new collection path. The multi-tenant bank families ride the
  same prefix: ``serve.bank_rebuilds`` / ``serve.bank_occupancy`` /
  ``serve.bank_members`` / ``serve.bank_capacity`` /
  ``serve.bank_resident_bytes`` (labeled per bank) and the
  ``serve.tenants_per_flush`` count histogram. At 1000+ tenants the
  per-model dimension is the exposition's cardinality risk — engines
  running ``fleet_rollup_only`` (``serve/stats.py``) never bind it, so
  a scrape stays O(pages) with the per-bank gauges carrying the
  catalog-level story.
"""

import json
import re

from . import metrics as _metrics

__all__ = [
    "prometheus_text",
    "json_snapshot",
    "fleet_text",
    "fleet_snapshot",
    "escape_label_value",
]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
#: exposition-grammar escapes for label VALUES (exactly the three
#: escapable characters of the text format: backslash first so an
#: escaped escape never double-fires) — a model registered as
#: ``name@version`` with quotes/newlines in its name must still emit
#: parseable text
_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}
#: HELP text escapes only backslash and newline (quotes are legal there)
_HELP_ESC = {"\\": "\\\\", "\n": "\\n"}


def _prom_name(name, prefix="skdist"):
    name = _NAME_RE.sub("_", name)
    return f"{prefix}_{name}" if prefix else name


def escape_label_value(v):
    """One label value under the text-exposition escaping rules."""
    return "".join(_LABEL_ESC.get(c, c) for c in str(v))


def _prom_labels(key, extra=()):
    pairs = list(extra) + list(key)
    if not pairs:
        return ""
    body = ",".join(
        '{}="{}"'.format(_NAME_RE.sub("_", k), escape_label_value(v))
        for k, v in pairs
    )
    return "{" + body + "}"


def _fmt(v):
    if isinstance(v, float):
        # the grammar's value tokens for non-finite floats
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        return repr(v)
    return str(v)


def _headers(lines, pname, kind, help_text):
    """The per-family ``# HELP`` + ``# TYPE`` pair (HELP first, the
    conventional order; omitted when the family registered no help)."""
    if help_text:
        esc = "".join(_HELP_ESC.get(c, c) for c in str(help_text))
        lines.append(f"# HELP {pname} {esc}")
    lines.append(f"# TYPE {pname} {kind}")


def prometheus_text(registry=None, prefix="skdist"):
    """Render ``registry`` (default: the process registry) in the
    Prometheus text exposition format. Returns one string ending in a
    newline."""
    reg = registry if registry is not None else _metrics.registry()
    lines = []
    for name, fam in sorted(reg.families().items()):
        pname = _prom_name(name, prefix)
        if fam.kind == "counter":
            _headers(lines, f"{pname}_total", "counter", fam.help)
            for key, v in sorted(fam.children().items()):
                lines.append(
                    f"{pname}_total{_prom_labels(key)} {_fmt(v)}"
                )
        elif fam.kind == "gauge":
            _headers(lines, pname, "gauge", fam.help)
            for key, v in sorted(fam.children().items()):
                lines.append(f"{pname}{_prom_labels(key)} {_fmt(v)}")
        elif fam.kind == "histogram":
            _headers(lines, pname, "histogram", fam.help)
            bounds = fam.buckets
            for key, child in sorted(fam.children().items()):
                cum = 0
                for b, c in zip(bounds, child["counts"]):
                    cum += c
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(key, [('le', _fmt(float(b)))])} "
                        f"{cum}"
                    )
                lines.append(
                    f"{pname}_bucket"
                    f"{_prom_labels(key, [('le', '+Inf')])} "
                    f"{child['count']}"
                )
                lines.append(
                    f"{pname}_sum{_prom_labels(key)} "
                    f"{_fmt(float(child['sum']))}"
                )
                lines.append(
                    f"{pname}_count{_prom_labels(key)} {child['count']}"
                )
    return "\n".join(lines) + "\n"


def json_snapshot(registry=None, path=None):
    """The registry as nested plain dicts (JSON-serializable); written
    to ``path`` when given."""
    reg = registry if registry is not None else _metrics.registry()
    snap = reg.snapshot()
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, indent=1, sort_keys=True)
    return snap


def _serve_only(reg):
    out = {}
    for name, fam in reg.families().items():
        if name.startswith("serve.") or name.startswith("rounds."):
            out[name] = fam
    return out


class _View:
    """Minimal registry-shaped wrapper over a family subset."""

    def __init__(self, fams):
        self._fams = fams

    def families(self):
        return dict(self._fams)

    def snapshot(self):
        return _metrics.snapshot_families(self._fams)


def fleet_text(registry=None, prefix="skdist"):
    """Prometheus exposition restricted to the serving-fleet families
    (``serve.*`` with their replica / ``name@version`` labels, plus the
    ``rounds.*`` dispatch totals the replicas' flushes fold into)."""
    reg = registry if registry is not None else _metrics.registry()
    return prometheus_text(_View(_serve_only(reg)), prefix=prefix)


def fleet_snapshot(registry=None, path=None):
    """JSON counterpart of :func:`fleet_text`."""
    reg = registry if registry is not None else _metrics.registry()
    snap = _View(_serve_only(reg)).snapshot()
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, indent=1, sort_keys=True)
    return snap
