"""
The ops endpoint: a stdlib-only HTTP server exposing the telemetry
plane to scrapers and humans.

Three routes, all read-only:

- ``/metrics`` — Prometheus text exposition (the Prometheus scrape
  contract). The hook decides WHOSE metrics: a bare process serves its
  own registry; the procfleet supervisor serves the harvested FLEET
  registry, so one scrape covers every replica process with
  ``replica``/``pid`` labels and ``skdist_stale`` marking replicas
  whose harvest went quiet.
- ``/healthz`` — liveness JSON. Status 200 while the hook reports
  healthy, 503 otherwise (the fleet hook reports unhealthy when no
  replica is routable — load balancers and k8s probes read the status
  code, humans read the body).
- ``/debug/flightrec`` — the flight recorder's current snapshot
  document (``obs.flightrec``): the last few hundred things this
  process (and, under the fleet hook, its workers' standing files)
  did.

Opt-in only: nothing binds unless the operator passes a port or sets
``SKDIST_OBS_PORT`` (``ProcessReplicaSet`` reads it; the variable is
STRIPPED from worker spawn environments so a fleet's children do not
fight the supervisor for the bind). Port 0 binds an ephemeral port —
read it back from :attr:`OpsServer.port` (tests, and multi-tenant
hosts that register the port elsewhere). The server binds
``127.0.0.1`` by default: the exposition carries operational detail,
and putting it on a routable interface is an explicit operator
decision (``host=``).

Built on ``http.server.ThreadingHTTPServer`` — no dependencies, a few
requests per scrape interval, entirely off the serving hot path.
"""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["OpsServer", "start_from_env", "resolve_port"]


def resolve_port(explicit=None):
    """The configured ops port: the explicit argument wins, else
    ``SKDIST_OBS_PORT``; None/empty = endpoint off. ``0`` is a LIVE
    value (ephemeral bind), so only None/"" disable."""
    if explicit is not None:
        return int(explicit)
    raw = os.environ.get("SKDIST_OBS_PORT", "").strip()
    if raw == "":
        return None
    return int(raw)


class _Handler(BaseHTTPRequestHandler):
    server_version = "skdist-obs/1"

    def _send(self, code, body, content_type):
        data = body.encode("utf-8") if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        hooks = self.server.hooks
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(
                    200, hooks["metrics"](),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/healthz":
                doc = hooks["healthz"]()
                code = 200 if doc.get("healthy", True) else 503
                self._send(code, json.dumps(doc, default=str),
                           "application/json")
            elif path == "/debug/flightrec":
                self._send(
                    200, json.dumps(hooks["flightrec"](), default=str),
                    "application/json",
                )
            else:
                self._send(404, json.dumps({
                    "error": "not found",
                    "routes": ["/metrics", "/healthz",
                               "/debug/flightrec"],
                }), "application/json")
        except Exception as exc:  # a broken hook must not kill the server
            self._send(500, json.dumps({"error": repr(exc)}),
                       "application/json")

    def log_message(self, fmt, *args):
        pass  # scrapes every few seconds must not spam stderr


def _default_metrics():
    from . import export

    return export.prometheus_text()


def _default_healthz():
    return {"healthy": True, "pid": os.getpid()}


def _default_flightrec():
    from . import flightrec

    return flightrec.recorder().snapshot_doc()


class OpsServer:
    """The ops endpoint (module docstring). Hooks are zero-arg
    callables returning the route's payload; each defaults to the
    process-local view."""

    def __init__(self, port=0, host="127.0.0.1", metrics=None,
                 healthz=None, flightrec=None):
        self.hooks = {
            "metrics": metrics or _default_metrics,
            "healthz": healthz or _default_healthz,
            "flightrec": flightrec or _default_flightrec,
        }
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.hooks = self.hooks
        self._thread = None

    @property
    def port(self):
        """The BOUND port (meaningful after construction, incl. the
        ephemeral-bind case of port=0)."""
        return self._httpd.server_address[1]

    @property
    def url(self):
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="skdist-obs-httpd",
            )
            self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def start_from_env(port=None, **hooks):
    """Start an :class:`OpsServer` when a port is configured
    (argument or ``SKDIST_OBS_PORT``); returns it, or None when the
    endpoint is off."""
    resolved = resolve_port(port)
    if resolved is None:
        return None
    return OpsServer(port=resolved, **hooks).start()
