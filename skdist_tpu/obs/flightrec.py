"""
Flight recorder: an always-on bounded ring of what this process was
doing RIGHT BEFORE it mattered.

The telemetry registry answers "how much, cumulatively"; the tracer
answers "what, in order" but is opt-in and sized for whole searches.
When a replica process dies, a fleet parks a crash-looping worker, a
router raises ``AllReplicasUnhealthy``, or a round loop exhausts its
retry budget, the question is narrower and the stakes higher: *what
were the last few hundred things this process did*, captured at a cost
low enough to leave on unconditionally. That is this module — the
aviation flight-recorder shape: a small ring of recent round stats,
fault-layer events, and fleet lifecycle notes, dumped to a timestamped
**incident file** when something dies.

Three write paths feed the ring with no configuration:

- ``publish_round_stats`` (``obs.metrics``) notes every completed
  dispatch's round summary;
- ``faults.record`` notes every fault-layer event (retries, parks,
  failovers, heartbeat misses ...);
- the procfleet supervisor notes replica lifecycle events.

Each note is one dict append under a lock — O(ring) memory, no I/O.
I/O happens only at DUMP time:

- :meth:`FlightRecorder.dump_incident` writes
  ``skdist-incident-<UTC>-pid<pid>-<reason>.json`` (ring + registry
  snapshot + recent trace-span tail) into ``SKDIST_FLIGHTREC_DIR``
  (default: ``<tmp>/skdist-flightrec``). Reasons are throttled (one
  dump per reason per ``min_interval_s``) so a router raising
  ``AllReplicasUnhealthy`` per queued request cannot dump-storm the
  disk.
- a **standing snapshot** (:meth:`start_autodump`): a daemon thread
  atomically rewrites one well-known file every interval. This is the
  SIGKILL answer — a process cannot dump *at* SIGKILL, so it dumps
  *continuously* and cheaply, and the supervisor harvests the last
  written snapshot of a dead child from its standing file (the
  procfleet contract). SIGTERM and normal exits additionally dump
  synchronously (:func:`install_signal_dump` chains the existing
  handler), and the write path is plain json-dump-to-temp + atomic
  ``os.replace`` — a reader never sees a torn file.

Incident files are self-describing JSON (schema in DESIGN.md
"Distributed observability"): ``{"schema": 1, "kind": "incident",
"reason", "t_unix", "pid", "label", "events": [...], "metrics":
{...}, "spans": [...]}``.
"""

import json
import os
import tempfile
import threading
import time
from collections import deque

__all__ = [
    "FlightRecorder",
    "recorder",
    "note",
    "dump_incident",
    "incident_dir",
    "install_signal_dump",
]

SCHEMA = 1

#: keys of a RoundStats dict worth keeping per ring entry (the full
#: dict rides last_round_stats already; the ring wants the story line)
_ROUND_KEYS = (
    "mode", "rounds", "tasks", "retries", "kernel_mode",
    "retired_rung", "retired_convergence",
)

#: how many of the trace ring's most recent events an incident carries
_SPAN_TAIL = 64


def incident_dir(explicit=None):
    """Where incident files land: the explicit argument, else
    ``SKDIST_FLIGHTREC_DIR``, else ``<tmp>/skdist-flightrec``."""
    if explicit:
        return str(explicit)
    env = os.environ.get("SKDIST_FLIGHTREC_DIR", "").strip()
    if env:
        return env
    return os.path.join(tempfile.gettempdir(), "skdist-flightrec")


class FlightRecorder:
    """Bounded event ring + incident/standing-snapshot dumps (module
    docstring). ``capacity`` bounds the ring; ``min_interval_s``
    throttles per-reason incident dumps."""

    def __init__(self, capacity=512, min_interval_s=5.0, label=None):
        self._ring = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.min_interval_s = float(min_interval_s)
        self.label = label
        self._last_dump = {}   # reason -> monotonic time of last dump
        self._seq = 0          # uniquifies same-second incident names
        self._auto_stop = None
        self._auto_thread = None
        self.standing_path = None

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------
    def note(self, kind, **data):
        """Append one event to the ring. Values must be cheap plain
        data (they are json-dumped at incident time with a str()
        fallback for anything exotic)."""
        ev = {"t": time.time(), "kind": str(kind)}
        ev.update(data)
        with self._lock:
            self._ring.append(ev)

    def note_round(self, stats):
        """One completed dispatch's summary (called by
        ``obs.metrics.publish_round_stats``)."""
        if not isinstance(stats, dict):
            return
        self.note("round", **{k: stats.get(k) for k in _ROUND_KEYS})

    def set_label(self, label):
        """Identity stamped into every dump (the procfleet sets
        ``replica <i>`` worker-side)."""
        self.label = str(label)

    def events(self):
        with self._lock:
            return [dict(e) for e in self._ring]

    def clear(self):
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------------
    # dumping
    # ------------------------------------------------------------------
    def snapshot_doc(self, reason=None, metrics=True):
        """The dump document: ring events, a registry snapshot, and
        the tail of the trace ring (span summaries — name/ts/dur/args,
        already plain dicts)."""
        from . import metrics as obs_metrics
        from . import trace as obs_trace

        doc = {
            "schema": SCHEMA,
            "kind": "incident" if reason else "snapshot",
            "t_unix": time.time(),
            "pid": os.getpid(),
            "label": self.label,
            "events": self.events(),
        }
        if reason:
            doc["reason"] = str(reason)
        if metrics:
            try:
                doc["metrics"] = obs_metrics.registry().snapshot()
            except Exception as exc:  # a dump must never raise
                doc["metrics"] = {"error": repr(exc)}
        try:
            # limit= renders ONLY the tail — this runs every second on
            # the autodump thread, where rendering a full 64k ring to
            # keep 64 events would be continuous allocation burn
            doc["spans"] = obs_trace.chrome_trace_events(
                clock="wall", limit=_SPAN_TAIL
            )
        except Exception as exc:
            doc["spans"] = [{"error": repr(exc)}]
        return doc

    @staticmethod
    def _write_atomic(path, doc):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, default=str)
        os.replace(tmp, path)

    def dump_incident(self, reason, dir=None, throttle=True, extra=None):
        """Write a timestamped incident file; returns its path, or
        None when throttled / the write failed (a recorder must never
        take down the thing it is recording). ``extra`` (a plain-data
        dict) lands under the doc's ``"extra"`` key — the procfleet
        supervisor attaches the dead replica's identity and its last
        harvested worker snapshot there."""
        reason = str(reason)
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if throttle and last is not None and (
                    now - last < self.min_interval_s):
                return None
            self._last_dump[reason] = now
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:64]
        path = os.path.join(
            incident_dir(dir),
            f"skdist-incident-{stamp}-pid{os.getpid()}"
            f"-{seq:03d}-{safe}.json",
        )
        doc = self.snapshot_doc(reason=reason)
        if extra is not None:
            doc["extra"] = extra
        try:
            self._write_atomic(path, doc)
        except Exception:
            return None
        return path

    def dump_now(self, path=None):
        """Synchronously (re)write the standing snapshot file."""
        path = path or self.standing_path
        if not path:
            return None
        try:
            self._write_atomic(path, self.snapshot_doc())
        except Exception:
            return None
        return path

    # ------------------------------------------------------------------
    # standing snapshot (the SIGKILL path)
    # ------------------------------------------------------------------
    def start_autodump(self, path, interval_s=1.0):
        """Start the standing-snapshot daemon thread (idempotent per
        recorder; a second call re-points the path)."""
        self.standing_path = str(path)
        if self._auto_thread is not None and self._auto_thread.is_alive():
            return
        stop = self._auto_stop = threading.Event()

        def loop():
            while not stop.wait(float(interval_s)):
                self.dump_now()
            self.dump_now()  # one final write on clean stop

        self._auto_thread = threading.Thread(
            target=loop, daemon=True, name="skdist-flightrec-autodump",
        )
        self._auto_thread.start()

    def stop_autodump(self, final_dump=True):
        stop = self._auto_stop
        if stop is not None:
            stop.set()
        t = self._auto_thread
        if t is not None:
            t.join(timeout=5.0)
        self._auto_thread = None
        if final_dump:
            self.dump_now()


_RECORDER = FlightRecorder()


def recorder():
    """The process-wide default recorder."""
    return _RECORDER


def note(kind, **data):
    _RECORDER.note(kind, **data)


def dump_incident(reason, dir=None, throttle=True):
    return _RECORDER.dump_incident(reason, dir=dir, throttle=throttle)


def install_signal_dump(signals=None, reason="signal"):
    """Dump an incident when one of ``signals`` (default: SIGTERM)
    arrives, CHAINING any existing handler — Python signal handlers
    run between bytecodes on the main thread, which is as
    "signal-safe" as a Python process gets; SIGKILL is unhandleable by
    design, which is what the standing autodump file is for."""
    import signal as _signal

    if signals is None:
        signals = (_signal.SIGTERM,)
    for sig in signals:
        prev = _signal.getsignal(sig)

        def handler(signum, frame, _prev=prev):
            _RECORDER.dump_incident(f"{reason}-{signum}")
            _RECORDER.dump_now()
            if callable(_prev):
                _prev(signum, frame)
            elif _prev == _signal.SIG_DFL:
                _signal.signal(signum, _signal.SIG_DFL)
                _signal.raise_signal(signum)

        _signal.signal(sig, handler)
