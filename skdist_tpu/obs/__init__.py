"""
skdist_tpu.obs — the unified telemetry plane.

Three parts, one store:

- :mod:`obs.metrics` — a thread-safe process-wide registry of labeled
  counters / gauges / histograms. Every subsystem's signals live here
  (compile cache hit/miss/lower-time, fault retry/quarantine, elastic
  shrinks/regrows, streaming byte accounting, serving request/latency
  stats); the legacy surfaces (``faults.snapshot()``,
  ``compile_cache.snapshot()``, ``backend.last_round_stats``,
  ``ServingEngine.stats()``) are views over it.
- :mod:`obs.trace` — structured nested spans (``round_dispatch``,
  ``compile``, ``block_feed``, ``flush``, ``rung_eval``,
  ``replica_failover``) in a bounded ring behind ``SKDIST_TRACE=1``,
  exported as Perfetto-loadable Chrome trace-event JSON, with optional
  ``jax.profiler.TraceAnnotation`` passthrough (``SKDIST_TRACE_JAX=1``)
  for chip-side device-time attribution.
- :mod:`obs.export` — Prometheus text exposition + JSON snapshot over
  the registry, including the serving fleet's per-replica and
  per-``name@version`` label dimensions.

Two distributed additions ride on top (PR 15):

- :mod:`obs.flightrec` — an always-on bounded flight recorder of
  recent rounds/faults/fleet events, dumped to timestamped incident
  files on replica death, crash-loop parks, ``AllReplicasUnhealthy``,
  and exhausted round retries; workers keep an atomically-rewritten
  standing snapshot so even a SIGKILLed process leaves its last
  seconds behind for the supervisor to harvest.
- :mod:`obs.httpd` — the opt-in stdlib ops endpoint
  (``SKDIST_OBS_PORT``): ``/metrics`` (fleet exposition),
  ``/healthz``, ``/debug/flightrec``.

See docs/DESIGN.md "Telemetry plane" and "Distributed observability".
"""

from . import export, flightrec, httpd, metrics, trace  # noqa: F401
from .metrics import (  # noqa: F401
    ROUND_STATS_REQUIRED,
    RoundStats,
    compile_scope,
    counter,
    gauge,
    histogram,
    new_round_stats,
    publish_round_stats,
    registry,
)
from .trace import (  # noqa: F401
    export_chrome_trace,
    instant,
    span,
    stitch_traces,
)

__all__ = [
    "metrics",
    "trace",
    "export",
    "flightrec",
    "httpd",
    "stitch_traces",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "compile_scope",
    "RoundStats",
    "ROUND_STATS_REQUIRED",
    "new_round_stats",
    "publish_round_stats",
    "span",
    "instant",
    "export_chrome_trace",
]
