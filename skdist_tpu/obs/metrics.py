"""
Process-wide metrics registry: the one place every subsystem's signals
land.

Before this module the framework's observability was a pile of ad-hoc
dicts — three independent ``last_round_stats`` construction sites in
``parallel/backend.py`` (classic, iterative, streamed, each with its
own key set), a serving-only ``ServingStats``, and standalone counter
dicts in ``faults.py`` and ``compile_cache.py``. The registry replaces
all of them as the *store*; the old surfaces (``faults.snapshot()``,
``compile_cache.snapshot()``, ``backend.last_round_stats``,
``serve.stats()``) remain as *views* over it, and the exporters
(``obs.export``: Prometheus text exposition, JSON snapshot) and the
span tracer (``obs.trace``) read from the same place — Prometheus'
"one registry, many collectors, label dimensions for the rest" model
(Prometheus client_golang; Borgmon before it).

Three metric kinds, all thread-safe and labelable:

- :class:`Counter` — monotonically increasing value (int or float —
  ``lower_time_s`` style wall accumulators are float counters).
- :class:`Gauge` — set-to-current value (queue depth, mesh extent).
- :class:`Histogram` — fixed bucket counts (Prometheus ``le``
  semantics: cumulative at exposition time) PLUS a bounded sample ring
  for exact rolling percentiles (the serving-latency p50/p99 view —
  bucket interpolation would be too coarse for sub-ms SLOs).

Labels are passed as keyword arguments to the record calls
(``counter("serve.requests", model="m@1").inc()``); a metric family is
one name, its children one value per label tuple. The empty label set
is a legitimate child ("the unlabeled total").

**Scoped compile attribution** (:func:`compile_scope`): a thread-local
tag the compile cache stamps onto its miss counters, so a serving
engine can count the compiles *its* dispatches caused — process-global
counters alone cannot distinguish a served shape escaping the bucket
set from a background fit compiling in the same process (the
``compiles_after_warmup`` false-trip ``serve/stats.py``'s old module
docstring admitted to).

**RoundStats** (:func:`new_round_stats`): the converged per-dispatch
schema of ``backend.last_round_stats``. Every dispatch path (classic,
compacted/iterative, streamed, streamed-predict) starts from the same
required key set — missing values are explicitly ``None``/0, never
absent — and :func:`publish_round_stats` folds the dispatch's totals
into the registry when it completes, so the per-call dict is the
recent-history view and the registry the cumulative one.
"""

import math
import threading
from collections import deque

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "compile_scope",
    "current_scope",
    "ROUND_STATS_REQUIRED",
    "RoundStats",
    "new_round_stats",
    "publish_round_stats",
    "merge_state",
]

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def _label_key(labels):
    """Canonical hashable form of a label dict: sorted (k, v) tuple,
    values coerced to str (Prometheus labels are strings)."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """One family: a name plus per-label-tuple children.

    Each family carries its OWN lock: the record paths (serving
    submit/complete, per-round billing) run on many threads at once,
    and a single registry-wide lock measurably serialised the serving
    hot path under concurrent clients. The registry's lock guards only
    family creation."""

    kind = "untyped"

    def __init__(self, name, registry, help=""):
        self.name = name
        self.help = help
        self._registry = registry
        self._lock = threading.Lock()

    def labels(self, **labels):
        raise NotImplementedError


class _BoundCounter:
    """Pre-resolved handle to ONE label child: the hot-path form —
    the label dict build + sort happened once at :meth:`Counter.child`
    time, so ``inc`` is a lock + a dict update. Serving's per-request
    record calls go through these."""

    __slots__ = ("_fam", "_key")

    def __init__(self, fam, key):
        self._fam = fam
        self._key = key

    def inc(self, n=1):
        fam = self._fam
        with fam._lock:
            fam._values[self._key] = fam._values.get(self._key, 0) + n


class Counter(_Metric):
    """Monotonic counter family (int or float increments)."""

    kind = "counter"

    def __init__(self, name, registry, help=""):
        super().__init__(name, registry, help)
        self._values = {}

    def inc(self, n=1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def child(self, **labels):
        """Bound handle for repeated increments of one label child."""
        return _BoundCounter(self, _label_key(labels))

    def get(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def total(self):
        """Sum over every label child."""
        with self._lock:
            return sum(self._values.values()) if self._values else 0

    def children(self):
        with self._lock:
            return dict(self._values)

    def reset(self):
        with self._lock:
            self._values.clear()


class Gauge(_Metric):
    """Set-to-current value family."""

    kind = "gauge"

    def __init__(self, name, registry, help=""):
        super().__init__(name, registry, help)
        self._values = {}

    def set(self, value, **labels):
        with self._lock:
            self._values[_label_key(labels)] = value

    def inc(self, n=1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def get(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def child(self, **labels):
        """Bound handle (``set``/``inc``) for one label child."""
        return _BoundGauge(self, _label_key(labels))

    def children(self):
        with self._lock:
            return dict(self._values)

    def reset(self):
        with self._lock:
            self._values.clear()


class _BoundGauge:
    """Pre-resolved handle to one gauge child (see _BoundCounter)."""

    __slots__ = ("_fam", "_key")

    def __init__(self, fam, key):
        self._fam = fam
        self._key = key

    def set(self, value):
        fam = self._fam
        with fam._lock:
            fam._values[self._key] = value

    def inc(self, n=1):
        fam = self._fam
        with fam._lock:
            fam._values[self._key] = fam._values.get(self._key, 0) + n


class _HistChild:
    __slots__ = ("counts", "sum", "count", "ring")

    def __init__(self, n_buckets, window):
        self.counts = [0] * (n_buckets + 1)  # + overflow (+Inf)
        self.sum = 0.0
        self.count = 0
        self.ring = deque(maxlen=window)


class Histogram(_Metric):
    """Fixed-boundary histogram + bounded percentile ring.

    ``observe`` bills the matching bucket (upper-bound semantics: the
    first boundary >= the value, like Prometheus ``le``) and appends
    the raw sample to a bounded ring; :meth:`percentile` computes the
    exact linear-interpolated percentile of the ring's window —
    matching ``numpy.percentile``'s default method on the same samples
    — so rolling latency views stay exact while the Prometheus
    exposition stays fixed-cost.
    """

    kind = "histogram"

    def __init__(self, name, registry, help="", buckets=_DEFAULT_BUCKETS,
                 window=4096):
        super().__init__(name, registry, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.window = int(window)
        self._children = {}

    def _child(self, key):
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = _HistChild(
                len(self.buckets), self.window
            )
        return child

    def observe(self, value, **labels):
        self._observe(_label_key(labels), float(value))

    def _observe(self, key, value):
        with self._lock:
            child = self._child(key)
            i = 0
            for b in self.buckets:
                if value <= b:
                    break
                i += 1
            child.counts[i] += 1
            child.sum += value
            child.count += 1
            child.ring.append(value)

    def child(self, **labels):
        """Bound handle (``observe``) for one label child."""
        return _BoundHistogram(self, _label_key(labels))

    def percentile(self, q, **labels):
        """Exact percentile of the rolling sample window (``q`` in
        [0, 100], numpy 'linear' interpolation), or None when empty."""
        with self._lock:
            child = self._children.get(_label_key(labels))
            samples = list(child.ring) if child is not None else []
        if not samples:
            return None
        samples.sort()
        if len(samples) == 1:
            return samples[0]
        rank = (float(q) / 100.0) * (len(samples) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(samples) - 1)
        frac = rank - lo
        return samples[lo] * (1.0 - frac) + samples[hi] * frac

    def get(self, **labels):
        """(count, sum) of one child."""
        with self._lock:
            child = self._children.get(_label_key(labels))
            if child is None:
                return 0, 0.0
            return child.count, child.sum

    def children(self):
        """{label key: {"counts", "sum", "count"}} — counts are
        PER-BUCKET (non-cumulative); the exporter cumulates for ``le``."""
        with self._lock:
            return {
                key: {
                    "counts": list(c.counts),
                    "sum": c.sum,
                    "count": c.count,
                }
                for key, c in self._children.items()
            }

    def merge_child(self, key, dump):
        """Fold one harvested child dump (a :meth:`children` value)
        into the child at label ``key`` — the fleet-merge path. Bucket
        layouts must match (the harvest recreates the family with the
        dumped boundaries); the percentile ring does NOT travel (raw
        samples stay process-local — the merged view keeps bucket
        counts/sum/count, which is what the exposition serves)."""
        counts = dump["counts"]
        if len(counts) != len(self.buckets) + 1:
            raise ValueError(
                f"histogram {self.name!r}: merge of {len(counts)} "
                f"buckets into {len(self.buckets) + 1}"
            )
        with self._lock:
            child = self._child(key)
            for i, c in enumerate(counts):
                child.counts[i] += int(c)
            child.sum += float(dump["sum"])
            child.count += int(dump["count"])

    def reset(self):
        with self._lock:
            self._children.clear()


class _BoundHistogram:
    """Pre-resolved handle to one histogram child (see _BoundCounter)."""

    __slots__ = ("_fam", "_key")

    def __init__(self, fam, key):
        self._fam = fam
        self._key = key

    def observe(self, value):
        self._fam._observe(self._key, float(value))


class MetricsRegistry:
    """Thread-safe name → metric-family store (module docstring).

    One process-wide default instance backs the whole framework
    (:func:`registry`); tests may build private instances. Family kinds
    are sticky: asking for an existing name with a different kind
    raises (a silent kind change would corrupt the exposition).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}

    def _family(self, cls, name, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, self, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is a {m.kind}, not a {cls.kind}"
                )
            elif not m.help and kwargs.get("help"):
                # help is sticky at the first NON-EMPTY registration: a
                # bare counter(name) peek (tests, ad-hoc reads) must not
                # strip the HELP line off the family's real
                # registration site for the rest of the process
                m.help = kwargs["help"]
            return m

    def counter(self, name, help=""):
        return self._family(Counter, name, help=help)

    def gauge(self, name, help=""):
        return self._family(Gauge, name, help=help)

    def histogram(self, name, help="", buckets=_DEFAULT_BUCKETS,
                  window=4096):
        return self._family(Histogram, name, help=help, buckets=buckets,
                            window=window)

    def families(self):
        with self._lock:
            return dict(self._metrics)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def reset(self, prefix=None):
        """Zero every family (or only those whose name starts with
        ``prefix``). Family objects are kept — handles stay live."""
        with self._lock:
            for name, m in self._metrics.items():
                if prefix is None or name.startswith(prefix):
                    m.reset()

    def snapshot(self):
        """Plain-dict dump: {name: {"kind", "values": {label key:
        value-or-hist dict}}} — the JSON exporter's input."""
        return snapshot_families(self.families())

    def dump_state(self):
        """The registry's full state in a merge-round-trippable form:
        ``{name: {"kind", "help", "children": {label-key tuple:
        value}}}`` (histograms add ``buckets``/``window``). Unlike
        :meth:`snapshot` the label keys stay STRUCTURED tuples — this
        is the telemetry-harvest wire form (it rides the procfleet's
        pickle frames), and :func:`merge_state` rebuilds exact label
        children from it, with fleet labels layered on top."""
        out = {}
        for name, m in self.families().items():
            ent = {"kind": m.kind, "help": m.help,
                   "children": m.children()}
            if m.kind == "histogram":
                ent["buckets"] = tuple(m.buckets)
                ent["window"] = m.window
            out[name] = ent
        return out


def snapshot_families(families):
    """Render a {name: family} mapping as nested plain dicts — the ONE
    definition of the snapshot's label-key format, shared by
    :meth:`MetricsRegistry.snapshot` and the exporters' family-subset
    views (``obs.export.fleet_snapshot``)."""
    out = {}
    for name, m in sorted(families.items()):
        out[name] = {"kind": m.kind, "values": {
            "|".join(f"{k}={v}" for k, v in key) if key else "": val
            for key, val in m.children().items()
        }}
    return out


def merge_state(state, into, labels=None):
    """Fold one process's :meth:`MetricsRegistry.dump_state` into the
    ``into`` registry, layering ``labels`` (e.g. ``{"replica": "1",
    "pid": "4242"}``) onto every child — the fleet-merge primitive the
    procfleet supervisor uses to build one exposition covering every
    worker. Fleet labels WIN over same-named labels the worker already
    carried (the supervisor's roster is the authority on which replica
    slot a process occupies). Counters/histograms accumulate, gauges
    last-write-win per label child."""
    labels = {str(k): str(v) for k, v in (labels or {}).items()}
    for name, ent in state.items():
        kind = ent.get("kind")
        for key, val in ent.get("children", {}).items():
            child_labels = dict(key)
            child_labels.update(labels)
            if kind == "counter":
                into.counter(name, help=ent.get("help", "")).inc(
                    val, **child_labels
                )
            elif kind == "gauge":
                into.gauge(name, help=ent.get("help", "")).set(
                    val, **child_labels
                )
            elif kind == "histogram":
                fam = into.histogram(
                    name, help=ent.get("help", ""),
                    buckets=ent.get("buckets", _DEFAULT_BUCKETS),
                    window=ent.get("window", 4096),
                )
                fam.merge_child(_label_key(child_labels), val)
    return into


_REGISTRY = MetricsRegistry()


def registry():
    """The process-wide default registry."""
    return _REGISTRY


def counter(name, help=""):
    return _REGISTRY.counter(name, help=help)


def gauge(name, help=""):
    return _REGISTRY.gauge(name, help=help)


def histogram(name, help="", buckets=_DEFAULT_BUCKETS, window=4096):
    return _REGISTRY.histogram(name, help=help, buckets=buckets,
                               window=window)


# ---------------------------------------------------------------------------
# scoped compile attribution
# ---------------------------------------------------------------------------

_SCOPE = threading.local()


class _ScopeCtx:
    __slots__ = ("tag", "prev")

    def __init__(self, tag):
        self.tag = tag
        self.prev = None

    def __enter__(self):
        self.prev = getattr(_SCOPE, "tag", None)
        _SCOPE.tag = self.tag
        return self

    def __exit__(self, *exc):
        _SCOPE.tag = self.prev
        return False


def compile_scope(tag):
    """Context manager tagging this thread's compile misses with
    ``tag`` (see module docstring): while active,
    ``compile_cache``'s miss counters additionally bill
    ``compile.scoped_misses{scope=tag}``, which is what a serving
    engine's ``compiles_after_warmup`` measures — per-engine deltas
    that concurrent non-serving work cannot move."""
    return _ScopeCtx(str(tag))


def current_scope():
    """This thread's active compile-attribution tag, or None."""
    return getattr(_SCOPE, "tag", None)


# ---------------------------------------------------------------------------
# RoundStats: the converged last_round_stats schema
# ---------------------------------------------------------------------------

#: keys EVERY dispatch path's ``last_round_stats`` carries, with their
#: explicit "nothing happened" values — the schema contract the
#: regression tests pin per path. ``mode`` is the path discriminator
#: ("pipelined"/"synchronous" classic, "compacted", "streamed",
#: "streamed_predict"); ``kernel_mode`` is stamped by the estimator
#: dispatch sites (``models/linear.annotate_round_kernel_mode``) and
#: stays None for non-estimator dispatches; the retirement split is 0
#: outside the compacted path; the byte accounting is 0 where no bytes
#: moved on that leg.
ROUND_STATS_REQUIRED = {
    "mode": None,            # path discriminator
    "rounds": 0,             # device rounds (or blocks grouped) run
    "tasks": 0,              # tasks the dispatch covered
    "kernel_mode": None,     # dense / packed_* / hist_tree / None
    "retries": 0,            # fault re-dispatches
    "dispatch_s": 0.0,       # host time slicing/placing/enqueueing
    "gather_wait_s": 0.0,    # host time blocked on device results
    "retired_rung": 0,       # lanes killed by an adaptive rung
    "retired_convergence": 0,  # lanes that ran to convergence/cap
    "shared_bytes": 0,       # placed shared-tree bytes (broadcast leg)
    "streamed_bytes": 0,     # H2D-fed block bytes (streaming leg)
    # streamed-rung accounting (both are documented upper-bound
    # estimates — see models/streaming's rung seams): solver passes the
    # killed lanes would still have paid, and whole-dataset bytes the
    # shortened race never streamed
    "passes_saved": 0,
    "streamed_bytes_saved": 0,
    # binned-block-cache accounting (streamed GBDT): bytes written
    # building the uint8 cache this fit (0 on a cache HIT — the 4x
    # read-amplification win is observable, not asserted) and bytes
    # read back from it across all boosting passes
    "binned_bytes_cached": 0,
    "binned_bytes_streamed": 0,
    "rung_survivors": None,  # per-rung survivor counts, "12,4,2"
}


class RoundStats(dict):
    """``last_round_stats`` with the required schema pre-filled: a
    plain dict to every existing consumer, plus the guarantee that the
    :data:`ROUND_STATS_REQUIRED` keys exist from construction.
    ``_published`` (attribute, not a key — it must never appear in the
    user-visible dict) records the values already folded into the
    registry, so re-publishing after further accumulation (a streamed
    fit's scoring pass mutating the same dict) folds only the delta."""

    __slots__ = ("_published",)


def new_round_stats(mode=None, **extra):
    """One dispatch's stats dict with the converged schema pre-filled
    (required keys present with explicit None/0 defaults, ``mode`` and
    any path-specific ``extra`` applied on top)."""
    stats = RoundStats(ROUND_STATS_REQUIRED)
    stats["mode"] = mode
    stats.update(extra)
    return stats


#: RoundStats keys folded into registry counters at publish (numeric
#: accumulators only — the discriminators/mode strings stay view-side)
_ROUND_PUBLISH_KEYS = (
    "rounds", "tasks", "retries", "dispatch_s", "gather_wait_s",
    "retired_rung", "retired_convergence", "streamed_bytes",
    "passes_saved", "streamed_bytes_saved",
    "binned_bytes_cached", "binned_bytes_streamed",
)


def publish_round_stats(stats):
    """Fold one completed dispatch's RoundStats into the registry:
    ``rounds.<key>`` counters labeled by dispatch path, plus a
    ``rounds.dispatches`` counter — the cumulative half of the
    "registry backs last_round_stats" contract. Tolerant of partial
    dicts (a caller that died mid-dispatch publishes what it has), and
    IDEMPOTENT-BY-DELTA on :class:`RoundStats`: a second publish after
    further accumulation (the streamed scoring pass extends the fit's
    dict; a compacted attempt publishes before downgrading to the
    classic fallback) folds only what moved since the first."""
    if not isinstance(stats, dict):
        return
    path = str(stats.get("mode"))
    prev = getattr(stats, "_published", None)
    if prev is None:
        counter("rounds.dispatches").inc(1, path=path)
        prev = {}
    for key in _ROUND_PUBLISH_KEYS:
        delta = (stats.get(key) or 0) - prev.get(key, 0)
        if delta > 0:  # counters stay monotonic even if a view resets
            counter(f"rounds.{key}").inc(delta, path=path)
    try:
        stats._published = {
            k: (stats.get(k) or 0) for k in _ROUND_PUBLISH_KEYS
        }
    except AttributeError:  # plain dict: single-shot publish only
        pass
    # kernel_mode is stamped AFTER the dispatch returns
    # (models/linear.annotate_round_kernel_mode), which bills the
    # rounds.kernel_mode counter itself — not double-counted here
    from . import flightrec

    flightrec.recorder().note_round(stats)
