"""
Pallas TPU kernel for the per-level tree histogram.

The histogram is the hot op of tree building (models/tree.py): per
level, ``hist[f, j, b, c] = Σ_i [Xb[i,f]==b][node[i]==j]·Ych[i,c]``.
The XLA formulations either scatter (serialises on TPU) or contract a
materialised one-hot ``Xoh (n, d·B)`` against ``NW (n, nl·C)``
(``hist_mode='matmul'``) — one big MXU matmul whose operands round-trip
HBM every level.

This kernel runs the SAME contraction with both one-hot factors built
on the fly in VMEM:

    grid (f, lane-block, sample-chunk):
      M  (S, B)   = [Xb_chunk[f] == bin]          (VPU compares)
      NW (S, LB)  = [node_chunk == lane//C] · Ych_chunk[:, lane%C]
      out[f, :, lane-block] += Mᵀ @ NW            (MXU, f32 accumulate)

so nothing of size (n, d·B) or (n, nl·C) ever exists in HBM; HBM
traffic is the raw inputs re-read ``nl·C/LB`` times. FLOPs are
identical to 'matmul' (d·B·n·nl·C — no padding waste: the node axis
rides the MXU lane dimension fused with channels).

``interpret=True`` (automatic off-TPU) runs the kernel through the
Pallas interpreter, so correctness is testable on the CPU mesh; the
compiled path is selected on real TPU backends.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def _ceil_to(x, m):
    return -(-x // m) * m


@functools.partial(
    jax.jit, static_argnames=("nl", "n_bins", "interpret", "S", "LB")
)
def level_histogram(Xb, node_key, Ych, *, nl, n_bins, interpret=False,
                    S=512, LB=128):
    """Per-level histogram via a Pallas kernel.

    Args:
      Xb: (n, d) int32 binned features.
      node_key: (n,) int32 — node id relative to the level start in
        [0, nl), or any value >= nl for samples not at this level.
      Ych: (n, C) f32 per-sample channels.
      nl: nodes at this level (static).
      n_bins: B (static).

    Returns (d, nl, B, C) f32.
    """
    from jax.experimental import pallas as pl

    n, d = Xb.shape
    C = Ych.shape[1]
    B = n_bins
    L = nl * C
    n_pad = _ceil_to(max(n, S), S)
    L_pad = _ceil_to(max(L, LB), LB)

    XbT = Xb.T  # (d, n)
    if n_pad != n:
        XbT = jnp.pad(XbT, ((0, 0), (0, n_pad - n)))
        # padded samples: key >= nl matches no lane's node id
        node_key = jnp.pad(node_key, (0, n_pad - n),
                           constant_values=np.int32(nl))
        Ych = jnp.pad(Ych, ((0, n_pad - n), (0, 0)))
    # Mosaic tiles the LAST TWO dims of each block; a (1, S) block over
    # the (d, n) array would put a size-1 block on the d axis (neither
    # 8-divisible nor full). Lift d to a leading grid-only dim so the
    # last two block dims are (1==full, S).
    XbT = XbT.reshape(d, 1, n_pad)
    node_key = node_key.reshape(1, n_pad)

    def kernel(xb_ref, nk_ref, ych_ref, out_ref):
        si = pl.program_id(2)
        li = pl.program_id(1)

        # M (S, B): bin one-hot of this feature's sample chunk
        bins = xb_ref[0, 0, :]  # (S,) int32
        M = (
            bins[:, None] == lax.broadcasted_iota(jnp.int32, (S, B), 1)
        ).astype(jnp.float32)

        # NW (S, LB): lane l encodes (node j = l//C, channel c = l%C)
        lane = li * LB + lax.broadcasted_iota(jnp.int32, (1, LB), 1)
        node_of_lane = lane // C  # (1, LB)
        chan_of_lane = lane % C
        nodes = nk_ref[0, :]  # (S,)
        ych = ych_ref[:]  # (S, C)
        # spread channels along lanes with a constant (C, LB) one-hot
        # matmul — constant along the sample axis, so built once per
        # step, not per sample (C is tiny; static gather lowers poorly
        # on some backends)
        chan_oh = (
            lax.broadcasted_iota(jnp.int32, (C, LB), 0) == chan_of_lane
        ).astype(jnp.float32)
        ych_lane = lax.dot_general(
            ych, chan_oh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (S, LB)
        NW = jnp.where(nodes[:, None] == node_of_lane, ych_lane, 0.0)

        part = lax.dot_general(
            M, NW, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (B, LB)

        @pl.when(si == 0)
        def _():
            out_ref[0, :, :] = part

        @pl.when(si != 0)
        def _():
            out_ref[0, :, :] = out_ref[0, :, :] + part

    grid = (d, L_pad // LB, n_pad // S)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, S), lambda f, l, s: (f, 0, s)),
            pl.BlockSpec((1, S), lambda f, l, s: (0, s)),
            pl.BlockSpec((S, C), lambda f, l, s: (s, 0)),
        ],
        out_specs=pl.BlockSpec((1, B, LB), lambda f, l, s: (f, 0, l)),
        out_shape=jax.ShapeDtypeStruct((d, B, L_pad), jnp.float32),
        interpret=interpret,
    )(XbT, node_key, Ych)

    hist_bnc = out[:, :, :L].reshape(d, B, nl, C)
    return hist_bnc.transpose(0, 2, 1, 3)  # (d, nl, B, C)


def pallas_supported():
    """Whether the compiled Pallas path targets the current backend.

    Off-TPU the kernel still runs (interpreter), just slowly — callers
    use this to pick interpret mode."""
    return jax.default_backend() == "tpu"
