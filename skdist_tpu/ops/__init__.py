"""
Low-level XLA/pallas ops supporting the estimator kernels.
"""

from .binning import apply_bins, quantile_bin_edges

__all__ = ["quantile_bin_edges", "apply_bins"]
