"""
Quantile binning for histogram-based tree growing.

The reference's trees (sklearn Cython builders, reached via
``/root/reference/skdist/distribute/ensemble.py:106-108``) do exact
split search over sorted feature values — a data-dependent-shape
algorithm XLA cannot express efficiently. The TPU-native design follows
the LightGBM/XGBoost-hist approach instead: features are discretised
once into ``n_bins`` quantile bins, after which split search is a
fixed-shape histogram reduction (see ``models/tree.py``).
"""

import numpy as np
import jax.numpy as jnp

MAX_BINS = 256


def quantile_bin_edges(X, n_bins=32):
    """Per-feature quantile bin edges, host-side, once per fit.

    Returns ``edges`` of shape (n_features, n_bins - 1); feature j maps
    value v to bin ``searchsorted(edges[j], v, side='right')`` ∈
    [0, n_bins). Degenerate (constant) features get +inf edges → all
    values land in bin 0.
    """
    X = np.asarray(X, dtype=np.float32)
    n, d = X.shape
    if not 2 <= n_bins <= MAX_BINS:
        raise ValueError(f"n_bins must be in [2, {MAX_BINS}], got {n_bins}")
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # (d, n_bins-1)
    # collapse duplicate edges (low-cardinality features) so empty bins
    # sit at the top; +inf keeps searchsorted stable
    for j in range(d):
        e = edges[j]
        dup = np.concatenate([[False], e[1:] <= e[:-1]])
        e[dup] = np.inf
        edges[j] = np.sort(e)
    return edges


def apply_bins_np(X, edges):
    """Numpy twin of :func:`apply_bins` (bit-identical bin ids —
    ``searchsorted(e, x, 'right')`` counts edges <= x exactly like the
    device kernel's ``sum(x >= e)``, and NaN is pinned to bin 0 to
    match ``NaN >= e`` being all-false where searchsorted would send
    it top): the host (C) forest engine's fit/predict path bins
    without touching jax at all."""
    X = np.asarray(X, np.float32)
    edges = np.asarray(edges, np.float32)
    out = np.empty(X.shape, np.int32)
    for j in range(X.shape[1]):
        col = X[:, j]
        idx = np.searchsorted(edges[j], col, side="right")
        nan = np.isnan(col)
        if nan.any():
            idx[nan] = 0
        out[:, j] = idx
    return out


def apply_bins(X, edges):
    """Discretise X (n, d) with edges (d, B-1) → int32 bins (n, d).

    jit-safe; used at both fit and predict time so split thresholds can
    be stored as bin ids.
    """
    from jax import lax

    X = jnp.asarray(X)
    edges = jnp.asarray(edges)

    # scan over features: bounds the (n, B-1) comparison temp to one
    # feature at a time instead of an (n, d, B-1) cube
    def one_feature(_, xe):
        x, e = xe
        return None, jnp.sum(x[:, None] >= e[None, :], axis=1)

    _, bins = lax.scan(one_feature, None, (X.T, edges))
    return bins.T.astype(jnp.int32)
