"""
Quantile binning for histogram-based tree growing.

The reference's trees (sklearn Cython builders, reached via
``/root/reference/skdist/distribute/ensemble.py:106-108``) do exact
split search over sorted feature values — a data-dependent-shape
algorithm XLA cannot express efficiently. The TPU-native design follows
the LightGBM/XGBoost-hist approach instead: features are discretised
once into ``n_bins`` quantile bins, after which split search is a
fixed-shape histogram reduction (see ``models/tree.py``).
"""

import numpy as np
import jax.numpy as jnp

MAX_BINS = 256


def quantile_bin_edges(X, n_bins=32):
    """Per-feature quantile bin edges, host-side, once per fit.

    Returns ``edges`` of shape (n_features, n_bins - 1); feature j maps
    value v to bin ``searchsorted(edges[j], v, side='right')`` ∈
    [0, n_bins). Degenerate (constant) features get +inf edges → all
    values land in bin 0.
    """
    X = np.asarray(X, dtype=np.float32)
    n, d = X.shape
    if not 2 <= n_bins <= MAX_BINS:
        raise ValueError(f"n_bins must be in [2, {MAX_BINS}], got {n_bins}")
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # (d, n_bins-1)
    # collapse duplicate edges (low-cardinality features) so empty bins
    # sit at the top; +inf keeps searchsorted stable
    for j in range(d):
        e = edges[j]
        dup = np.concatenate([[False], e[1:] <= e[:-1]])
        e[dup] = np.inf
        edges[j] = np.sort(e)
    return edges


class StreamingQuantileSketch:
    """One-pass per-feature weighted quantile sketch over blocks.

    The streamed GBDT fit cannot hold the dataset to run
    :func:`quantile_bin_edges` exactly, so each ``ChunkedDataset``
    block folds into this sketch and the merged result derives the
    dataset-level edges. Design:

    - state is a per-feature weighted value multiset ``(values,
      weights)``, values strictly sorted, weights summed per value;
    - :meth:`update` inserts a block column exactly, then — only when
      the multiset outgrows ``grid = compression * n_bins`` distinct
      values — compresses it back to ``grid`` evenly-spaced weighted
      quantile candidates. Constant and duplicate-heavy columns stay
      EXACT (few distinct values → never compressed);
    - :meth:`merge` is an exact multiset union (concat, sort, combine
      equal values) with NO compression, so merging is commutative and
      associative: block sketches merged in any order yield bitwise
      identical edges (test-pinned);
    - :meth:`edges` selects weighted quantiles at the
      ``linspace(0, 1, n_bins + 1)[1:-1]`` targets and applies the same
      duplicate-collapse-to-+inf convention as
      :func:`quantile_bin_edges`.

    Rank error is bounded by the compression grid: with ``compression``
    candidates per requested bin, a compressed column's quantile ranks
    are off by at most ~1/grid of the weight mass, so edges land within
    one requested-bin rank width of the exact quantiles (test-pinned at
    ``1 / n_bins``).
    """

    __slots__ = ("n_bins", "grid", "_vals", "_wts", "n_features")

    def __init__(self, n_features, n_bins=32, compression=8):
        if not 2 <= n_bins <= MAX_BINS:
            raise ValueError(
                f"n_bins must be in [2, {MAX_BINS}], got {n_bins}"
            )
        self.n_features = int(n_features)
        self.n_bins = int(n_bins)
        self.grid = int(compression) * int(n_bins)
        self._vals = [np.empty(0, np.float64) for _ in range(n_features)]
        self._wts = [np.empty(0, np.float64) for _ in range(n_features)]

    @staticmethod
    def _combine(v, w):
        """Sort and sum weights of equal values → strictly sorted (v, w)."""
        order = np.argsort(v, kind="mergesort")
        v, w = v[order], w[order]
        keep = np.concatenate([[True], v[1:] != v[:-1]])
        idx = np.cumsum(keep) - 1
        wsum = np.zeros(int(idx[-1]) + 1 if len(idx) else 0, np.float64)
        np.add.at(wsum, idx, w)
        return v[keep], wsum

    def _fold(self, j, v, w):
        v = np.concatenate([self._vals[j], v])
        w = np.concatenate([self._wts[j], w])
        self._vals[j], self._wts[j] = self._combine(v, w)

    def update(self, X_block, sample_weight=None):
        """Fold one block (rows, d) into the sketch. NaNs are dropped
        (they bin to 0 downstream regardless of edges)."""
        X = np.asarray(X_block, np.float64)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"block shape {X.shape} does not match "
                f"n_features={self.n_features}"
            )
        if sample_weight is None:
            w_rows = np.ones(X.shape[0], np.float64)
        else:
            w_rows = np.asarray(sample_weight, np.float64)
        for j in range(self.n_features):
            col = X[:, j]
            fin = ~np.isnan(col)
            v, w = self._combine(col[fin], w_rows[fin])
            self._fold(j, v, w)
            if len(self._vals[j]) > self.grid:
                self._compress(j)
        return self

    def _compress(self, j):
        """Shrink column j to ``grid`` weighted-quantile candidates."""
        v, w = self._vals[j], self._wts[j]
        cum = np.cumsum(w)
        total = cum[-1]
        targets = (np.arange(self.grid) + 0.5) / self.grid * total
        pick = np.searchsorted(cum, targets, side="left")
        pick = np.unique(np.clip(pick, 0, len(v) - 1))
        # re-attribute every source point's weight to its nearest
        # surviving candidate so total mass is conserved
        dest = np.searchsorted(v[pick], v, side="left")
        dest = np.clip(dest, 0, len(pick) - 1)
        wsum = np.zeros(len(pick), np.float64)
        np.add.at(wsum, dest, w)
        self._vals[j], self._wts[j] = v[pick], wsum

    def merge(self, other):
        """Exact multiset union with ``other`` (commutative/associative;
        no compression happens here, so merge order cannot change the
        derived edges)."""
        if other.n_features != self.n_features:
            raise ValueError("cannot merge sketches of different widths")
        for j in range(self.n_features):
            self._fold(j, other._vals[j], other._wts[j])
        return self

    def edges(self, n_bins=None):
        """Derive (d, n_bins - 1) f32 edges — the streamed twin of
        :func:`quantile_bin_edges`, same duplicate-collapse convention."""
        n_bins = self.n_bins if n_bins is None else int(n_bins)
        qs = np.linspace(0, 1, n_bins + 1)[1:-1]
        out = np.empty((self.n_features, n_bins - 1), np.float32)
        for j in range(self.n_features):
            v, w = self._vals[j], self._wts[j]
            if len(v) == 0:
                out[j] = np.inf
                continue
            cum = np.cumsum(w)
            total = cum[-1]
            # inverse-CDF (step) weighted quantiles: the value whose
            # cumulative-weight interval contains the target rank.
            # On duplicate-heavy columns this lands INSIDE runs of
            # equal values exactly like np.quantile's interpolation
            # does almost everywhere; on compressed continuous columns
            # the candidate grid bounds the step to ~1/grid of rank.
            pick = np.searchsorted(cum, qs * total, side="left")
            e = v[np.clip(pick, 0, len(v) - 1)].astype(np.float32)
            dup = np.concatenate([[False], e[1:] <= e[:-1]])
            e[dup] = np.inf
            out[j] = np.sort(e)
        return out


def apply_bins_np(X, edges):
    """Numpy twin of :func:`apply_bins` (bit-identical bin ids —
    ``searchsorted(e, x, 'right')`` counts edges <= x exactly like the
    device kernel's ``sum(x >= e)``, and NaN is pinned to bin 0 to
    match ``NaN >= e`` being all-false where searchsorted would send
    it top): the host (C) forest engine's fit/predict path bins
    without touching jax at all."""
    X = np.asarray(X, np.float32)
    edges = np.asarray(edges, np.float32)
    out = np.empty(X.shape, np.int32)
    for j in range(X.shape[1]):
        col = X[:, j]
        idx = np.searchsorted(edges[j], col, side="right")
        nan = np.isnan(col)
        if nan.any():
            idx[nan] = 0
        out[:, j] = idx
    return out


def apply_bins(X, edges):
    """Discretise X (n, d) with edges (d, B-1) → int32 bins (n, d).

    jit-safe; used at both fit and predict time so split thresholds can
    be stored as bin ids.
    """
    from jax import lax

    X = jnp.asarray(X)
    edges = jnp.asarray(edges)

    # scan over features: bounds the (n, B-1) comparison temp to one
    # feature at a time instead of an (n, d, B-1) cube
    def one_feature(_, xe):
        x, e = xe
        return None, jnp.sum(x[:, None] >= e[None, :], axis=1)

    _, bins = lax.scan(one_feature, None, (X.T, edges))
    return bins.T.astype(jnp.int32)
