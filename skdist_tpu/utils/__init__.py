from .validation import (
    check_estimator_backend,
    check_is_fitted,
    check_n_iter,
    safe_indexing,
    safe_split,
)

__all__ = [
    "check_estimator_backend",
    "check_is_fitted",
    "check_n_iter",
    "safe_indexing",
    "safe_split",
]
