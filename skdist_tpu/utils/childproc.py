"""
Shared wedge-isolation child runner for the benchmark drivers
(bench.py, benchmarks/run_all.py).

The axon TPU tunnel can wedge such that a device op blocks forever and
uninterruptibly — in-process timeouts cannot fire, and even SIGKILL may
leave the child in an unkillable D-state. The only reliable containment
is: run the device-touching phase in a CHILD process, enforce the
deadline from the parent, kill the whole process GROUP on expiry (so
grandchildren spawned by the phase die too), and bound the post-kill
wait so an unkillable child is abandoned rather than inherited as a
parent hang (the round-2 bug this module consolidates: one driver's
copy of this logic dropped the bounded wait and could hang forever in
``subprocess.run``'s internal ``wait()``).
"""

import os
import signal
import subprocess
import sys


def run_child_with_deadline(cmd, timeout, kill_wait=10, capture=True):
    """Run ``cmd`` with a hard deadline; never block past
    ``timeout + kill_wait``.

    Returns ``(status, returncode, stdout_text)``:
      status: 'ok' (rc 0), 'error' (nonzero rc), or 'timeout'
      stdout_text: captured stdout ('' when nothing landed), or None
        with ``capture=False`` (child inherits the parent's stdout).

    The child is started in its own session (process group) so the
    deadline kill reaches grandchildren as well.
    """
    popen_kw = {"start_new_session": True}
    if capture:
        popen_kw.update(stdout=subprocess.PIPE, text=True)
    proc = subprocess.Popen(cmd, **popen_kw)
    out = None
    try:
        out, _ = proc.communicate(timeout=timeout)
        status = "ok" if proc.returncode == 0 else "error"
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        try:
            out, _ = proc.communicate(timeout=kill_wait)
        except subprocess.TimeoutExpired:
            pass  # unkillable child: abandon, do not inherit its hang
        status = "timeout"
    return status, proc.returncode, (out if capture else None)


def _kill_group(proc):
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


def relay(out):
    """Forward a child's captured stdout to this process's stdout."""
    if out:
        sys.stdout.write(out)
        sys.stdout.flush()
