"""
Shared wedge-isolation child runner for the benchmark drivers
(bench.py, benchmarks/run_all.py).

The axon TPU tunnel can wedge such that a device op blocks forever and
uninterruptibly — in-process timeouts cannot fire, and even SIGKILL may
leave the child in an unkillable D-state. The only reliable containment
is: run the device-touching phase in a CHILD process, enforce the
deadline from the parent, kill the whole process GROUP on expiry (so
grandchildren spawned by the phase die too), and bound the post-kill
wait so an unkillable child is abandoned rather than inherited as a
parent hang (the round-2 bug this module consolidates: one driver's
copy of this logic dropped the bounded wait and could hang forever in
``subprocess.run``'s internal ``wait()``).
"""

import os
import signal
import subprocess
import sys


def run_child_with_deadline(cmd, timeout, kill_wait=10, capture=True):
    """Run ``cmd`` with a hard deadline; never block past
    ``timeout + kill_wait``.

    Returns ``(status, returncode, output_text)``:
      status: 'ok' (rc 0), 'error' (nonzero rc), or 'timeout'
      returncode: the child's exit code — or, explicitly, ``None``
        for the ABANDONED-UNKILLABLE case: the bounded post-kill wait
        expired before the child could be reaped, so no exit code
        exists yet (and whatever Popen might eventually learn is
        unknowable here; callers must treat None as "containment gave
        up", not as success).
      output_text: captured stdout AND stderr interleaved (stderr is
        merged into the stdout pipe so a crashing child's traceback
        survives containment instead of vanishing), '' when nothing
        landed, or None with ``capture=False`` (the child inherits
        the parent's streams).

    The child is started in its own session (process group) so the
    deadline kill reaches grandchildren as well.
    """
    popen_kw = {"start_new_session": True}
    if capture:
        popen_kw.update(stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT, text=True)
    proc = subprocess.Popen(cmd, **popen_kw)
    out = None
    try:
        out, _ = proc.communicate(timeout=timeout)
        rc = proc.returncode
        status = "ok" if rc == 0 else "error"
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        try:
            out, _ = proc.communicate(timeout=kill_wait)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            # unkillable child: abandon, do not inherit its hang — and
            # return an EXPLICIT None (the process was never reaped;
            # there is no exit code), not whatever stale value the
            # Popen object happens to hold
            rc = None
        status = "timeout"
    return status, rc, (out if capture else None)


def _kill_group(proc, sig=signal.SIGKILL):
    """Signal a child's whole process group (grandchildren included);
    falls back to the process alone when the group is gone. THE one
    containment recipe — the procfleet supervisor imports it rather
    than growing a drifting copy."""
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass


def relay(out):
    """Forward a child's captured stdout to this process's stdout."""
    if out:
        sys.stdout.write(out)
        sys.stdout.flush()
