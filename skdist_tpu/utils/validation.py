"""
Validation and indexing helpers.

Behavioural counterparts of the reference's vendored sklearn utilities
(``/root/reference/skdist/distribute/validation.py:14-264`` and
``utils.py:146-223``) — re-implemented against the protocols, not
copied: row indexing across numpy / scipy sparse / pandas / list,
fitted-state checks, backend banner printing, and n_iter capping.
"""

import numbers

import numpy as np


def full_length_sample_weight(fit_params, n):
    """The batched device paths' fit-params contract, shared by search
    and the OvR/OvO multiclass strategies (one definition, so the
    accepted-weights rules cannot drift between fan-out families): the
    compiled programs accept exactly ONE array-valued fit param — a
    full-length per-sample ``sample_weight``, which composes
    multiplicatively with fold/down-sampling/pair masks.

    Returns ``(sw_or_None, ok)``. ``ok`` False routes the fit to the
    generic host path (any other fit param, ragged or non-numeric
    weights, wrong length — where the host estimators' own validation
    owns the failure); ``(None, True)`` means "no weights, batched path
    fine". ``(n, 1)`` column weights flatten; anything else non-1-D
    (0-d scalars, (n, k) matrices) is not a per-sample weight vector.
    """
    if not fit_params:
        return None, True
    if set(fit_params) != {"sample_weight"}:
        return None, False
    sw = fit_params["sample_weight"]
    if sw is None:
        return None, True
    try:
        arr = np.asarray(sw, dtype=np.float64)
    except (ValueError, TypeError):
        return None, False
    if arr.ndim == 2 and arr.shape[1] == 1:
        arr = arr.ravel()
    if arr.ndim == 1 and arr.shape[0] == n:
        return arr, True
    return None, False


def check_estimator_backend(estimator, verbose=False):
    """Print which execution path a fit will use (reference
    ``_check_estimator``, validation.py:14-20, printed spark-vs-local)."""
    if verbose:
        backend = getattr(estimator, "backend", None)
        if backend is None:
            print("Will fit using local backend")
        else:
            print(f"Will fit using {type(backend).__name__ if not isinstance(backend, str) else backend}")


def check_is_fitted(estimator, attributes=None):
    """Raise if estimator has no fitted attributes (version-portable,
    reference validation.py:23-29)."""
    if attributes is not None:
        if isinstance(attributes, str):
            attributes = [attributes]
        fitted = all(hasattr(estimator, a) for a in attributes)
    else:
        fitted = any(
            v for v in vars(estimator) if v.endswith("_") and not v.startswith("__")
        )
    if not fitted:
        raise AttributeError(
            f"This {type(estimator).__name__} instance is not fitted yet. "
            "Call 'fit' before using this estimator."
        )


def check_error_score(error_score):
    """Validate the ``error_score`` policy AT ``fit()`` ENTRY: 'raise'
    or a real number (NaN included — sklearn's default). Validating
    lazily — only when the first fit actually fails — meant a typo'd
    ``error_score="nan"`` surfaced mid-search and discarded hours of
    completed work; this is the front-door check. Returns the value
    unchanged so call sites can inline it."""
    if isinstance(error_score, str):
        if error_score == "raise":
            return error_score
        raise ValueError(
            f"error_score must be 'raise' or a number, got "
            f"{error_score!r} (did you mean numpy.nan?)"
        )
    if isinstance(error_score, bool) or not isinstance(
            error_score, numbers.Number):
        raise ValueError(
            f"error_score must be 'raise' or a number, got "
            f"{error_score!r}"
        )
    return error_score


def check_n_iter(n_iter, param_distributions):
    """Cap n_iter at the size of a fully-enumerable grid (reference
    ``_check_n_iter``, validation.py:99-110)."""
    all_lists = all(
        not hasattr(v, "rvs") for v in param_distributions.values()
    )
    if all_lists:
        from sklearn.model_selection import ParameterGrid

        grid_size = len(ParameterGrid(param_distributions))
        return min(grid_size, n_iter)
    return n_iter


def safe_indexing(X, indices):
    """Row-subset X across container types (reference
    ``_safe_indexing``, validation.py:146-183)."""
    if X is None:
        return None
    if hasattr(X, "iloc"):
        return X.iloc[indices]
    if hasattr(X, "shape"):  # numpy / scipy sparse
        return X[indices]
    return [X[i] for i in indices]


def safe_split(estimator, X, y, indices, train_indices=None):
    """Train/test subset respecting precomputed kernels (reference
    ``_safe_split``, utils.py:171-209)."""
    if getattr(estimator, "kernel", None) == "precomputed":
        if not hasattr(X, "shape"):
            raise ValueError("Precomputed kernels require array X")
        if train_indices is None:
            X_subset = X[np.ix_(indices, indices)]
        else:
            X_subset = X[np.ix_(indices, train_indices)]
    else:
        X_subset = safe_indexing(X, indices)
    y_subset = safe_indexing(y, indices) if y is not None else None
    return X_subset, y_subset


def index_fit_params(X, fit_params, indices):
    """Slice array-valued fit params down to a fold's rows (reference
    ``_index_param_value``, search.py:208-210): a value that is
    array-like with one entry per sample of X (e.g. a full-length
    ``sample_weight``) is indexed by ``indices``; everything else
    passes through untouched."""
    if not fit_params:
        return {}
    n = num_samples(X)
    out = {}
    for key, value in fit_params.items():
        is_arraylike = (
            hasattr(value, "__len__") or hasattr(value, "shape")
        ) and not isinstance(value, (str, bytes, dict))
        if is_arraylike:
            try:
                matches = num_samples(value) == n
            except TypeError:
                matches = False
            if matches:
                value = safe_indexing(value, indices)
        out[key] = value
    return out


def num_samples(x):
    """Number of samples in array-like x (reference utils.py:146-168)."""
    if hasattr(x, "shape") and x.shape is not None:
        if len(x.shape) == 0:
            raise TypeError("Singleton array cannot be considered a valid collection.")
        if isinstance(x.shape[0], numbers.Integral):
            return x.shape[0]
    if hasattr(x, "__len__"):
        return len(x)
    raise TypeError(f"Expected sequence or array-like, got {type(x)}")
