"""Best-effort memory budgets for densification guardrails.

The reference never densified: Spark broadcast chunks
(``/root/reference/skdist/distribute/multiclass.py:35-62``) existed
precisely because X was big. The TPU path densifies for the MXU, so it
needs to know — BEFORE allocating — whether a densified sparse input
can exist at all; an uninformative OOM minutes later on a flaky tunnel
is the failure mode this prevents.
"""

import os
import sys

#: explicit operator override (bytes) for the densification budget
BUDGET_ENV = "SKDIST_DENSIFY_BUDGET_BYTES"


def available_host_bytes():
    """Currently-available physical host memory, or None off-POSIX."""
    try:
        return os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return None


def free_device_bytes_if_live():
    """Free HBM on the default device — ONLY if a jax backend is
    already initialised in this process. Never triggers device init
    itself: this is called from host-side data plumbing that may run
    before (or instead of) any device work, and initialising a wedged
    tunnel from a shape check would be absurd."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:  # nothing initialised yet
            return None
        dev = jax_mod.devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None
        free = stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)
        return free if free > 0 else None
    except Exception:
        return None


def densify_budget_bytes():
    """(budget, source_description) for a full densified allocation.

    The binding constraint is the tighter of available host RAM (the
    dense ndarray is built on host) and free HBM when a device backend
    is live (fit paths place the whole X). Returns (None, "") when
    nothing can be determined.
    """
    env = os.environ.get(BUDGET_ENV)
    if env:
        try:
            return int(float(env)), f"{BUDGET_ENV} override"
        except ValueError:
            pass
    candidates = []
    host = available_host_bytes()
    if host:
        candidates.append((host, "available host RAM"))
    dev = free_device_bytes_if_live()
    if dev:
        candidates.append((dev, "free device HBM"))
    if not candidates:
        return None, ""
    return min(candidates)
