"""Best-effort memory budgets for densification guardrails.

The reference never densified: Spark broadcast chunks
(``/root/reference/skdist/distribute/multiclass.py:35-62``) existed
precisely because X was big. The TPU path densifies for the MXU, so it
needs to know — BEFORE allocating — whether a densified sparse input
can exist at all; an uninformative OOM minutes later on a flaky tunnel
is the failure mode this prevents.
"""

import os

#: explicit operator override (bytes) for the densification budget
BUDGET_ENV = "SKDIST_DENSIFY_BUDGET_BYTES"


def available_host_bytes():
    """Currently-available physical host memory, or None off-POSIX."""
    try:
        return os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return None


def _proc_status_kb(field):
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(field + ":"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return None


def rss_bytes():
    """Current resident set size of this process, or None off-Linux —
    the streaming smoke's bounded-host-memory probe."""
    return _proc_status_kb("VmRSS")


def peak_rss_bytes():
    """Lifetime peak resident set size (VmHWM, falling back to
    ``ru_maxrss`` where the kernel omits it), or None where neither
    exists. Monotone: the streaming smoke asserts on the DELTA across
    the out-of-core fit, not the absolute value (the interpreter + jax
    runtime own the baseline)."""
    v = _proc_status_kb("VmHWM")
    if v is not None:
        return v
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except (ImportError, ValueError, OSError):
        return None


def densify_budget_bytes():
    """(budget, source_description) for a full densified allocation.

    The binding constraint is available host RAM: the dense ndarray is
    built on host, and host feasibility is a prerequisite for every
    downstream path. Free HBM is deliberately NOT part of the bound —
    a mesh with a 'data' axis row-shards X across devices, so one
    device's free HBM is the wrong ceiling (it would reject multi-chip
    fits that are fine); device-side fitting is the job of the
    backend's AOT memory-analysis round sizing and its OOM backstop.
    Returns (None, "") when nothing can be determined.
    """
    env = os.environ.get(BUDGET_ENV)
    if env:
        try:
            return int(float(env)), f"{BUDGET_ENV} override"
        except ValueError:
            pass
    host = available_host_bytes()
    if host:
        return host, "available host RAM"
    return None, ""
