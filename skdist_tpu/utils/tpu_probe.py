"""
Wedged-accelerator guard shared by the repo-root entry points
(bench.py, __graft_entry__.py).

The axon TPU tunnel can wedge such that device init blocks forever
*in-process* (uninterruptible). The probe therefore runs in a child
process with a hard timeout AND a bounded post-kill wait — if the child
lands in an unkillable state, the parent still returns instead of
inheriting the hang. Output is not captured (no pipes to drain).
"""

import os
import subprocess
import sys

_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready(); "
    "import pathlib, sys; pathlib.Path(sys.argv[1]).write_text("
    "jax.default_backend())"
)

# process-wide memo: the probe must run BEFORE the parent initialises
# any backend (a parent holding the device would starve the child), and
# a wedged device should cost its timeout once, not per entry point
_RESULT = None
# set the moment this module pins jax to CPU: once pinned, the
# in-process platform IS cpu-fallback for the rest of the process no
# matter what a later fresh probe observes, so _RESULT must never be
# overwritten with a recovered tunnel's name (the recovered name is
# still *returned* so orchestrators can dispatch fresh child processes)
_PINNED = False


def probe_platform_or_cpu(timeout=30, post_kill_wait=10, fresh=False):
    """Return the live default JAX platform name, or pin CPU in-process
    and return 'cpu-fallback' when the device never answers.

    Probes even when JAX_PLATFORMS is unset (jax auto-selects an
    accelerator there too); short-circuits an explicit cpu pin — both
    the env-var form and an in-process ``jax.config`` pin (the latter is
    what conftest.py does, and paying the subprocess timeout there would
    be pure waste). The first call's verdict is memoised for the process;
    ``fresh=True`` re-probes (for long-lived orchestrators asking "is
    the tunnel still alive NOW"). After a cpu-fallback pin a fresh probe
    that finds a recovered tunnel returns the live platform name — the
    caller can use it in fresh child processes — but the memo stays
    'cpu-fallback', because this process's jax config remains pinned.
    """
    global _RESULT, _PINNED
    if _RESULT is not None and not fresh:
        return _RESULT
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # ALSO pin in-process: this environment's sitecustomize
        # re-registers the axon plugin at interpreter start and can
        # override the env var's platform choice, so "cpu" in the env
        # does not by itself stop jax from initialising the (possibly
        # wedged) tunnel backend on first device use. The config pin is
        # authoritative; idempotent when cpu was already selected.
        import jax

        jax.config.update("jax_platforms", "cpu")
        _RESULT = "cpu"
        return _RESULT
    # In-process cpu pin short-circuit — but NOT when the pin was
    # applied by this module's own earlier fallback and the caller asks
    # for a fresh verdict: a fresh probe must be able to answer
    # 'cpu-fallback' (tunnel still dead) or report a recovered tunnel,
    # not misread the fallback pin as a deliberate user pin.
    if not (fresh and _RESULT == "cpu-fallback"):
        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            try:
                if (jax_mod.config.jax_platforms or "").strip() == "cpu":
                    _RESULT = "cpu"
                    return _RESULT
            except AttributeError:
                pass
    import tempfile

    fd, out_path = tempfile.mkstemp(suffix=".probe")
    os.close(fd)
    proc = None
    reason = "probe could not be launched"
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_CODE, out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        proc.wait(timeout=timeout)
        if proc.returncode == 0:
            with open(out_path) as f:
                name = f.read().strip()
            if name:
                if _PINNED:
                    # tunnel recovered but this process is already
                    # pinned to CPU: report liveness without letting
                    # later memoised calls misread the in-process state
                    return name
                _RESULT = name
                return _RESULT
            reason = "probe produced no platform name"
        else:
            reason = f"device init failed (probe exit {proc.returncode})"
    except subprocess.TimeoutExpired:
        reason = f"device init did not answer within {timeout}s"
    except Exception as exc:
        reason = f"probe error ({type(exc).__name__})"
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=post_kill_wait)
            except subprocess.TimeoutExpired:
                pass  # unkillable child; abandon it rather than hang
        try:
            os.unlink(out_path)
        except OSError:
            pass

    print(
        f"[skdist_tpu] {reason}; falling back to CPU for this process",
        file=sys.stderr,
    )
    if not _PINNED:
        import jax

        jax.config.update("jax_platforms", "cpu")
        _PINNED = True
    _RESULT = "cpu-fallback"
    return _RESULT
