"""
Estimator base protocol and backend-aware cloning.

Plays the role of the reference's distribution primitives
(``/root/reference/skdist/distribute/base.py:8-72``): an sc-aware
``_clone`` that skips copying the cluster handle and reattaches it, a
partition-count policy, and broadcast unwrapping. Here the "cluster
handle" is a :class:`skdist_tpu.parallel.backend.TaskBackend` (or a
``jax.sharding.Mesh``), which must never be deep-copied or pickled into
a fitted artifact.
"""

import copy
import inspect
from collections import defaultdict

import numpy as np

# Constructor attribute names that hold live runtime handles. They are
# excluded from deep-copy during clone and stripped after fit so fitted
# estimators stay picklable (reference strips `sc`: search.py:568-570).
_RUNTIME_ATTRS = ("backend", "sc", "mesh")


class BaseEstimator:
    """sklearn-protocol base: introspective ``get_params``/``set_params``.

    Implemented from the protocol (not vendored from sklearn) so our
    estimators compose with sklearn pipelines, ``sklearn.base.clone``,
    and each other. Parameters are the constructor arguments, like
    sklearn; fitted state is attributes with trailing underscores.
    """

    @classmethod
    def _get_param_names(cls):
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        names = [
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        ]
        return sorted(names)

    def get_params(self, deep=True):
        out = {}
        for key in self._get_param_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params") and not isinstance(value, type):
                for sub_key, sub_value in value.get_params(deep=True).items():
                    out[f"{key}__{sub_key}"] = sub_value
            out[key] = value
        return out

    def set_params(self, **params):
        if not params:
            return self
        valid = set(self._get_param_names())
        nested = defaultdict(dict)
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(
                    f"Invalid parameter {key!r} for estimator {self!r}. "
                    f"Valid parameters are: {sorted(valid)}."
                )
            if delim:
                nested[key][sub_key] = value
            else:
                setattr(self, key, value)
        for key, sub_params in nested.items():
            getattr(self, key).set_params(**sub_params)
        return self

    def __repr__(self):
        params = ", ".join(
            f"{k}={getattr(self, k, None)!r}"
            for k in self._get_param_names()
            if not isinstance(getattr(self, k, None), np.ndarray)
        )
        return f"{type(self).__name__}({params})"

    # -- sklearn duck-typing helpers ------------------------------------
    def _more_tags(self):
        return {}

    def __sklearn_tags__(self):  # pragma: no cover - sklearn >=1.6 interop
        from sklearn.utils import Tags, InputTags, TargetTags

        est_type = getattr(self, "_estimator_type", None)
        tags = Tags(
            estimator_type=est_type,
            target_tags=TargetTags(required=est_type in ("classifier", "regressor")),
            input_tags=InputTags(sparse=True, allow_nan=False),
        )
        if est_type == "classifier":
            from sklearn.utils import ClassifierTags

            tags.classifier_tags = ClassifierTags()
        elif est_type == "regressor":
            from sklearn.utils import RegressorTags

            tags.regressor_tags = RegressorTags()
        return tags


class ClassifierMixin:
    _estimator_type = "classifier"

    def score(self, X, y, sample_weight=None):
        from sklearn.metrics import accuracy_score

        return accuracy_score(y, self.predict(X), sample_weight=sample_weight)


class RegressorMixin:
    _estimator_type = "regressor"

    def score(self, X, y, sample_weight=None):
        from sklearn.metrics import r2_score

        return r2_score(y, self.predict(X), sample_weight=sample_weight)


class TransformerMixin:
    def fit_transform(self, X, y=None, **fit_params):
        return self.fit(X, y, **fit_params).transform(X)


def clone(estimator, safe=True):
    """Backend-aware clone (reference ``_clone``, base.py:8-50).

    Returns an unfitted copy with the same parameters. Runtime handles
    (``backend``/``sc``/``mesh`` constructor params) are carried over by
    *reference*, never deep-copied — a backend may hold live device
    buffers, thread pools, or a ``Mesh``.
    """
    if estimator is None:
        return None
    if isinstance(estimator, (list, tuple)):
        return type(estimator)(clone(e, safe=safe) for e in estimator)
    if not hasattr(estimator, "get_params"):
        if not safe:
            return copy.deepcopy(estimator)
        raise TypeError(
            f"Cannot clone object {estimator!r}: it does not implement get_params."
        )
    params = estimator.get_params(deep=False)
    handles = {}
    for name in _RUNTIME_ATTRS:
        if name in params:
            handles[name] = params.pop(name)
    new_params = {}
    for name, value in params.items():
        if hasattr(value, "get_params") and not isinstance(value, type):
            new_params[name] = clone(value, safe=safe)
        else:
            new_params[name] = copy.deepcopy(value)
    new_params.update(handles)
    new_object = type(estimator)(**new_params)
    # post-clone identity check, as the reference does (base.py:38-46)
    check_params = new_object.get_params(deep=False)
    for name in params:
        if check_params[name] is not new_params[name] and not isinstance(
            new_params[name], (int, float, str, bool, type(None))
        ):
            raise RuntimeError(
                f"Cannot clone {estimator!r}: constructor does not set "
                f"parameter {name!r} verbatim."
            )
    return new_object


def strip_runtime(estimator):
    """Remove live runtime handles post-fit so the artifact pickles clean.

    The analogue of the reference's ``del self.sc`` at the end of every
    fit (search.py:568-570, multiclass.py:283-285, ensemble.py:335).
    Recurses into nested estimators.
    """
    if estimator is None or not hasattr(estimator, "get_params"):
        return estimator
    for name in _RUNTIME_ATTRS:
        if hasattr(estimator, name) and getattr(estimator, name) is not None:
            try:
                setattr(estimator, name, None)
            except AttributeError:
                pass
    for value in vars(estimator).values():
        if hasattr(value, "get_params"):
            strip_runtime(value)
        elif isinstance(value, (list, tuple)):
            for item in value:
                if hasattr(item, "get_params"):
                    strip_runtime(item)
    return estimator
