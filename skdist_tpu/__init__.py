"""
skdist_tpu: TPU-native distributed scikit-learn meta-estimators.

A ground-up re-design of the capabilities of Ibotta/sk-dist
(reference: /root/reference/skdist/__init__.py:4-13) for TPU hardware.

Where sk-dist fans embarrassingly-parallel model fits out over a PySpark
cluster (``sc.parallelize(...).map(fit).collect()``), skdist_tpu batches
them into single XLA programs: many fits of the same shape become one
``vmap``-ed, ``jit``-compiled kernel whose task axis is sharded over a
``jax.sharding.Mesh`` of TPU devices. Training data lives HBM-resident
and replicated; per-task hyperparameters and fold masks ride the mapped
axis; scores gather over ICI collectives instead of a Spark ``collect()``.

Every distributed estimator also runs without any accelerator: passing
``backend=None`` (the analogue of sk-dist's ``sc=None``) selects a local
thread/serial execution path with identical semantics, so the full test
suite runs on CPU.

Public surface (mirrors sk-dist's component inventory):

- ``skdist_tpu.distribute.search``: ``DistGridSearchCV``,
  ``DistRandomizedSearchCV``, ``DistMultiModelSearch``
- ``skdist_tpu.distribute.multiclass``: ``DistOneVsRestClassifier``,
  ``DistOneVsOneClassifier``
- ``skdist_tpu.distribute.ensemble``: ``DistRandomForestClassifier/Regressor``,
  ``DistExtraTreesClassifier/Regressor``, ``DistRandomTreesEmbedding``
- ``skdist_tpu.distribute.eliminate``: ``DistFeatureEliminator``
- ``skdist_tpu.distribute.encoder``: ``Encoderizer``, ``EncoderizerExtractor``
- ``skdist_tpu.distribute.predict``: batched large-scale inference
- ``skdist_tpu.serve``: online inference runtime — ``ServingEngine``
  with dynamic micro-batching, shape buckets, and an AOT-prewarmed
  ``ModelRegistry`` (concurrent small requests, the traffic-serving
  counterpart of ``batch_predict``)
- ``skdist_tpu.models``: JAX/XLA estimator kernels (logistic regression,
  linear SVC, SGD, ridge, decision trees and forests) replacing the
  sklearn Cython / liblinear compute the reference leaned on
- ``skdist_tpu.preprocessing`` / ``skdist_tpu.postprocessing``: pipeline
  transformers and ``SimpleVoter``
- ``skdist_tpu.obs``: the unified telemetry plane — process-wide
  metrics registry (the store behind ``last_round_stats``,
  ``serve.stats()`` and the fault/compile counters), structured span
  tracing with Perfetto export (``SKDIST_TRACE=1``), and
  Prometheus/JSON exporters
"""

__version__ = "0.1.0"


_EXPORTS = {
        "DistGridSearchCV": "skdist_tpu.distribute.search",
        "DistRandomizedSearchCV": "skdist_tpu.distribute.search",
        "DistMultiModelSearch": "skdist_tpu.distribute.search",
        "DistOneVsRestClassifier": "skdist_tpu.distribute.multiclass",
        "DistOneVsOneClassifier": "skdist_tpu.distribute.multiclass",
        "DistRandomForestClassifier": "skdist_tpu.distribute.ensemble",
        "DistRandomForestRegressor": "skdist_tpu.distribute.ensemble",
        "DistExtraTreesClassifier": "skdist_tpu.distribute.ensemble",
        "DistExtraTreesRegressor": "skdist_tpu.distribute.ensemble",
        "DistRandomTreesEmbedding": "skdist_tpu.distribute.ensemble",
        "DistFeatureEliminator": "skdist_tpu.distribute.eliminate",
        "DistHistGradientBoostingClassifier": "skdist_tpu.models.gbdt",
        "DistHistGradientBoostingRegressor": "skdist_tpu.models.gbdt",
        "ChunkedDataset": "skdist_tpu.data",
        "Encoderizer": "skdist_tpu.distribute.encoder",
        "EncoderizerExtractor": "skdist_tpu.distribute.encoder",
        "get_prediction_udf": "skdist_tpu.distribute.predict",
        "batch_predict": "skdist_tpu.distribute.predict",
        "SimpleVoter": "skdist_tpu.postprocessing",
        "LocalBackend": "skdist_tpu.parallel",
        "TPUBackend": "skdist_tpu.parallel",
        "ServingEngine": "skdist_tpu.serve",
        "ModelRegistry": "skdist_tpu.serve",
        "CatalogStore": "skdist_tpu.catalog",
        "RefreshJob": "skdist_tpu.catalog",
}


def __getattr__(name):
    """Lazy top-level conveniences (``skdist_tpu.DistGridSearchCV`` …)
    without importing jax at package-import time; resolved attributes
    are cached in the module namespace."""
    from importlib import import_module

    if name in _EXPORTS:
        obj = getattr(import_module(_EXPORTS[name]), name)
        globals()[name] = obj
        return obj
    raise AttributeError(f"module 'skdist_tpu' has no attribute {name!r}")
