"""
skdist_tpu: TPU-native distributed scikit-learn meta-estimators.

A ground-up re-design of the capabilities of Ibotta/sk-dist
(reference: /root/reference/skdist/__init__.py:4-13) for TPU hardware.

Where sk-dist fans embarrassingly-parallel model fits out over a PySpark
cluster (``sc.parallelize(...).map(fit).collect()``), skdist_tpu batches
them into single XLA programs: many fits of the same shape become one
``vmap``-ed, ``jit``-compiled kernel whose task axis is sharded over a
``jax.sharding.Mesh`` of TPU devices. Training data lives HBM-resident
and replicated; per-task hyperparameters and fold masks ride the mapped
axis; scores gather over ICI collectives instead of a Spark ``collect()``.

Every distributed estimator also runs without any accelerator: passing
``backend=None`` (the analogue of sk-dist's ``sc=None``) selects a local
thread/serial execution path with identical semantics, so the full test
suite runs on CPU.

Public surface (mirrors sk-dist's component inventory):

- ``skdist_tpu.distribute.search``: ``DistGridSearchCV``,
  ``DistRandomizedSearchCV``, ``DistMultiModelSearch``
- ``skdist_tpu.distribute.multiclass``: ``DistOneVsRestClassifier``,
  ``DistOneVsOneClassifier``
- ``skdist_tpu.distribute.ensemble``: ``DistRandomForestClassifier/Regressor``,
  ``DistExtraTreesClassifier/Regressor``, ``DistRandomTreesEmbedding``
- ``skdist_tpu.distribute.eliminate``: ``DistFeatureEliminator``
- ``skdist_tpu.distribute.encoder``: ``Encoderizer``, ``EncoderizerExtractor``
- ``skdist_tpu.distribute.predict``: batched large-scale inference
- ``skdist_tpu.models``: JAX/XLA estimator kernels (logistic regression,
  linear SVC, SGD, ridge, decision trees and forests) replacing the
  sklearn Cython / liblinear compute the reference leaned on
- ``skdist_tpu.preprocessing`` / ``skdist_tpu.postprocessing``: pipeline
  transformers and ``SimpleVoter``
"""

__version__ = "0.1.0"
