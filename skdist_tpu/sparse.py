"""
Packed-CSR shared-data plane: sparse X as a first-class fit/predict
representation.

The flagship workloads are hashed-text grids (the reference's 20news
OvR/OvO examples, BASELINE config 3): a HashingVectorizer matrix at
2**18 columns and ~1% density. Densifying that input — the original
fit-path policy — inflates it ~100x in host RAM, replicates the dense
copy into every device's HBM, and pays O(n·d) solver FLOPs on zeros.
This module is the shared alternative, promoted from the predict-side
CSR path (``distribute/predict.py``'s former private ``_pack_csr_rows``)
and consumed by the fit plane, the batched search/multiclass paths, and
batch prediction alike:

- :class:`PackedX` — the device representation: ``idx (n, m) int32`` /
  ``val (n, m) float32``, one padded row per sample, ``m`` = max nnz
  per row. Padding entries are ``(0, 0.0)``: every kernel below treats
  them as "add 0.0 to column 0", so the representation is EXACT. It is
  a registered JAX pytree, which is what makes the rest of the stack
  indifferent to it: backend placement (``_resolve_placement``), the
  broadcast-reuse cache (keyed per host leaf), row-sharded
  ``shared_specs``, ``shape_sig``/AOT keys, and donation all operate on
  its two leaves like any other shared array.
- the two contractions every linear solver needs:
  :func:`packed_matvec` (``X @ W``: gather + row-dot, O(nnz·k)) and
  :func:`packed_rmatvec` (``X.T @ r``: scatter-add over the packed
  columns, O(nnz·k)) — plus :func:`packed_to_dense` (the
  dense-matmul-on-packed variant: one device scatter rebuilds the dense
  block, then the MXU runs ordinary matmuls; H2D still ships only the
  packed pair) and :func:`packed_weighted_gram` (``XᵀSX`` via the m²
  scatter, for the closed-form ridge family).
- routing (:func:`pack_for_fit`): pack exactly when packing wins.
  The padded pair costs ``n·m·8`` bytes vs ``n·d·4`` dense, so the
  decision is byte-driven (``d >= 2·m·savings``; savings default 4x,
  see :data:`PACK_MIN_SAVINGS`) with an nnz-OUTLIER guard: a few rows
  with huge nnz inflate ``m`` — and the padding bill — for every row,
  so heavily skewed inputs fall back to the densify path rather than
  pay max-row padding. ``SKDIST_SPARSE_FIT=0`` disables packing
  entirely; ``=1``/``force`` packs any 2-D sparse input.
- matvec-mode selection (:func:`resolve_matvec_mode`): ``gather`` vs
  ``dense`` (dense-matmul-on-packed) vs ``pallas`` (the on-chip
  kernels of ``ops/pallas_sparse.py``: both contractions recast as
  one-hot matmuls whose dense sub-block is rebuilt in VMEM — no
  (n, d) tensor in HBM, no serialised gather/scatter) is a measured,
  persisted decision per platform — the same calibration idiom as the
  tree kernels' ``hist_mode`` (``models/hist_calib.py``): environment
  override, then a committed ``sparse_calib.json`` table written by
  on-platform sweeps (an extended ``build_tools/tpu_tree_sweep.py``
  records both tables), then the heuristic default (``gather`` —
  nnz-proportional everywhere; ``dense``/``pallas`` only win where an
  MXU exists, which is the sweep's call to make). Off-TPU a selected
  ``pallas`` runs through the Pallas interpreter — correct (the CPU
  mesh tests it bitwise) but slow, so no CPU calibration ever picks
  it.

The 1-tuple-shape special case of scipy's 1-D sparse arrays
(``csr_array`` of a vector) is handled ONCE here, in
:func:`sparse_to_dense_f32` — 1-D sparse input is a column vector,
exactly as the dense path treats a 1-D ndarray.
"""

import json
import os
import threading

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "PackedX",
    "is_sparse_2d",
    "max_nnz_per_row",
    "pack_csr_rows",
    "pack_decision",
    "would_pack",
    "pack_for_fit",
    "sparse_to_dense_f32",
    "packed_matvec",
    "packed_rmatvec",
    "packed_to_dense",
    "packed_weighted_gram",
    "matvec_any",
    "LinearOperator",
    "resolve_matvec_mode",
    "get_matvec_calibration",
    "record_matvec_calibration",
]

#: kill switch / force switch for the packed fit plane: "0" restores
#: the densify-everything policy, "1"/"force" packs any 2-D sparse
#: input regardless of the byte heuristic
SPARSE_FIT_ENV = "SKDIST_SPARSE_FIT"

#: explicit matvec-mode override: "gather" | "dense"
SPARSE_MATVEC_ENV = "SKDIST_SPARSE_MATVEC"

#: how many times smaller (bytes) the packed pair must be than the
#: dense f32 matrix before the fit path packs — below this the MXU's
#: dense matmul beats gather/scatter indexing
PACK_MIN_SAVINGS = 4.0
PACK_SAVINGS_ENV = "SKDIST_SPARSE_PACK_SAVINGS"

#: nnz-outlier guard: when the max row nnz exceeds this multiple of the
#: 95th percentile AND padding inflates the packed pair past the same
#: multiple of the true nnz, the matrix is skew-pathological — max-row
#: padding would bill every row for a handful of heavy ones
OUTLIER_FACTOR = 4.0

_VALID_MATVEC_MODES = ("gather", "dense", "pallas")

#: explicit row-chunk override for the weighted-gram contraction; the
#: automatic chunking derives from the meminfo budget (see
#: :func:`packed_weighted_gram`)
GRAM_CHUNK_ENV = "SKDIST_GRAM_CHUNK_ROWS"


# ---------------------------------------------------------------------------
# the packed representation
# ---------------------------------------------------------------------------

class PackedX:
    """Padded-row packed CSR: ``idx (n, m) int32``, ``val (n, m) f32``.

    A registered JAX pytree whose leaves are the two arrays and whose
    static treedef carries ``n_cols`` — so the logical width ``d`` is a
    compile-time constant wherever the pytree flows (kernels read it
    without tracing it), and two packings of different widths can never
    share a compiled program.
    """

    __slots__ = ("idx", "val", "n_cols")

    def __init__(self, idx, val, n_cols):
        self.idx = idx
        self.val = val
        self.n_cols = int(n_cols)

    @property
    def shape(self):
        """Logical (n, d) — what shape-generic callers read."""
        return (self.idx.shape[0], self.n_cols)

    def __len__(self):
        return int(self.idx.shape[0])

    @property
    def m(self):
        """Packed width: max nnz per row (plus padding)."""
        return int(self.idx.shape[1])

    @property
    def nbytes(self):
        return int(self.idx.nbytes) + int(self.val.nbytes)

    @property
    def dense_nbytes(self):
        """What the densified f32 matrix would cost."""
        return int(self.shape[0]) * int(self.n_cols) * 4

    def __repr__(self):  # pragma: no cover - debugging nicety
        n, d = self.shape
        return (f"PackedX(n={n}, d={d}, m={self.m}, "
                f"{self.nbytes >> 10} KiB packed vs "
                f"{self.dense_nbytes >> 10} KiB dense)")


jax.tree_util.register_pytree_node(
    PackedX,
    lambda x: ((x.idx, x.val), x.n_cols),
    lambda n_cols, leaves: PackedX(leaves[0], leaves[1], n_cols),
)


# ---------------------------------------------------------------------------
# host-side packing + routing
# ---------------------------------------------------------------------------

def is_sparse_2d(X):
    """scipy-sparse duck test, 2-D only (1-D sparse arrays are column
    vectors for the dense path — see :func:`sparse_to_dense_f32`)."""
    return (hasattr(X, "toarray") and hasattr(X, "tocsr")
            and len(X.shape) == 2)


def max_nnz_per_row(X):
    """Packed width m from ``indptr`` alone — shared by the budget
    guardrails and the pack so they can never disagree about the
    padding rule (a changed rule here changes both)."""
    nnz = np.diff(np.asarray(X.indptr))
    return max(1, int(nnz.max()) if nnz.size else 1)


def pack_csr_rows(X):
    """CSR → ``(idx (n, m) int32, val (n, m) f32)``, m = max nnz per
    row, padded with ``(0, 0.0)``. Every consumer kernel treats padding
    as "add 0.0 to column 0", so the packed form is exact."""
    indptr = np.asarray(X.indptr)
    nnz = np.diff(indptr)
    m = max_nnz_per_row(X)
    n = X.shape[0]
    pos = indptr[:-1, None] + np.arange(m)[None, :]
    mask = np.arange(m)[None, :] < nnz[:, None]
    idx = np.zeros((n, m), np.int32)
    val = np.zeros((n, m), np.float32)
    idx[mask] = np.asarray(X.indices)[pos[mask]]
    val[mask] = np.asarray(X.data)[pos[mask]]
    return idx, val


def _pack_savings():
    env = os.environ.get(PACK_SAVINGS_ENV, "").strip()
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    return PACK_MIN_SAVINGS


def pack_decision(X):
    """Routing decision for a 2-D CSR input: ``(pack, reason, m)``.

    ``pack`` is True when the packed pair beats the dense matrix by at
    least :data:`PACK_MIN_SAVINGS` in device bytes (``n·m·8`` vs
    ``n·d·4``) AND the nnz distribution is not outlier-skewed. All
    statistics come from ``indptr`` alone — no data is touched before
    the decision, so declining costs nothing.
    """
    env = os.environ.get(SPARSE_FIT_ENV, "").strip().lower()
    if env in ("0", "false", "no", "off"):
        return False, "disabled via " + SPARSE_FIT_ENV, None
    nnz = np.diff(np.asarray(X.indptr))
    m = max(1, int(nnz.max()) if nnz.size else 1)
    if env in ("1", "true", "force", "on"):
        return True, "forced via " + SPARSE_FIT_ENV, m
    n, d = X.shape
    if n == 0:
        return False, "empty input", m
    if m * 8 * _pack_savings() > d * 4:
        return False, (
            f"dense-competitive density (m={m} of d={d}: the packed "
            f"pair saves < {_pack_savings()}x device bytes)"
        ), m
    # nnz-outlier guard: m is the MAX row nnz, and every row pays
    # padding to it — a handful of heavy rows must not bill the rest
    p95 = float(np.percentile(nnz, 95)) if nnz.size else 0.0
    total = max(1, int(nnz.sum()))
    if (m > OUTLIER_FACTOR * max(p95, 1.0)
            and n * m > OUTLIER_FACTOR * total):
        return False, (
            f"nnz outlier (max row nnz {m} vs p95 {p95:.0f}: padding "
            f"would inflate {total} nnz to {n * m} slots)"
        ), m
    return True, "packed", m


def would_pack(X):
    """Whether :func:`pack_for_fit` would return a ``PackedX`` for
    ``X`` — the same routing decision (sparsity, byte heuristic,
    outlier guard, pack-budget check), decided from shape and
    ``indptr`` alone without building anything. Callers that only need
    the routing outcome (e.g. to order a host-path bail before paying
    a dense conversion) use this instead of packing and discarding."""
    if not is_sparse_2d(X):
        return False
    X = X.tocsr()
    pack, _reason, m = pack_decision(X)
    if not pack:
        return False
    from .utils.meminfo import densify_budget_bytes

    budget, _ = densify_budget_bytes()
    n, _d = X.shape
    if budget is not None and n * max(1, m) * 8 * 3 > budget:
        # the pack itself is budget-checked (the pair plus its build
        # intermediates must fit host RAM — if they don't, dense
        # certainly doesn't either, and the densify guardrail owns the
        # error message)
        return False
    return True


def pack_for_fit(X):
    """``PackedX`` when the fit plane should consume ``X`` packed, else
    None (callers densify). Non-sparse and 1-D sparse inputs always
    return None; the routing decision lives in :func:`would_pack`."""
    if not would_pack(X):
        return None
    X = X.tocsr()
    idx, val = pack_csr_rows(X)
    return PackedX(idx, val, X.shape[1])


def sparse_to_dense_f32(X):
    """Densify a scipy-sparse input to float32, with the budget
    guardrail. The sparse leg of ``models.linear.as_dense_f32``; the
    1-tuple-shape special case of scipy's 1-D sparse arrays is handled
    here (column vector), once, for every caller."""
    if len(X.shape) == 1:
        # csr_array of a vector: 1-tuple shape; a 1-D input is a
        # single feature column, exactly like a 1-D ndarray
        out = np.asarray(X.toarray(), dtype=np.float32)
        return np.ascontiguousarray(out.reshape(-1, 1))
    _check_densify_budget(X.shape[0], X.shape[1])
    if hasattr(X, "tocsr") and X.shape[0] * X.shape[1] >= (1 << 22):
        from .native import csr_to_dense_f32

        return csr_to_dense_f32(X)
    out = np.asarray(X.toarray())
    if out.ndim == 1:
        out = out.reshape(-1, 1)
    return np.ascontiguousarray(out, dtype=np.float32)


def _check_densify_budget(n_rows, n_cols):
    """Refuse a densification that cannot fit, with remedies."""
    from .utils.meminfo import BUDGET_ENV, densify_budget_bytes

    est = int(n_rows) * int(n_cols) * 4
    budget, source = densify_budget_bytes()
    if budget is None or est <= budget:
        return

    def _fmt(b):
        return (f"{b / 1e9:.2f} GB" if b >= 1e8 else f"{b / 1e6:.1f} MB")

    raise ValueError(
        f"densifying this ({n_rows}, {n_cols}) sparse input needs "
        f"~{_fmt(est)} as float32, but only ~{_fmt(budget)} "
        f"is available ({source}). Hashed-text widths this large do not "
        "belong on the dense path. Options: (1) FIT without densifying "
        "— the packed-CSR sparse fit plane (skdist_tpu.sparse) handles "
        "2-D sparse input at packable density automatically for the "
        "linear families; reaching this error means the input was "
        "routed dense (density/nnz-outlier heuristics, or "
        f"{SPARSE_FIT_ENV}=0) — force packing with {SPARSE_FIT_ENV}=1; "
        "(2) for inference use distribute.batch_predict, which streams "
        "sparse rows in groups (device models take the packed CSR "
        "path) and never materialises the full dense matrix; (3) "
        "re-hash to a bounded width — the Encoderizer configs cap "
        "HashingVectorizer at 2**12..2**14 (distribute/_defaults.py) — "
        "or reduce features first (TruncatedSVDTransformer); (4) raise "
        f"the limit explicitly via {BUDGET_ENV} if you know better."
    )


# ---------------------------------------------------------------------------
# device kernels: the two contractions + the dense-on-packed rebuild
# ---------------------------------------------------------------------------

def packed_matvec(idx, val, W):
    """``X @ W`` on the packed pair: gather + row-dot, O(nnz·k) FLOPs.

    ``W`` is ``(d[+1],)`` or ``(d[+1], k)``; padding entries gather row
    0 of W with weight 0.0 and contribute nothing. vmap-safe (the task
    axis may batch W)."""
    g = W[idx]  # (n, m) or (n, m, k)
    if g.ndim == 2:
        return jnp.sum(val * g, axis=1)
    return jnp.einsum("nm,nmk->nk", val, g)


def packed_rmatvec(idx, val, r, n_cols):
    """``X.T @ r`` on the packed pair: scatter-add over the packed
    columns, O(nnz·k). ``r`` is ``(n,)`` or ``(n, k)``; returns
    ``(n_cols,)`` / ``(n_cols, k)``. Padding scatters 0.0 into row 0."""
    if r.ndim == 1:
        out = jnp.zeros((n_cols,), r.dtype)
        return out.at[idx].add(val * r[:, None])
    out = jnp.zeros((n_cols, r.shape[-1]), r.dtype)
    return out.at[idx].add(val[:, :, None] * r[:, None, :])


def packed_to_dense(idx, val, n_cols):
    """Scatter-rebuild the dense ``(n, n_cols)`` block on device — the
    dense-matmul-on-packed variant's one-time cost: H2D still ships
    only the packed pair, and the MXU then runs ordinary matmuls.
    Duplicate (row, col) entries accumulate, matching CSR semantics."""
    n = idx.shape[0]
    rows = jnp.arange(n)[:, None]
    return jnp.zeros((n, n_cols), val.dtype).at[rows, idx].add(val)


#: task-batch factor billed by the automatic gram chunking: the gram
#: usually runs inside a vmapped round (batched CV ridge fits), where
#: EVERY lane of the traced program materialises its own (chunk, m, m)
#: tensor simultaneously — and at trace time the kernel cannot see how
#: many lanes the round stacked. Billing a conservative per-trace lane
#: count keeps the guard effective in the batched case; over-chunking
#: only lengthens the fori_loop, under-chunking OOMs.
GRAM_BATCH_ASSUMPTION = 16


def _gram_row_chunk(n, m):
    """Rows per chunk for :func:`packed_weighted_gram`, or None for the
    single-shot scatter. Env override first (absolute — the operator
    knows the real round shape); otherwise the (n, m, m) contribution
    tensor × :data:`GRAM_BATCH_ASSUMPTION` vmap lanes is billed against
    the meminfo budget (the same plumbing the densify guardrail uses)
    at 1/8 — the tensor, its XLA temps, and the scatter's operands
    coexist — and chunking engages only when that bill overshoots the
    share. The budget is host-RAM-derived (the plumbing the ISSUE
    reuses); device-HBM-aware sizing stays the backend round sizer's
    job."""
    env = os.environ.get(GRAM_CHUNK_ENV, "").strip()
    if env:
        try:
            v = int(float(env))
            if v > 0:
                return min(v, n)
        except ValueError:
            pass
    from .utils.meminfo import densify_budget_bytes

    budget, _ = densify_budget_bytes()
    if budget is None:
        return None
    lane_bytes = int(m) * int(m) * 4 * GRAM_BATCH_ASSUMPTION
    share = budget // 8
    if int(n) * lane_bytes <= share:
        return None
    return max(1, int(share // max(lane_bytes, 1)))


def packed_weighted_gram(idx, val, sw, n_cols, row_chunk=None):
    """``Xᵀ S X`` via the m² scatter: contribution
    ``sw[n]·val[n,a]·val[n,b]`` lands at ``(idx[n,a], idx[n,b])`` —
    O(nnz·m) scatter ops instead of the dense gram's O(n·d²) FLOPs.

    The (n, m, m) contribution tensor is materialised, which suits the
    moderate-m regimes the ridge family usually runs at — but above a
    budget threshold (:func:`_gram_row_chunk`, reusing the meminfo
    budget plumbing; ``SKDIST_GRAM_CHUNK_ROWS`` overrides) the
    contraction switches to a row-chunked accumulation: a fori_loop
    over fixed-size row chunks, each materialising only
    (chunk, m, m). Chunk padding uses zero weights/values, so the
    chunked result equals the single-shot scatter (exactly on integer
    data; to f32 addition-order noise otherwise). The chunk decision
    is made at TRACE time from static shapes, so it is vmap-safe (a
    batched ``sw`` rides through the dynamic slices untouched)."""
    n, m = idx.shape
    if row_chunk is None:
        row_chunk = _gram_row_chunk(n, m)
    if row_chunk is None or int(row_chunk) >= n:
        vw = val * sw[:, None]
        contrib = vw[:, :, None] * val[:, None, :]
        out = jnp.zeros((n_cols, n_cols), val.dtype)
        return out.at[idx[:, :, None], idx[:, None, :]].add(contrib)
    chunk = max(1, int(row_chunk))
    n_pad = -(-n // chunk) * chunk
    if n_pad != n:
        # zero-weight padded rows contribute 0.0 at (0, 0) — exact
        idx = jnp.concatenate(
            [idx, jnp.zeros((n_pad - n, m), idx.dtype)], axis=0
        )
        val = jnp.concatenate(
            [val, jnp.zeros((n_pad - n, m), val.dtype)], axis=0
        )
        sw = jnp.concatenate(
            [sw, jnp.zeros((n_pad - n,), sw.dtype)], axis=0
        )

    def body(c, acc):
        i0 = c * chunk
        ii = jax.lax.dynamic_slice_in_dim(idx, i0, chunk, axis=0)
        vv = jax.lax.dynamic_slice_in_dim(val, i0, chunk, axis=0)
        ss = jax.lax.dynamic_slice_in_dim(sw, i0, chunk, axis=0)
        vw = vv * ss[:, None]
        contrib = vw[:, :, None] * vv[:, None, :]
        return acc.at[ii[:, :, None], ii[:, None, :]].add(contrib)

    out0 = jnp.zeros((n_cols, n_cols), val.dtype)
    return jax.lax.fori_loop(0, n_pad // chunk, body, out0)


def matvec_any(X, W):
    """``X @ W`` for either representation — the decision/proba
    kernels' one entry point, so a model fit packed scores packed
    shared data AND dense predict blocks through one closure."""
    if isinstance(X, PackedX):
        return packed_matvec(X.idx, X.val, W)
    return X @ W


# ---------------------------------------------------------------------------
# the matvec interface the fit problems consume
# ---------------------------------------------------------------------------

class LinearOperator:
    """The augmented design matrix ``X̃ = [X | 1]`` behind one matvec
    interface, for dense ndarrays and :class:`PackedX` alike — what
    lets the LogReg/LinearSVC/SGD/Ridge fit problems (and through them
    the iteration-sliced solvers and the convergence-compacted
    scheduler) run unchanged on sparse data.

    Dense inputs reproduce the pre-sparse-plane expressions VERBATIM
    (``Xa @ W``, ``Xa[i] @ W``, ``Xa.T @ (Xa * sw)``), so the dense
    paths' pinned numerics cannot move. Packed inputs append the
    intercept as one extra packed column (``idx=d, val=1``) and route
    through the gather/scatter kernels above — or, in ``mode='dense'``,
    through one :func:`packed_to_dense` rebuild followed by the exact
    dense expressions (the MXU variant) — or, in ``mode='pallas'``,
    through the on-chip Pallas kernels (``ops/pallas_sparse``): the
    forward matvec carries a custom VJP whose backward IS the Pallas
    rmatvec, so the solvers autodiff through it exactly as through the
    gather form.

    ``matmul_dtype='bfloat16'`` applies the LogReg bf16 contract: bf16
    operands, f32 accumulation, solver state f32. On the gather path
    the products round to bf16 before the f32 row-sum — same
    opt-in-screening precision class as the dense bf16 pass. The bf16
    contract is DEFINED on the gather expressions: ``mode='pallas'``
    under bf16 keeps the forward/backward on the gather path rather
    than inventing a third precision class.
    """

    __slots__ = ("d", "p", "n", "Xa", "pidx", "pval", "bf16", "_Xmm",
                 "dtype", "pallas", "_pmv")

    def __init__(self, X, fit_intercept, matmul_dtype=None, mode="gather"):
        if mode not in _VALID_MATVEC_MODES:
            raise ValueError(
                f"mode must be one of {_VALID_MATVEC_MODES}; got {mode!r}"
            )
        self.bf16 = matmul_dtype == "bfloat16"
        self._Xmm = None
        self.pallas = False
        self._pmv = None
        self.dtype = X.val.dtype if isinstance(X, PackedX) else X.dtype
        if isinstance(X, PackedX):
            d = X.n_cols
            idx, val = X.idx, X.val
            n = idx.shape[0]
            if fit_intercept:
                idx = jnp.concatenate(
                    [idx, jnp.full((n, 1), d, idx.dtype)], axis=1
                )
                val = jnp.concatenate(
                    [val, jnp.ones((n, 1), val.dtype)], axis=1
                )
            self.d, self.p, self.n = d, d + int(bool(fit_intercept)), n
            if mode == "dense":
                # rebuild once per trace; XLA keeps the block live for
                # every matvec of the solve (HBM returns, H2D doesn't)
                self.Xa = packed_to_dense(idx, val, self.p)
                self.pidx = self.pval = None
            else:
                self.Xa = None
                self.pidx, self.pval = idx, val
                if mode == "pallas" and not self.bf16:
                    from .ops.pallas_sparse import matvec_with_vjp

                    self.pallas = True
                    self._pmv = matvec_with_vjp(idx, val, self.p)
        else:
            if fit_intercept:
                ones = jnp.ones((X.shape[0], 1), X.dtype)
                Xa = jnp.concatenate([X, ones], axis=1)
            else:
                Xa = X
            self.Xa = Xa
            self.pidx = self.pval = None
            self.d = X.shape[1]
            self.p = Xa.shape[1]
            self.n = X.shape[0]

    # -- X̃ @ W ---------------------------------------------------------
    def matvec(self, W):
        if self.Xa is not None:
            if self.bf16:
                if self._Xmm is None:
                    self._Xmm = self.Xa.astype(jnp.bfloat16)
                # precision pinned so the library-wide 'highest'
                # tracing default doesn't promote the bf16 pass
                return jax.lax.dot_general(
                    self._Xmm, W.astype(jnp.bfloat16),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.DEFAULT,
                )
            return self.Xa @ W
        if self.bf16:
            g = W.astype(jnp.bfloat16)[self.pidx]
            v = self.pval.astype(jnp.bfloat16)
            if g.ndim == 2:
                return jnp.sum((v * g).astype(jnp.float32), axis=1)
            return jnp.sum(
                (v[:, :, None] * g).astype(jnp.float32), axis=1
            )
        if self.pallas:
            return self._pmv(W)
        return packed_matvec(self.pidx, self.pval, W)

    # -- X̃ᵀ @ r --------------------------------------------------------
    def rmatvec(self, r):
        if self.Xa is not None:
            return self.Xa.T @ r
        if self.pallas:
            from .ops.pallas_sparse import packed_rmatvec as pl_rmatvec

            return pl_rmatvec(self.pidx, self.pval, r, self.p)
        return packed_rmatvec(self.pidx, self.pval, r, self.p)

    # -- row-batch forms (the SGD mini-batch contractions) --------------
    def row_matvec(self, i, W):
        if self.Xa is not None:
            return self.Xa[i] @ W
        if self.pallas:
            # the SGD family computes its gradients explicitly (no
            # autodiff through the row forms), so the raw kernels serve
            from .ops.pallas_sparse import packed_matvec as pl_matvec

            return pl_matvec(self.pidx[i], self.pval[i], W)
        return packed_matvec(self.pidx[i], self.pval[i], W)

    def row_rmatvec(self, i, g):
        if self.Xa is not None:
            return self.Xa[i].T @ g
        if self.pallas:
            from .ops.pallas_sparse import packed_rmatvec as pl_rmatvec

            return pl_rmatvec(self.pidx[i], self.pval[i], g, self.p)
        return packed_rmatvec(self.pidx[i], self.pval[i], g, self.p)

    # -- closed-form ridge pieces ---------------------------------------
    def weighted_gram_rhs(self, sw, T):
        """``(X̃ᵀSX̃, (SX̃)ᵀT)`` — the two solves of the ridge normal
        equations. Dense keeps the historical op order exactly; the
        packed gram runs the m² scatter in the gather/dense modes and
        the on-chip Pallas rebuild-and-matmul form in ``mode='pallas'``
        (``ops/pallas_sparse.packed_weighted_gram`` — the last packed
        contraction with a Pallas kernel, interpret mode off-TPU),
        while the rhs rides the mode's rmatvec."""
        if self.Xa is not None:
            Xw = self.Xa * sw[:, None]
            return self.Xa.T @ Xw, Xw.T @ T
        if self.pallas:
            from .ops.pallas_sparse import (
                packed_weighted_gram as pl_gram,
            )

            G = pl_gram(self.pidx, self.pval, sw, self.p)
        else:
            G = packed_weighted_gram(self.pidx, self.pval, sw, self.p)
        b = self.rmatvec(sw[:, None] * T)
        return G, b


# ---------------------------------------------------------------------------
# matvec-mode calibration (the hist_mode idiom)
# ---------------------------------------------------------------------------

_DEFAULT_CALIB_PATH = os.path.join(
    os.path.dirname(__file__), "models", "sparse_calib.json"
)
#: env override so sweeps can stage candidate entries in scratch files
CALIB_PATH_ENV = "SKDIST_SPARSE_CALIB_PATH"
_CALIB_LOCK = threading.Lock()
_CALIB_CACHE = {}  # path -> (mtime, table)


def _calib_path():
    return os.environ.get(CALIB_PATH_ENV) or _DEFAULT_CALIB_PATH


def _load_calib():
    path = _calib_path()
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return {}
    with _CALIB_LOCK:
        ent = _CALIB_CACHE.get(path)
        if ent is None or ent[0] != mtime:
            try:
                with open(path) as f:
                    ent = (mtime, json.load(f))
                _CALIB_CACHE[path] = ent
            except (OSError, ValueError):
                return ent[1] if ent else {}
        return ent[1] or {}


def get_matvec_calibration(platform):
    """Measured matvec-mode entry for ``platform`` or None."""
    ent = _load_calib().get(platform)
    if not isinstance(ent, dict) or ent.get("mode") not in _VALID_MATVEC_MODES:
        return None
    return ent


def record_matvec_calibration(platform, mode, measured=None, source=None):
    """Persist a sweep result (merging with other platforms' entries),
    mirroring ``models/hist_calib.record_calibration``."""
    if mode not in _VALID_MATVEC_MODES:
        raise ValueError(
            f"mode must be one of {_VALID_MATVEC_MODES}; got {mode!r}"
        )
    path = _calib_path()
    with _CALIB_LOCK:
        table = {}
        try:
            with open(path) as f:
                table = json.load(f)
        except (OSError, ValueError):
            pass
        ent = {"mode": mode}
        if measured is not None:
            ent["measured"] = measured
        if source is not None:
            ent["source"] = source
        table[platform] = ent
        with open(path, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        _CALIB_CACHE.pop(path, None)
    return table[platform]


def resolve_matvec_mode(platform=None):
    """The packed matvec mode for this process: environment override →
    calibration table → heuristic default (``gather`` — the
    nnz-proportional kernels; ``dense`` is the rebuilt-MXU variant and
    ``pallas`` the on-chip VMEM-rebuild kernels, either of which a
    sweep may certify per platform — CPU sweeps never pick ``pallas``,
    whose off-TPU form is the interpreter)."""
    env = os.environ.get(SPARSE_MATVEC_ENV, "").strip().lower()
    if env in _VALID_MATVEC_MODES:
        return env
    if platform is None:
        platform = jax.default_backend()
    calib = get_matvec_calibration(platform)
    if calib is not None:
        return calib["mode"]
    return "gather"
