/*
 * fasthash: native text feature hashing for skdist_tpu.
 *
 * The reference leaned on sklearn's Cython/C featurisation
 * (HashingVectorizer via murmurhash, reached from the Encoderizer
 * default pipelines). This module supplies the equivalent native
 * kernel for skdist_tpu's FastHashingVectorizer: tokenise documents
 * (word or char_wb analyzers), form n-grams, FNV-1a hash them into a
 * bounded feature space, and emit CSR arrays ready for scipy.
 *
 * Exact algorithm (tokenisation rules, hash, bucketing) is mirrored by
 * the pure-Python fallback in skdist_tpu/native/__init__.py; the test
 * suite asserts bit-identical outputs between the two.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* FNV-1a 32-bit */
static uint32_t fnv1a(const char *data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; i++) {
        h ^= (unsigned char)data[i];
        h *= 16777619u;
    }
    return h;
}

typedef struct {
    uint32_t *buf;
    size_t len, cap;
} U32Vec;

static int u32vec_push(U32Vec *v, uint32_t x) {
    if (v->len == v->cap) {
        size_t ncap = v->cap ? v->cap * 2 : 64;
        uint32_t *nbuf = (uint32_t *)realloc(v->buf, ncap * sizeof(uint32_t));
        if (!nbuf) return -1;
        v->buf = nbuf;
        v->cap = ncap;
    }
    v->buf[v->len++] = x;
    return 0;
}

static int is_token_char(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '_' || (c >= 0x80);
}

/* collect word token [start, end) offsets; ASCII-lowercase in place */
typedef struct {
    size_t start, end;
} Span;

typedef struct {
    Span *buf;
    size_t len, cap;
} SpanVec;

static int spanvec_push(SpanVec *v, size_t s, size_t e) {
    if (v->len == v->cap) {
        size_t ncap = v->cap ? v->cap * 2 : 32;
        Span *nbuf = (Span *)realloc(v->buf, ncap * sizeof(Span));
        if (!nbuf) return -1;
        v->buf = nbuf;
        v->cap = ncap;
    }
    v->buf[v->len].start = s;
    v->buf[v->len].end = e;
    v->len++;
    return 0;
}

/* hash word n-grams: tokens joined by single spaces */
static int hash_word_ngrams(char *text, size_t tlen, int nlo, int nhi,
                            uint32_t n_features, U32Vec *out) {
    SpanVec toks = {0};
    size_t i = 0;
    int rc = 0;
    char *scratch = NULL;
    while (i < tlen) {
        while (i < tlen && !is_token_char((unsigned char)text[i])) i++;
        size_t s = i;
        while (i < tlen && is_token_char((unsigned char)text[i])) i++;
        /* sklearn-like: tokens of length >= 2 bytes */
        if (i - s >= 2) {
            if (spanvec_push(&toks, s, i) < 0) { rc = -1; goto done; }
        }
    }
    scratch = (char *)malloc(tlen + (size_t)nhi);
    if (!scratch) { rc = -1; goto done; }
    for (int n = nlo; n <= nhi; n++) {
        if ((size_t)n > toks.len) break;
        for (size_t t = 0; t + (size_t)n <= toks.len; t++) {
            size_t pos = 0;
            for (int j = 0; j < n; j++) {
                Span sp = toks.buf[t + (size_t)j];
                if (j) scratch[pos++] = ' ';
                memcpy(scratch + pos, text + sp.start, sp.end - sp.start);
                pos += sp.end - sp.start;
            }
            if (u32vec_push(out, fnv1a(scratch, pos) % n_features) < 0) {
                rc = -1;
                goto done;
            }
        }
    }
done:
    free(scratch);
    free(toks.buf);
    return rc;
}

/* char_wb n-grams: per word padded with single spaces on both sides */
static int hash_charwb_ngrams(char *text, size_t tlen, int nlo, int nhi,
                              uint32_t n_features, U32Vec *out) {
    size_t i = 0;
    char *scratch = (char *)malloc(tlen + 2);
    if (!scratch) return -1;
    int rc = 0;
    while (i < tlen) {
        while (i < tlen && !is_token_char((unsigned char)text[i])) i++;
        size_t s = i;
        while (i < tlen && is_token_char((unsigned char)text[i])) i++;
        if (i == s) continue;
        size_t wlen = i - s;
        scratch[0] = ' ';
        memcpy(scratch + 1, text + s, wlen);
        scratch[wlen + 1] = ' ';
        size_t plen = wlen + 2;
        for (int n = nlo; n <= nhi; n++) {
            if ((size_t)n > plen) break;
            for (size_t p = 0; p + (size_t)n <= plen; p++) {
                if (u32vec_push(out, fnv1a(scratch + p, (size_t)n)
                                         % n_features) < 0) {
                    rc = -1;
                    goto done;
                }
            }
        }
    }
done:
    free(scratch);
    return rc;
}

static int cmp_u32(const void *a, const void *b) {
    uint32_t x = *(const uint32_t *)a, y = *(const uint32_t *)b;
    return (x > y) - (x < y);
}

/*
 * hash_docs(docs: list[str], n_features: int, nlo: int, nhi: int,
 *           analyzer: int (0=word, 1=char_wb), lowercase: int,
 *           binary: int)
 * -> (indptr: bytes int64, indices: bytes int32, data: bytes float32)
 */
static PyObject *hash_docs(PyObject *self, PyObject *args) {
    PyObject *docs;
    unsigned int n_features;
    int nlo, nhi, analyzer, lowercase, binary;
    if (!PyArg_ParseTuple(args, "OIiiiii", &docs, &n_features, &nlo, &nhi,
                          &analyzer, &lowercase, &binary))
        return NULL;
    if (!PyList_Check(docs)) {
        PyErr_SetString(PyExc_TypeError, "docs must be a list of str");
        return NULL;
    }
    if (n_features == 0 || nlo < 1 || nhi < nlo) {
        PyErr_SetString(PyExc_ValueError, "bad n_features / ngram range");
        return NULL;
    }
    Py_ssize_t n_docs = PyList_GET_SIZE(docs);

    int64_t *indptr = (int64_t *)malloc((size_t)(n_docs + 1) * sizeof(int64_t));
    U32Vec all_idx = {0};
    float *all_data = NULL;
    size_t data_cap = 0, data_len = 0;
    U32Vec doc_hashes = {0};
    if (!indptr) goto fail_nomem;
    indptr[0] = 0;

    for (Py_ssize_t di = 0; di < n_docs; di++) {
        PyObject *item = PyList_GET_ITEM(docs, di);
        if (!PyUnicode_Check(item)) {
            PyErr_SetString(PyExc_TypeError, "docs must be a list of str");
            goto fail;
        }
        Py_ssize_t blen;
        const char *bytes = PyUnicode_AsUTF8AndSize(item, &blen);
        if (!bytes) goto fail;
        char *text = (char *)malloc((size_t)blen + 1);
        if (!text) goto fail_nomem;
        if (lowercase) {
            for (Py_ssize_t b = 0; b < blen; b++) {
                char c = bytes[b];
                text[b] = (c >= 'A' && c <= 'Z') ? (char)(c + 32) : c;
            }
        } else {
            memcpy(text, bytes, (size_t)blen);
        }
        text[blen] = 0;

        doc_hashes.len = 0;
        int rc = analyzer == 0
            ? hash_word_ngrams(text, (size_t)blen, nlo, nhi, n_features,
                               &doc_hashes)
            : hash_charwb_ngrams(text, (size_t)blen, nlo, nhi, n_features,
                                 &doc_hashes);
        free(text);
        if (rc < 0) goto fail_nomem;

        /* sort + run-length encode into CSR row */
        if (doc_hashes.len)
            qsort(doc_hashes.buf, doc_hashes.len, sizeof(uint32_t), cmp_u32);
        size_t r = 0;
        while (r < doc_hashes.len) {
            uint32_t col = doc_hashes.buf[r];
            size_t cnt = 1;
            while (r + cnt < doc_hashes.len && doc_hashes.buf[r + cnt] == col)
                cnt++;
            if (data_len == data_cap) {
                size_t ncap = data_cap ? data_cap * 2 : 1024;
                float *nd = (float *)realloc(all_data, ncap * sizeof(float));
                if (!nd) goto fail_nomem;
                all_data = nd;
                data_cap = ncap;
            }
            if (u32vec_push(&all_idx, col) < 0) goto fail_nomem;
            all_data[data_len++] = binary ? 1.0f : (float)cnt;
            r += cnt;
        }
        indptr[di + 1] = (int64_t)data_len;
    }

    {
        PyObject *py_indptr = PyBytes_FromStringAndSize(
            (const char *)indptr, (Py_ssize_t)((n_docs + 1) * sizeof(int64_t)));
        PyObject *py_indices = PyBytes_FromStringAndSize(
            (const char *)all_idx.buf, (Py_ssize_t)(data_len * sizeof(uint32_t)));
        PyObject *py_data = PyBytes_FromStringAndSize(
            (const char *)all_data, (Py_ssize_t)(data_len * sizeof(float)));
        free(indptr);
        free(all_idx.buf);
        free(all_data);
        free(doc_hashes.buf);
        if (!py_indptr || !py_indices || !py_data) {
            Py_XDECREF(py_indptr);
            Py_XDECREF(py_indices);
            Py_XDECREF(py_data);
            return NULL;
        }
        PyObject *out = PyTuple_Pack(3, py_indptr, py_indices, py_data);
        Py_DECREF(py_indptr);
        Py_DECREF(py_indices);
        Py_DECREF(py_data);
        return out;
    }

fail_nomem:
    PyErr_NoMemory();
fail:
    free(indptr);
    free(all_idx.buf);
    free(all_data);
    free(doc_hashes.buf);
    return NULL;
}

static PyMethodDef Methods[] = {
    {"hash_docs", hash_docs, METH_VARARGS,
     "Hash documents into CSR arrays (indptr, indices, data)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fasthash", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit__fasthash(void) {
    return PyModule_Create(&moduledef);
}
