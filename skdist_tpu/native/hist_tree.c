/*
 * hist_tree: multithreaded per-level histogram accumulation + split
 * search for the host (CPU) forest engine.
 *
 * The device tree builder (models/tree.py) expresses the per-level
 * histogram as an XLA scatter-add (CPU) or one-hot matmul / Pallas
 * contraction (TPU). On CPU the scatter executes effectively serially
 * and was measured as the whole forest's bottleneck (hist_calib.json:
 * 20.1 s warm / 100 trees vs sklearn's 7.5 s on 20k x 54). These
 * kernels replace it for the host path (the role sklearn's Cython
 * builder played for the reference — reference
 * skdist/distribute/ensemble.py:106-108):
 *
 * hist_level — index-based accumulation, 2 adds per (sample, feature)
 * for classification instead of C=K+1 channel adds, parallelised over
 * (tree, feature) slabs with the GIL released. An optional per-(tree,
 * feature) activity mask skips features no node at this level sampled
 * (with max_features='sqrt' the union is small at shallow levels).
 *
 * best_splits — the per-level split search as ONE streaming pass over
 * the histogram (running left-accumulators per bin) instead of the
 * numpy cumsum + einsum pipeline and its histogram-sized temporaries.
 * Honors the per-(tree, feature, node) sampling mask and (ExtraTrees)
 * evaluates only the pre-drawn random threshold, computing the
 * occupied-bin range inline. Tie-breaking matches numpy argmax over a
 * feature-major (f*B + b) flattening: iteration is f-then-b ascending
 * with strictly-greater comparison.
 *
 * Contracts are mirrored by pure-numpy fallbacks in
 * models/native_forest.py / native/__init__.py (tested equal).
 *
 * Layouts: hist f32 (Tb, d, nl, B, C); XbT u8 (d, n) feature-major
 * bins; node_rel i32 (Tb, n), -1 = inactive; W f32 (Tb, n); cls i32
 * (n) or yv f32 (n); act u8 (Tb, d); fmask u8 (Tb, d, nl);
 * urand f32 (Tb, d, nl).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

#define MAX_CH 260 /* channel cap for stack accumulators (K <= 259) */

/* ------------------------------------------------------------------ */
/* hist_level                                                          */
/* ------------------------------------------------------------------ */

typedef struct {
    float *hist;
    const uint8_t *XbT;
    const int32_t *node_rel;
    const float *W;
    const int32_t *cls; /* NULL for regression */
    const float *yv;    /* NULL for classification */
    const uint8_t *act; /* NULL = all features active */
    int64_t n, d, nl, B, C;
    int64_t item0, item1; /* (t, f) flat work range */
} HistJob;

static void *hist_items(void *arg) {
    HistJob *j = (HistJob *)arg;
    const int64_t n = j->n, d = j->d, B = j->B, C = j->C;
    const int64_t slab = j->nl * B * C;
    for (int64_t item = j->item0; item < j->item1; item++) {
        const int64_t t = item / d, f = item % d;
        float *base = j->hist + item * slab;
        memset(base, 0, (size_t)slab * sizeof(float));
        if (j->act && !j->act[item])
            continue;
        const uint8_t *bins = j->XbT + f * n;
        const int32_t *nr = j->node_rel + t * n;
        const float *w = j->W + t * n;
        if (j->cls != NULL) {
            const int32_t *cls = j->cls;
            for (int64_t s = 0; s < n; s++) {
                const int32_t node = nr[s];
                const float ws = w[s];
                if (node < 0 || ws == 0.0f)
                    continue;
                float *h = base + ((int64_t)node * B + bins[s]) * C;
                h[cls[s]] += ws;
                if (ws > 0.0f)
                    h[C - 1] += 1.0f;
            }
        } else {
            const float *yv = j->yv;
            for (int64_t s = 0; s < n; s++) {
                const int32_t node = nr[s];
                const float ws = w[s];
                if (node < 0 || ws == 0.0f)
                    continue;
                float *h = base + ((int64_t)node * B + bins[s]) * C;
                const float y = yv[s];
                h[0] += ws;
                h[1] += ws * y;
                h[2] += ws * y * y;
                if (ws > 0.0f)
                    h[3] += 1.0f;
            }
        }
    }
    return NULL;
}

/* ------------------------------------------------------------------ */
/* best_splits                                                         */
/* ------------------------------------------------------------------ */

typedef struct {
    const float *hist;
    const uint8_t *fmask; /* NULL = all features sampled everywhere */
    const float *urand;   /* NULL = best-split mode (not ExtraTrees) */
    float *out_gain;
    int32_t *out_f, *out_t;
    float *out_cntl, *out_cntr;
    int64_t d, nl, B, C, K;
    int classification;
    double msl; /* min_samples_leaf on the unweighted count channel */
    int64_t item0, item1; /* (t, node) flat work range */
} SplitJob;

static void *split_items(void *arg) {
    SplitJob *j = (SplitJob *)arg;
    const int64_t d = j->d, nl = j->nl, B = j->B, C = j->C, K = j->K;
    const int64_t fstride = nl * B * C;
    double tot[MAX_CH], acc[MAX_CH];
    for (int64_t item = j->item0; item < j->item1; item++) {
        const int64_t t = item / nl, node = item % nl;
        const float *tbase = j->hist + t * d * fstride + node * B * C;
        const uint8_t *fm = j->fmask ? j->fmask + (t * d) * nl + node : NULL;
        const float *ur = j->urand ? j->urand + (t * d) * nl + node : NULL;
        double best_gain = -1e30, st = 0.0, totcnt = 0.0, totw = 0.0;
        int32_t best_f = 0, best_t = 0;
        double best_cl = 0.0, best_cr = 0.0;
        int have_tot = 0;
        for (int64_t f = 0; f < d; f++) {
            if (fm && !fm[f * nl])
                continue;
            const float *h = tbase + f * fstride;
            /* pass 1: node totals (feature-independent; computed once)
               and, for ExtraTrees, this feature's occupied bin range */
            int64_t lo = 0, hi = B - 1, seen = 0;
            if (!have_tot || ur) {
                if (!have_tot)
                    for (int64_t c = 0; c < C; c++)
                        tot[c] = 0.0;
                for (int64_t b = 0; b < B; b++) {
                    const float *hb = h + b * C;
                    if (!have_tot)
                        for (int64_t c = 0; c < C; c++)
                            tot[c] += hb[c];
                    if (ur && hb[C - 1] > 0.0f) {
                        if (!seen) {
                            lo = b;
                            seen = 1;
                        }
                        hi = b;
                    }
                }
                if (!have_tot) {
                    totcnt = tot[C - 1];
                    if (j->classification) {
                        double wt = 0.0, ss = 0.0;
                        for (int64_t c = 0; c < K; c++) {
                            wt += tot[c];
                            ss += tot[c] * tot[c];
                        }
                        totw = wt;
                        st = ss / (wt > 1e-12 ? wt : 1e-12);
                    }
                    have_tot = 1;
                }
            }
            int64_t tsel = -1;
            if (ur) {
                int64_t span = hi - lo;
                if (span < 1)
                    span = 1;
                tsel = lo + (int64_t)(ur[f * nl] * (double)span);
                if (tsel > B - 2)
                    tsel = B - 2;
                if (tsel < 0)
                    tsel = 0;
            }
            /* pass 2: running left stats per threshold */
            for (int64_t c = 0; c < C; c++)
                acc[c] = 0.0;
            for (int64_t b = 0; b < B; b++) {
                const float *hb = h + b * C;
                for (int64_t c = 0; c < C; c++)
                    acc[c] += hb[c];
                if (ur && b != tsel)
                    continue;
                const double cl = acc[C - 1], cr = totcnt - cl;
                if (cl < j->msl || cr < j->msl)
                    continue;
                double gain;
                if (j->classification) {
                    double wl = 0.0, sl = 0.0, wr = 0.0, sr = 0.0;
                    for (int64_t c = 0; c < K; c++) {
                        const double l = acc[c], r = tot[c] - l;
                        wl += l;
                        sl += l * l;
                        wr += r;
                        sr += r * r;
                    }
                    sl /= (wl > 1e-12 ? wl : 1e-12);
                    sr /= (wr > 1e-12 ? wr : 1e-12);
                    gain = sl + sr - st;
                } else {
                    const double w_l = acc[0], wy_l = acc[1],
                                 wy2_l = acc[2];
                    const double w_r = tot[0] - w_l, wy_r = tot[1] - wy_l,
                                 wy2_r = tot[2] - wy2_l;
                    const double sse_l =
                        wy2_l - wy_l * wy_l / (w_l > 1e-12 ? w_l : 1e-12);
                    const double sse_r =
                        wy2_r - wy_r * wy_r / (w_r > 1e-12 ? w_r : 1e-12);
                    const double sse_t =
                        tot[2] -
                        tot[1] * tot[1] / (tot[0] > 1e-12 ? tot[0] : 1e-12);
                    gain = sse_t - (sse_l + sse_r);
                }
                if (gain > best_gain) {
                    best_gain = gain;
                    best_f = (int32_t)f;
                    best_t = (int32_t)b;
                    best_cl = cl;
                    best_cr = cr;
                }
            }
        }
        j->out_gain[item] = (float)best_gain;
        j->out_f[item] = best_f;
        j->out_t[item] = best_t;
        j->out_cntl[item] = (float)best_cl;
        j->out_cntr[item] = (float)best_cr;
    }
    return NULL;
}

/* ------------------------------------------------------------------ */
/* forest_walk: predict-side tree traversal                            */
/* ------------------------------------------------------------------ */

typedef struct {
    const uint8_t *Xb; /* (n, d) row-major bins */
    const int32_t *feat, *thr; /* (T, N) */
    const uint8_t *is_split;   /* (T, N) */
    const float *leaf;         /* (T, N, K); NULL in apply mode */
    float *out_mean;           /* (n, K) mean leaf; NULL in apply mode */
    int32_t *out_nodes;        /* (n, T) final node ids; NULL otherwise */
    int64_t n, d, T, N, K, D;
    int64_t s0, s1; /* sample range */
} WalkJob;

static void *walk_samples(void *arg) {
    WalkJob *j = (WalkJob *)arg;
    const int64_t d = j->d, T = j->T, N = j->N, K = j->K, D = j->D;
    for (int64_t s = j->s0; s < j->s1; s++) {
        const uint8_t *row = j->Xb + s * d;
        float *acc = j->out_mean ? j->out_mean + s * K : NULL;
        if (acc)
            for (int64_t c = 0; c < K; c++)
                acc[c] = 0.0f;
        for (int64_t t = 0; t < T; t++) {
            const int32_t *feat = j->feat + t * N;
            const int32_t *thr = j->thr + t * N;
            const uint8_t *sp = j->is_split + t * N;
            int32_t node = 0;
            for (int64_t lvl = 0; lvl < D; lvl++) {
                if (!sp[node])
                    break;
                node = 2 * node + 1 + (row[feat[node]] > thr[node]);
            }
            if (acc) {
                const float *lv = j->leaf + (t * N + node) * K;
                for (int64_t c = 0; c < K; c++)
                    acc[c] += lv[c];
            } else {
                j->out_nodes[s * T + t] = node;
            }
        }
        if (acc) {
            const float inv = 1.0f / (float)T;
            for (int64_t c = 0; c < K; c++)
                acc[c] *= inv;
        }
    }
    return NULL;
}

/* ------------------------------------------------------------------ */
/* dispatch helpers                                                    */
/* ------------------------------------------------------------------ */

static int run_threaded(void *(*fn)(void *), void *jobs, size_t job_size,
                        int64_t *item0s, int64_t *item1s, int nt) {
    /* pthread_t is opaque (a struct on some platforms), so thread
     * liveness is tracked in a separate flag array rather than by
     * sentinel-zeroing the handles. */
    pthread_t tids[64];
    char started[64] = {0};
    for (int k = 0; k < nt; k++) {
        char *job = (char *)jobs + k * job_size;
        if (item0s[k] >= item1s[k])
            continue;
        if (k == nt - 1 || pthread_create(&tids[k], NULL, fn, job) != 0) {
            fn(job); /* last chunk (or spawn failure) runs inline */
        } else {
            started[k] = 1;
        }
    }
    for (int k = 0; k < nt; k++)
        if (started[k])
            pthread_join(tids[k], NULL);
    return 0;
}

static int clamp_threads(Py_ssize_t n_threads, int64_t n_items) {
    int nt = (int)n_threads;
    if (nt < 1)
        nt = 1;
    if (nt > 64)
        nt = 64;
    if ((int64_t)nt > n_items)
        nt = (int)(n_items > 0 ? n_items : 1);
    return nt;
}

/* ------------------------------------------------------------------ */
/* python entry points                                                 */
/* ------------------------------------------------------------------ */

static PyObject *hist_level(PyObject *self, PyObject *args) {
    Py_buffer hist_buf, xbt_buf, nr_buf, w_buf;
    Py_buffer cls_buf = {0}, yv_buf = {0}, act_buf = {0};
    Py_ssize_t n, d, Tb, nl, B, C, n_threads;
    PyObject *cls_obj, *yv_obj, *act_obj;
    if (!PyArg_ParseTuple(args, "w*y*y*y*OOOnnnnnnn", &hist_buf, &xbt_buf,
                          &nr_buf, &w_buf, &cls_obj, &yv_obj, &act_obj, &n,
                          &d, &Tb, &nl, &B, &C, &n_threads))
        return NULL;
    if (cls_obj != Py_None &&
        PyObject_GetBuffer(cls_obj, &cls_buf, PyBUF_SIMPLE) < 0)
        goto fail;
    if (yv_obj != Py_None &&
        PyObject_GetBuffer(yv_obj, &yv_buf, PyBUF_SIMPLE) < 0)
        goto fail;
    if (act_obj != Py_None &&
        PyObject_GetBuffer(act_obj, &act_buf, PyBUF_SIMPLE) < 0)
        goto fail;
    if ((cls_buf.buf == NULL) == (yv_buf.buf == NULL)) {
        PyErr_SetString(PyExc_ValueError,
                        "exactly one of cls / yv must be provided");
        goto fail;
    }
    if (hist_buf.len < (Py_ssize_t)(Tb * d * nl * B * C * sizeof(float)) ||
        xbt_buf.len < (Py_ssize_t)(d * n) ||
        nr_buf.len < (Py_ssize_t)(Tb * n * sizeof(int32_t)) ||
        w_buf.len < (Py_ssize_t)(Tb * n * sizeof(float)) ||
        (act_buf.buf && act_buf.len < (Py_ssize_t)(Tb * d))) {
        PyErr_SetString(PyExc_ValueError, "buffer too small for shape");
        goto fail;
    }

    {
        int64_t n_items = (int64_t)Tb * d;
        int nt = clamp_threads(n_threads, n_items);
        HistJob jobs[64];
        int64_t i0[64], i1[64];
        int64_t chunk = (n_items + nt - 1) / nt;
        for (int k = 0; k < nt; k++) {
            i0[k] = k * chunk;
            i1[k] = (k + 1) * chunk < n_items ? (k + 1) * chunk : n_items;
            jobs[k] = (HistJob){
                .hist = (float *)hist_buf.buf,
                .XbT = (const uint8_t *)xbt_buf.buf,
                .node_rel = (const int32_t *)nr_buf.buf,
                .W = (const float *)w_buf.buf,
                .cls = (const int32_t *)cls_buf.buf,
                .yv = (const float *)yv_buf.buf,
                .act = (const uint8_t *)act_buf.buf,
                .n = n, .d = d, .nl = nl, .B = B, .C = C,
                .item0 = i0[k], .item1 = i1[k],
            };
        }
        Py_BEGIN_ALLOW_THREADS;
        run_threaded(hist_items, jobs, sizeof(HistJob), i0, i1, nt);
        Py_END_ALLOW_THREADS;
    }

    if (cls_buf.buf)
        PyBuffer_Release(&cls_buf);
    if (yv_buf.buf)
        PyBuffer_Release(&yv_buf);
    if (act_buf.buf)
        PyBuffer_Release(&act_buf);
    PyBuffer_Release(&hist_buf);
    PyBuffer_Release(&xbt_buf);
    PyBuffer_Release(&nr_buf);
    PyBuffer_Release(&w_buf);
    Py_RETURN_NONE;

fail:
    if (cls_buf.buf)
        PyBuffer_Release(&cls_buf);
    if (yv_buf.buf)
        PyBuffer_Release(&yv_buf);
    if (act_buf.buf)
        PyBuffer_Release(&act_buf);
    PyBuffer_Release(&hist_buf);
    PyBuffer_Release(&xbt_buf);
    PyBuffer_Release(&nr_buf);
    PyBuffer_Release(&w_buf);
    return NULL;
}

static PyObject *best_splits(PyObject *self, PyObject *args) {
    Py_buffer hist_buf;
    Py_buffer fm_buf = {0}, ur_buf = {0};
    Py_buffer g_buf, f_buf, t_buf, cl_buf, cr_buf;
    Py_ssize_t Tb, d, nl, B, C, K, classification, n_threads;
    double msl;
    PyObject *fm_obj, *ur_obj;
    if (!PyArg_ParseTuple(args, "y*OOw*w*w*w*w*nnnnnnndn", &hist_buf,
                          &fm_obj, &ur_obj, &g_buf, &f_buf, &t_buf, &cl_buf,
                          &cr_buf, &Tb, &d, &nl, &B, &C, &K, &classification,
                          &msl, &n_threads))
        return NULL;
    if (fm_obj != Py_None &&
        PyObject_GetBuffer(fm_obj, &fm_buf, PyBUF_SIMPLE) < 0)
        goto fail;
    if (ur_obj != Py_None &&
        PyObject_GetBuffer(ur_obj, &ur_buf, PyBUF_SIMPLE) < 0)
        goto fail;
    if (C > MAX_CH || K > MAX_CH) {
        PyErr_SetString(PyExc_ValueError, "too many channels for C kernel");
        goto fail;
    }
    if (hist_buf.len < (Py_ssize_t)(Tb * d * nl * B * C * sizeof(float)) ||
        g_buf.len < (Py_ssize_t)(Tb * nl * sizeof(float)) ||
        f_buf.len < (Py_ssize_t)(Tb * nl * sizeof(int32_t)) ||
        t_buf.len < (Py_ssize_t)(Tb * nl * sizeof(int32_t)) ||
        cl_buf.len < (Py_ssize_t)(Tb * nl * sizeof(float)) ||
        cr_buf.len < (Py_ssize_t)(Tb * nl * sizeof(float)) ||
        (fm_buf.buf && fm_buf.len < (Py_ssize_t)(Tb * d * nl)) ||
        (ur_buf.buf &&
         ur_buf.len < (Py_ssize_t)(Tb * d * nl * sizeof(float)))) {
        PyErr_SetString(PyExc_ValueError, "buffer too small for shape");
        goto fail;
    }

    {
        int64_t n_items = (int64_t)Tb * nl;
        int nt = clamp_threads(n_threads, n_items);
        SplitJob jobs[64];
        int64_t i0[64], i1[64];
        int64_t chunk = (n_items + nt - 1) / nt;
        for (int k = 0; k < nt; k++) {
            i0[k] = k * chunk;
            i1[k] = (k + 1) * chunk < n_items ? (k + 1) * chunk : n_items;
            jobs[k] = (SplitJob){
                .hist = (const float *)hist_buf.buf,
                .fmask = (const uint8_t *)fm_buf.buf,
                .urand = (const float *)ur_buf.buf,
                .out_gain = (float *)g_buf.buf,
                .out_f = (int32_t *)f_buf.buf,
                .out_t = (int32_t *)t_buf.buf,
                .out_cntl = (float *)cl_buf.buf,
                .out_cntr = (float *)cr_buf.buf,
                .d = d, .nl = nl, .B = B, .C = C, .K = K,
                .classification = (int)classification,
                .msl = msl,
                .item0 = i0[k], .item1 = i1[k],
            };
        }
        Py_BEGIN_ALLOW_THREADS;
        run_threaded(split_items, jobs, sizeof(SplitJob), i0, i1, nt);
        Py_END_ALLOW_THREADS;
    }

    if (fm_buf.buf)
        PyBuffer_Release(&fm_buf);
    if (ur_buf.buf)
        PyBuffer_Release(&ur_buf);
    PyBuffer_Release(&hist_buf);
    PyBuffer_Release(&g_buf);
    PyBuffer_Release(&f_buf);
    PyBuffer_Release(&t_buf);
    PyBuffer_Release(&cl_buf);
    PyBuffer_Release(&cr_buf);
    Py_RETURN_NONE;

fail:
    if (fm_buf.buf)
        PyBuffer_Release(&fm_buf);
    if (ur_buf.buf)
        PyBuffer_Release(&ur_buf);
    PyBuffer_Release(&hist_buf);
    PyBuffer_Release(&g_buf);
    PyBuffer_Release(&f_buf);
    PyBuffer_Release(&t_buf);
    PyBuffer_Release(&cl_buf);
    PyBuffer_Release(&cr_buf);
    return NULL;
}

static PyObject *forest_walk(PyObject *self, PyObject *args) {
    Py_buffer xb_buf, feat_buf, thr_buf, sp_buf;
    Py_buffer leaf_buf = {0}, mean_buf = {0}, nodes_buf = {0};
    PyObject *leaf_obj, *mean_obj, *nodes_obj;
    Py_ssize_t n, d, T, N, K, D, n_threads;
    if (!PyArg_ParseTuple(args, "y*y*y*y*OOOnnnnnnn", &xb_buf, &feat_buf,
                          &thr_buf, &sp_buf, &leaf_obj, &mean_obj,
                          &nodes_obj, &n, &d, &T, &N, &K, &D, &n_threads))
        return NULL;
    if (leaf_obj != Py_None &&
        PyObject_GetBuffer(leaf_obj, &leaf_buf, PyBUF_SIMPLE) < 0)
        goto fail;
    if (mean_obj != Py_None &&
        PyObject_GetBuffer(mean_obj, &mean_buf, PyBUF_WRITABLE) < 0)
        goto fail;
    if (nodes_obj != Py_None &&
        PyObject_GetBuffer(nodes_obj, &nodes_buf, PyBUF_WRITABLE) < 0)
        goto fail;
    if ((mean_buf.buf == NULL) == (nodes_buf.buf == NULL) ||
        (mean_buf.buf != NULL && leaf_buf.buf == NULL)) {
        PyErr_SetString(PyExc_ValueError,
                        "need exactly one of out_mean (with leaf) / "
                        "out_nodes");
        goto fail;
    }
    if (xb_buf.len < (Py_ssize_t)(n * d) ||
        feat_buf.len < (Py_ssize_t)(T * N * sizeof(int32_t)) ||
        thr_buf.len < (Py_ssize_t)(T * N * sizeof(int32_t)) ||
        sp_buf.len < (Py_ssize_t)(T * N) ||
        (leaf_buf.buf &&
         leaf_buf.len < (Py_ssize_t)(T * N * K * sizeof(float))) ||
        (mean_buf.buf &&
         mean_buf.len < (Py_ssize_t)(n * K * sizeof(float))) ||
        (nodes_buf.buf &&
         nodes_buf.len < (Py_ssize_t)(n * T * sizeof(int32_t)))) {
        PyErr_SetString(PyExc_ValueError, "buffer too small for shape");
        goto fail;
    }

    {
        int nt = clamp_threads(n_threads, n);
        WalkJob jobs[64];
        int64_t i0[64], i1[64];
        int64_t chunk = (n + nt - 1) / nt;
        for (int k = 0; k < nt; k++) {
            i0[k] = k * chunk;
            i1[k] = (k + 1) * chunk < n ? (k + 1) * chunk : n;
            jobs[k] = (WalkJob){
                .Xb = (const uint8_t *)xb_buf.buf,
                .feat = (const int32_t *)feat_buf.buf,
                .thr = (const int32_t *)thr_buf.buf,
                .is_split = (const uint8_t *)sp_buf.buf,
                .leaf = (const float *)leaf_buf.buf,
                .out_mean = (float *)mean_buf.buf,
                .out_nodes = (int32_t *)nodes_buf.buf,
                .n = n, .d = d, .T = T, .N = N, .K = K, .D = D,
                .s0 = i0[k], .s1 = i1[k],
            };
        }
        Py_BEGIN_ALLOW_THREADS;
        run_threaded(walk_samples, jobs, sizeof(WalkJob), i0, i1, nt);
        Py_END_ALLOW_THREADS;
    }

    if (leaf_buf.buf)
        PyBuffer_Release(&leaf_buf);
    if (mean_buf.buf)
        PyBuffer_Release(&mean_buf);
    if (nodes_buf.buf)
        PyBuffer_Release(&nodes_buf);
    PyBuffer_Release(&xb_buf);
    PyBuffer_Release(&feat_buf);
    PyBuffer_Release(&thr_buf);
    PyBuffer_Release(&sp_buf);
    Py_RETURN_NONE;

fail:
    if (leaf_buf.buf)
        PyBuffer_Release(&leaf_buf);
    if (mean_buf.buf)
        PyBuffer_Release(&mean_buf);
    if (nodes_buf.buf)
        PyBuffer_Release(&nodes_buf);
    PyBuffer_Release(&xb_buf);
    PyBuffer_Release(&feat_buf);
    PyBuffer_Release(&thr_buf);
    PyBuffer_Release(&sp_buf);
    return NULL;
}

static PyMethodDef Methods[] = {
    {"hist_level", hist_level, METH_VARARGS,
     "accumulate per-level (tree, feature, node, bin, channel) histograms"},
    {"best_splits", best_splits, METH_VARARGS,
     "per-(tree, node) best split from a level histogram"},
    {"forest_walk", forest_walk, METH_VARARGS,
     "tree traversal: mean leaf values or final node ids per sample"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hist_tree", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit__hist_tree(void) { return PyModule_Create(&moduledef); }
