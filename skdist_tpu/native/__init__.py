"""
Native (C) runtime components with build-on-demand and pure-Python
fallbacks.

The reference framework's native compute lived in its dependencies
(sklearn Cython, Spark JVM, pyarrow C++ — SURVEY §2.2). skdist_tpu's
device compute is XLA; the host-side hot path that merits native code
is text featurisation (the Encoderizer's hashing vectorisers). This
package compiles ``fasthash.c`` with the system compiler on first use
(no pip/network needed) and falls back to a byte-identical pure-Python
implementation when no compiler is available.
"""

import os
import subprocess
import sysconfig
import tempfile
import threading

import numpy as np

_EXTS = {}
_LOAD_LOCK = threading.Lock()


def _load_ext(name, extra_flags=()):
    """Import the compiled module ``_<name>`` (from ``<name>.c``),
    building it on first use.

    Any failure anywhere (read-only tree, missing compiler, truncated
    artifact) returns None so callers take the pure-Python path — the
    fallback contract must survive hostile installs. Builds go to a
    temp file and are renamed into place (atomic on POSIX) so
    concurrent processes never load a half-written .so.
    """
    with _LOAD_LOCK:
        if name in _EXTS:
            return _EXTS[name]
        try:
            mod = _load_ext_inner(name, extra_flags)
        except Exception:
            mod = None
        _EXTS[name] = mod
        return mod


def _load_ext_inner(name, extra_flags):
    import importlib.util

    build_dir = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(build_dir, exist_ok=True)
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(build_dir, f"_{name}{suffix}")
    src = os.path.join(os.path.dirname(__file__), f"{name}.c")
    if not os.path.exists(so_path) or (
        os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(so_path)
    ):
        cc = os.environ.get("CC", "cc")
        include = sysconfig.get_paths()["include"]
        fd, tmp_path = tempfile.mkstemp(suffix=suffix, dir=build_dir)
        os.close(fd)
        try:
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", *extra_flags,
                 f"-I{include}", src, "-o", tmp_path],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp_path, so_path)
        finally:
            if os.path.exists(tmp_path):
                os.unlink(tmp_path)
    spec = importlib.util.spec_from_file_location(f"_{name}", so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_native():
    return _load_ext("fasthash")


# ---------------------------------------------------------------------------
# pure-Python reference implementation (byte-identical contract)
# ---------------------------------------------------------------------------

def _fnv1a(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h


def _is_token_char(b):
    return (
        (0x61 <= b <= 0x7A) or (0x41 <= b <= 0x5A) or (0x30 <= b <= 0x39)
        or b == 0x5F or b >= 0x80
    )


def _tokenize(text: bytes):
    toks, i, n = [], 0, len(text)
    while i < n:
        while i < n and not _is_token_char(text[i]):
            i += 1
        s = i
        while i < n and _is_token_char(text[i]):
            i += 1
        if i - s >= 2:
            toks.append(text[s:i])
    return toks


def _words_all(text: bytes):
    toks, i, n = [], 0, len(text)
    while i < n:
        while i < n and not _is_token_char(text[i]):
            i += 1
        s = i
        while i < n and _is_token_char(text[i]):
            i += 1
        if i > s:
            toks.append(text[s:i])
    return toks


def _py_hash_doc(text, n_features, nlo, nhi, analyzer, lowercase):
    if lowercase:
        # ASCII-only lowering, matching the C kernel
        text = bytes(
            b + 32 if 0x41 <= b <= 0x5A else b for b in text.encode("utf-8")
        )
    else:
        text = text.encode("utf-8")
    hashes = []
    if analyzer == 0:  # word
        toks = _tokenize(text)
        for n in range(nlo, nhi + 1):
            if n > len(toks):
                break
            for t in range(len(toks) - n + 1):
                gram = b" ".join(toks[t:t + n])
                hashes.append(_fnv1a(gram) % n_features)
    else:  # char_wb
        for w in _words_all(text):
            padded = b" " + w + b" "
            for n in range(nlo, nhi + 1):
                if n > len(padded):
                    break
                for p in range(len(padded) - n + 1):
                    hashes.append(_fnv1a(padded[p:p + n]) % n_features)
    return hashes


def _py_hash_docs(docs, n_features, nlo, nhi, analyzer, lowercase, binary):
    indptr = [0]
    indices, data = [], []
    for doc in docs:
        hashes = sorted(
            _py_hash_doc(doc, n_features, nlo, nhi, analyzer, lowercase)
        )
        i = 0
        while i < len(hashes):
            j = i
            while j < len(hashes) and hashes[j] == hashes[i]:
                j += 1
            indices.append(hashes[i])
            data.append(1.0 if binary else float(j - i))
            i = j
        indptr.append(len(indices))
    return (
        np.asarray(indptr, dtype=np.int64),
        np.asarray(indices, dtype=np.uint32),
        np.asarray(data, dtype=np.float32),
    )


def hash_documents(docs, n_features=2**12, ngram_range=(1, 1),
                   analyzer="word", lowercase=True, binary=False,
                   force_python=False):
    """Hash text documents → scipy CSR matrix (n_docs, n_features).

    Uses the compiled C kernel when available; the Python path is
    byte-identical (tested).
    """
    from scipy import sparse

    docs = [d if isinstance(d, str) else str(d) for d in docs]
    nlo, nhi = ngram_range
    a = {"word": 0, "char_wb": 1}[analyzer]
    native = None if force_python else _load_native()
    if native is not None:
        bi, bidx, bdat = native.hash_docs(
            docs, n_features, nlo, nhi, a, int(lowercase), int(binary)
        )
        indptr = np.frombuffer(bi, dtype=np.int64)
        indices = np.frombuffer(bidx, dtype=np.uint32)
        data = np.frombuffer(bdat, dtype=np.float32)
    else:
        indptr, indices, data = _py_hash_docs(
            docs, n_features, nlo, nhi, a, lowercase, binary
        )
    return sparse.csr_matrix(
        (data, indices.astype(np.int32), indptr),
        shape=(len(docs), n_features),
    )


def native_available():
    return _load_native() is not None


# ---------------------------------------------------------------------------
# per-level tree histograms (hist_tree.c) — host forest engine
# ---------------------------------------------------------------------------

def hist_tree_available():
    return _load_ext("hist_tree", ("-pthread",)) is not None


def hist_level(hist, XbT, node_rel, W, cls=None, yv=None, act=None,
               n_threads=None, force_python=False):
    """Accumulate (Tb, d, nl, B, C) per-level histograms (zero-fills
    ``hist`` first; callers pass ``np.empty``).

    ``XbT`` (d, n) uint8 feature-major bins, ``node_rel`` (Tb, n) int32
    (-1 = sample not at this level), ``W`` (Tb, n) f32 weights, and
    exactly one of ``cls`` (n,) int32 / ``yv`` (n,) f32 selects the
    classification / regression channel layout (see hist_tree.c).
    ``act`` (Tb, d) uint8 skips features no node of that tree sampled
    this level (their slabs are left zeroed — callers must not read
    stats from a skipped feature). The numpy fallback is semantically
    identical (tested).
    """
    Tb, d, nl, B, C = hist.shape
    n = XbT.shape[1]
    mod = None if force_python else _load_ext("hist_tree", ("-pthread",))
    if mod is not None:
        if n_threads is None:
            n_threads = min(16, os.cpu_count() or 1)
        mod.hist_level(
            hist, XbT, node_rel, W,
            None if cls is None else cls, None if yv is None else yv,
            None if act is None else act,
            n, d, Tb, nl, B, C, int(n_threads),
        )
        return hist
    # ---- numpy fallback: one bincount-style scatter per (tree, feature)
    hist[:] = 0.0
    flat = hist.reshape(Tb, d, nl * B, C)
    for t in range(Tb):
        w = W[t]
        live = (node_rel[t] >= 0) & (w != 0)
        if not live.any():
            continue
        nr = node_rel[t][live].astype(np.int64)
        wa = w[live]
        if cls is not None:
            ch = np.zeros((live.sum(), C), np.float32)
            ch[np.arange(len(wa)), cls[live]] = wa
            ch[:, C - 1] = (wa > 0)
        else:
            ya = yv[live]
            ch = np.stack([wa, wa * ya, wa * ya * ya,
                           (wa > 0).astype(np.float32)], axis=1)
        for f in range(d):
            if act is not None and not act[t, f]:
                continue
            seg = nr * B + XbT[f][live]
            np.add.at(flat[t, f], seg, ch)
    return hist


def forest_walk_native(Xb, trees, max_depth, mode="predict",
                       n_threads=None):
    """Predict-side tree traversal via the C kernel, or None when it
    is unavailable (callers then use the XLA walker).

    ``Xb`` (n, d) uint8 bins, ``trees`` the stacked pytree
    ``{feat, thr, is_split, leaf}`` (T, N)-shaped. ``mode='predict'``
    returns the (n, K) mean leaf vector; ``'apply'`` the (n, T) final
    node ids — matching ``models/forest.py::_forest_walker`` exactly
    (a node stays put once a non-split node is reached)."""
    mod = _load_ext("hist_tree", ("-pthread",))
    if mod is None:
        return None
    feat = np.ascontiguousarray(trees["feat"], np.int32)
    thr = np.ascontiguousarray(trees["thr"], np.int32)
    sp = np.ascontiguousarray(trees["is_split"], np.uint8)
    T, N = feat.shape
    if 2 ** (int(max_depth) + 1) - 1 > N:
        # a depth the arrays weren't built for (e.g. max_depth mutated
        # after fit) would walk past the buffers in C; the XLA walker's
        # clipped indexing degrades gracefully — fall through to it
        return None
    n, d = Xb.shape
    Xb = np.ascontiguousarray(Xb, np.uint8)
    if n_threads is None:
        n_threads = min(16, os.cpu_count() or 1)
    if mode == "predict":
        leaf = np.ascontiguousarray(trees["leaf"], np.float32)
        K = leaf.shape[2]
        out = np.empty((n, K), np.float32)
        mod.forest_walk(Xb, feat, thr, sp, leaf, out, None,
                        n, d, T, N, K, int(max_depth), int(n_threads))
        return out
    out = np.empty((n, T), np.int32)
    mod.forest_walk(Xb, feat, thr, sp, None, None, out,
                    n, d, T, N, 1, int(max_depth), int(n_threads))
    return out


def best_splits_native(hist, fmask, urand, K, classification,
                       min_samples_leaf, n_threads=None):
    """Per-(tree, node) best split from a level histogram via the C
    kernel, or None when the kernel is unavailable / the channel count
    exceeds its accumulator cap (callers then run the numpy scoring
    path). Returns ``(gain, f, t, cnt_l, cnt_r)`` each (Tb, nl)."""
    mod = _load_ext("hist_tree", ("-pthread",))
    Tb, d, nl, B, C = hist.shape
    if mod is None or C > 256 or K > 256:
        return None
    if n_threads is None:
        n_threads = min(16, os.cpu_count() or 1)
    gain = np.empty((Tb, nl), np.float32)
    bf = np.empty((Tb, nl), np.int32)
    bt = np.empty((Tb, nl), np.int32)
    cl = np.empty((Tb, nl), np.float32)
    cr = np.empty((Tb, nl), np.float32)
    mod.best_splits(
        hist, None if fmask is None else fmask,
        None if urand is None else urand,
        gain, bf, bt, cl, cr,
        Tb, d, nl, B, C, K, int(classification),
        float(min_samples_leaf), int(n_threads),
    )
    return gain, bf, bt, cl, cr


# ---------------------------------------------------------------------------
# multithreaded CSR -> dense f32 (densify.c)
# ---------------------------------------------------------------------------

def csr_to_dense_f32(X, force_python=False, n_threads=None):
    """Densify a scipy sparse matrix to a C-contiguous float32 array.

    The host-side boundary feeding the device: TPU has no general
    sparse matmul, so hashed-text CSR matrices densify before
    ``device_put``. The C kernel partitions rows across threads
    (zero-fill + scatter per block, GIL released); the fallback is
    scipy's single-threaded ``toarray``. Duplicate entries accumulate
    in both paths (scipy CSR semantics).
    """
    csr = X.tocsr()
    n_rows, n_cols = csr.shape
    mod = None if force_python else _load_ext("densify", ("-pthread",))
    if mod is None or n_rows == 0 or n_cols == 0:
        return np.ascontiguousarray(csr.toarray(), dtype=np.float32)
    data = np.ascontiguousarray(csr.data, dtype=np.float32)
    indices = np.ascontiguousarray(csr.indices)
    if indices.dtype not in (np.int32, np.int64):
        indices = indices.astype(np.int64)
    indptr = np.ascontiguousarray(csr.indptr, dtype=np.int64)
    out = np.empty((n_rows, n_cols), dtype=np.float32)
    if n_threads is None:
        n_threads = min(16, os.cpu_count() or 1)
    mod.csr_to_dense(
        out, data, indices, indptr, n_rows, n_cols,
        indices.dtype.itemsize, int(n_threads),
    )
    return out
