/*
 * densify: multithreaded CSR -> dense float32 for skdist_tpu.
 *
 * The sparse->dense boundary is the host-side hot path feeding the
 * device: TPU/XLA has no general sparse matmul, so every hashed-text
 * matrix (Encoderizer / FastHashingVectorizer output) densifies before
 * device_put. scipy's .toarray() is single-threaded and dominated by
 * the zero fill; this kernel partitions rows across threads, each
 * zero-filling and scattering its own block, with the GIL released.
 *
 * Contract (mirrored by the scipy fallback in native/__init__.py):
 * out[r, indices[j]] accumulates data[j] for j in
 * [indptr[r], indptr[r+1]) — ACCUMULATES, like scipy's toarray, so
 * duplicate column entries in a row sum rather than overwrite.
 *
 * Inputs arrive as contiguous buffers (no numpy C API dependency):
 * out f32 (n_rows*n_cols), data f32 (nnz), indices i32 or i64 (nnz),
 * indptr i64 (n_rows+1).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

typedef struct {
    float *out;
    const float *data;
    const void *indices;
    int idx_is_64;
    const int64_t *indptr;
    int64_t r0, r1, n_cols;
} Job;

static void *densify_rows(void *arg) {
    Job *j = (Job *)arg;
    memset(j->out + j->r0 * j->n_cols, 0,
           (size_t)(j->r1 - j->r0) * (size_t)j->n_cols * sizeof(float));
    if (j->idx_is_64) {
        const int64_t *idx = (const int64_t *)j->indices;
        for (int64_t r = j->r0; r < j->r1; r++) {
            float *row = j->out + r * j->n_cols;
            for (int64_t p = j->indptr[r]; p < j->indptr[r + 1]; p++)
                row[idx[p]] += j->data[p];
        }
    } else {
        const int32_t *idx = (const int32_t *)j->indices;
        for (int64_t r = j->r0; r < j->r1; r++) {
            float *row = j->out + r * j->n_cols;
            for (int64_t p = j->indptr[r]; p < j->indptr[r + 1]; p++)
                row[idx[p]] += j->data[p];
        }
    }
    return NULL;
}

static PyObject *csr_to_dense(PyObject *self, PyObject *args) {
    Py_buffer out_buf, data_buf, idx_buf, indptr_buf;
    Py_ssize_t n_rows, n_cols, idx_itemsize, n_threads;
    if (!PyArg_ParseTuple(args, "w*y*y*y*nnnn", &out_buf, &data_buf,
                          &idx_buf, &indptr_buf, &n_rows, &n_cols,
                          &idx_itemsize, &n_threads))
        return NULL;

    int ok = 1;
    const char *err = NULL;
    if (idx_itemsize != 4 && idx_itemsize != 8) {
        ok = 0; err = "indices must be int32 or int64";
    } else if ((Py_ssize_t)(indptr_buf.len / sizeof(int64_t)) < n_rows + 1) {
        ok = 0; err = "indptr too short";
    } else if (out_buf.len < (Py_ssize_t)(n_rows * n_cols * sizeof(float))) {
        ok = 0; err = "output buffer too small";
    } else {
        const int64_t *indptr = (const int64_t *)indptr_buf.buf;
        int64_t nnz = indptr[n_rows];
        if (data_buf.len < (Py_ssize_t)(nnz * sizeof(float))
            || idx_buf.len < (Py_ssize_t)(nnz * idx_itemsize)) {
            ok = 0; err = "data/indices shorter than indptr implies";
        }
    }
    if (!ok) {
        PyBuffer_Release(&out_buf);
        PyBuffer_Release(&data_buf);
        PyBuffer_Release(&idx_buf);
        PyBuffer_Release(&indptr_buf);
        PyErr_SetString(PyExc_ValueError, err);
        return NULL;
    }

    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    if (n_threads > n_rows) n_threads = n_rows > 0 ? n_rows : 1;

    Job jobs[64];
    pthread_t tids[64];
    int64_t per = n_rows / n_threads, extra = n_rows % n_threads;
    int spawned = 0;

    Py_BEGIN_ALLOW_THREADS
    int64_t r = 0;
    for (Py_ssize_t t = 0; t < n_threads; t++) {
        int64_t take = per + (t < extra ? 1 : 0);
        jobs[t] = (Job){
            .out = (float *)out_buf.buf,
            .data = (const float *)data_buf.buf,
            .indices = idx_buf.buf,
            .idx_is_64 = (idx_itemsize == 8),
            .indptr = (const int64_t *)indptr_buf.buf,
            .r0 = r, .r1 = r + take, .n_cols = n_cols,
        };
        r += take;
        if (t + 1 == n_threads) {
            densify_rows(&jobs[t]); /* run the last block inline */
        } else if (pthread_create(&tids[spawned], NULL, densify_rows,
                                  &jobs[t]) == 0) {
            spawned++; /* tids packed: joins stay aligned on failures */
        } else {
            densify_rows(&jobs[t]); /* thread spawn failed: run inline */
        }
    }
    for (int t = 0; t < spawned; t++)
        pthread_join(tids[t], NULL);
    Py_END_ALLOW_THREADS

    PyBuffer_Release(&out_buf);
    PyBuffer_Release(&data_buf);
    PyBuffer_Release(&idx_buf);
    PyBuffer_Release(&indptr_buf);
    Py_RETURN_NONE;
}

static PyMethodDef Methods[] = {
    {"csr_to_dense", csr_to_dense, METH_VARARGS,
     "Scatter CSR (data, indices, indptr) into a zeroed dense f32 "
     "buffer, rows partitioned across threads."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_densify", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit__densify(void) {
    return PyModule_Create(&moduledef);
}
