"""
Durable versioned model store: the catalog's source of truth.

The serving tier (PR-13/16) made "thousands of tenants on one mesh"
cheap to SERVE; this module makes the tenant population durable. The
reference world kept its periodically-retrained sk-dist models in a
blob store keyed by model name and date partition — restartable by
convention, not by contract. Here the contract is explicit:

- **dir-per-version, atomic publish**: a version is
  ``catalog_dir/<name>/<version>/`` holding ``model.pkl`` and
  ``manifest.json``. Both are written into a staging directory first
  and moved into place with one ``os.replace`` — a version either
  exists completely or not at all. SIGKILL mid-``put`` leaves only a
  staging orphan (swept by :meth:`CatalogStore.gc`), never a
  half-published version.

- **torn state is skipped, not fatal**: a version directory whose
  manifest is missing, truncated, or unparseable (a non-atomic copy,
  a bad disk, an interrupted backup restore) is invisible to
  :meth:`versions`/:meth:`get`/:meth:`latest`. A 100k-tenant catalog
  must cold-load past one corrupt tenant, not die on it.

- **manifest carries lineage**: params digest (sha256 of the pickled
  model, verified on :meth:`get`), serving precision tier, training
  provenance, the parent version a refresh warm-started from, and a
  ``status`` — ``published`` versions are servable; ``rejected``
  versions (a refresh that failed its quality gate) are stored for
  forensics but never resolved by :meth:`latest`/:meth:`get`-latest,
  so they can never reach a serving fleet through the rollout path.

- **retention is explicit**: :meth:`pin` exempts a version from
  :meth:`gc(keep_n) <gc>`, which otherwise keeps the newest ``keep_n``
  published versions per tenant and deletes the rest.
"""

import hashlib
import json
import os
import pickle
import shutil
import tempfile
import time

__all__ = ["CatalogStore", "CatalogRecord", "MANIFEST_FORMAT"]

#: manifest schema version — bump on incompatible layout changes
MANIFEST_FORMAT = 1

_MANIFEST = "manifest.json"
_MODEL = "model.pkl"
_PINNED = "PINNED"
_STAGING = ".staging"


class CatalogRecord:
    """One published (or rejected) version: name, version, manifest,
    and the directory that holds it."""

    __slots__ = ("name", "version", "path", "manifest")

    def __init__(self, name, version, path, manifest):
        self.name = name
        self.version = int(version)
        self.path = path
        self.manifest = manifest

    @property
    def spec(self):
        return f"{self.name}@{self.version}"

    @property
    def status(self):
        return self.manifest.get("status", "published")

    def __repr__(self):
        return (f"CatalogRecord({self.spec!r}, "
                f"status={self.status!r})")


class CatalogStore:
    """Durable, restart-survivable versioned model store (module
    docstring). Safe for concurrent writers in one process; atomic
    renames keep concurrent READERS safe across processes too."""

    def __init__(self, catalog_dir):
        self.catalog_dir = str(catalog_dir)
        os.makedirs(os.path.join(self.catalog_dir, _STAGING),
                    exist_ok=True)

    # ------------------------------------------------------------------
    # write path
    # ------------------------------------------------------------------
    def put(self, name, model, version=None, parent_version=None,
            serve_dtype="float32", provenance=None, status="published"):
        """Publish one version atomically; returns its
        :class:`CatalogRecord`. ``version=None`` assigns the next
        number after every version currently on disk (valid or
        pinned); an explicit version that already exists raises —
        versions are immutable, like the serving registry's."""
        name = self._check_name(name)
        if status not in ("published", "rejected"):
            raise ValueError(
                f"status must be 'published' or 'rejected'; got "
                f"{status!r}"
            )
        blob = pickle.dumps(model, protocol=pickle.HIGHEST_PROTOCOL)
        digest = "sha256:" + hashlib.sha256(blob).hexdigest()
        if version is None:
            have = self._version_dirs(name)
            version = (max(have) + 1) if have else 1
        version = int(version)
        final = self._vdir(name, version)
        if os.path.exists(final):
            raise ValueError(
                f"{name}@{version} already exists in the catalog; "
                "versions are immutable — put a new one"
            )
        manifest = {
            "format": MANIFEST_FORMAT,
            "name": name,
            "version": version,
            "digest": digest,
            "serve_dtype": serve_dtype,
            "status": status,
            "parent_version": (None if parent_version is None
                               else int(parent_version)),
            "provenance": dict(provenance or {}),
            "created_at": time.time(),
        }
        stage = tempfile.mkdtemp(
            prefix=f"{name}@{version}.",
            dir=os.path.join(self.catalog_dir, _STAGING),
        )
        try:
            with open(os.path.join(stage, _MODEL), "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            with open(os.path.join(stage, _MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.makedirs(os.path.dirname(final), exist_ok=True)
            # the atomic publish: the version appears complete or not
            # at all (os.replace of a directory is atomic on POSIX)
            os.replace(stage, final)
        except BaseException:
            shutil.rmtree(stage, ignore_errors=True)
            raise
        return CatalogRecord(name, version, final, manifest)

    def put_many(self, models, **common):
        """Bulk :meth:`put` of ``(name, model)`` pairs (or a dict)
        with shared keyword arguments; returns the records in input
        order. Each version still publishes atomically — a failure
        mid-batch leaves the earlier versions published (they are
        independently valid), and raises."""
        items = list(models.items()) if isinstance(models, dict) \
            else list(models)
        return [self.put(name, model, **common) for name, model in items]

    def pin(self, name, version):
        """Exempt ``name@version`` from :meth:`gc` (marker file — the
        manifest stays immutable)."""
        path = self._vdir(self._check_name(name), int(version))
        if self._load_manifest(path) is None:
            raise KeyError(f"{name}@{version} is not in the catalog")
        with open(os.path.join(path, _PINNED), "w") as f:
            f.write(str(time.time()))

    def unpin(self, name, version):
        path = self._vdir(self._check_name(name), int(version))
        try:
            os.unlink(os.path.join(path, _PINNED))
        except FileNotFoundError:
            pass

    def pinned(self, name, version):
        return os.path.exists(
            os.path.join(self._vdir(name, int(version)), _PINNED)
        )

    def gc(self, keep_n=3, name=None):
        """Retention: per tenant, keep the newest ``keep_n`` PUBLISHED
        versions plus every pinned version; delete the rest (old
        published versions, stale rejected versions, and torn version
        directories that never finished publishing). Also sweeps
        staging orphans from killed writers. Returns the removed
        ``(name, version)`` pairs."""
        keep_n = max(0, int(keep_n))
        removed = []
        for n in ([self._check_name(name)] if name is not None
                  else self.names(all_statuses=True)):
            base = os.path.join(self.catalog_dir, n)
            published = []
            others = []
            for v in self._version_dirs(n):
                man = self._load_manifest(self._vdir(n, v))
                if man is not None and man.get("status",
                                               "published") == "published":
                    published.append(v)
                else:
                    others.append(v)  # rejected or torn
            published.sort(reverse=True)
            keep = set(published[:keep_n])
            for v in sorted(published[keep_n:] + others):
                if v in keep or self.pinned(n, v):
                    continue
                shutil.rmtree(self._vdir(n, v), ignore_errors=True)
                removed.append((n, v))
            if not self._version_dirs(n):
                shutil.rmtree(base, ignore_errors=True)
        staging = os.path.join(self.catalog_dir, _STAGING)
        for ent in os.listdir(staging):
            shutil.rmtree(os.path.join(staging, ent),
                          ignore_errors=True)
        return removed

    # ------------------------------------------------------------------
    # read path
    # ------------------------------------------------------------------
    def names(self, all_statuses=False):
        """Tenant names with at least one published version (every
        valid version with ``all_statuses=True``), sorted."""
        out = []
        try:
            entries = sorted(os.listdir(self.catalog_dir))
        except FileNotFoundError:
            return []
        for n in entries:
            if n == _STAGING:
                continue
            if self.versions(n, all_statuses=all_statuses):
                out.append(n)
        return out

    def versions(self, name, all_statuses=True):
        """Valid version numbers for ``name``, ascending. Directories
        with a missing/torn/unparseable manifest are skipped — torn
        state is invisible, never fatal. ``all_statuses=False``
        restricts to published versions."""
        out = []
        for v in self._version_dirs(name):
            man = self._load_manifest(self._vdir(name, v))
            if man is None:
                continue
            if (not all_statuses
                    and man.get("status", "published") != "published"):
                continue
            out.append(v)
        return sorted(out)

    def latest(self, name):
        """The newest PUBLISHED record for ``name`` (rejected versions
        never resolve here — the gate's storage-only verdict), or
        ``None``."""
        vs = self.versions(name, all_statuses=False)
        if not vs:
            return None
        return self.record(name, vs[-1])

    def record(self, name, version):
        """The :class:`CatalogRecord` for one exact version (any
        status); raises ``KeyError`` if absent or torn."""
        path = self._vdir(self._check_name(name), int(version))
        man = self._load_manifest(path)
        if man is None:
            raise KeyError(f"{name}@{version} is not in the catalog")
        return CatalogRecord(name, int(version), path, man)

    def get(self, name, version=None, verify=True):
        """Load ``(model, record)``. ``version=None`` resolves the
        newest published version; an explicit version loads any
        status (forensics on rejected versions included). ``verify``
        checks the pickled bytes against the manifest digest — a
        silently corrupted blob must not deserialize into serving."""
        if version is None:
            rec = self.latest(name)
            if rec is None:
                raise KeyError(
                    f"{name} has no published version in the catalog"
                )
        else:
            rec = self.record(name, version)
        with open(os.path.join(rec.path, _MODEL), "rb") as f:
            blob = f.read()
        if verify:
            digest = "sha256:" + hashlib.sha256(blob).hexdigest()
            if digest != rec.manifest.get("digest"):
                raise ValueError(
                    f"{rec.spec}: model blob digest {digest} does not "
                    f"match its manifest "
                    f"({rec.manifest.get('digest')}) — the stored "
                    "params are corrupt; restore from a replica or gc "
                    "the version"
                )
        return pickle.loads(blob), rec

    def load_models(self, names=None):
        """``[(name, model), ...]`` for the newest published version
        of each tenant — the bulk cold-load feed for
        :func:`~skdist_tpu.catalog.rollout.cold_load`. Tenants with no
        published version are skipped."""
        out = []
        for n in (self.names() if names is None else names):
            try:
                model, _ = self.get(n)
            except KeyError:
                continue
            out.append((n, model))
        return out

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _check_name(self, name):
        name = str(name)
        if (not name or name.startswith(".") or "/" in name
                or "\\" in name or "@" in name):
            raise ValueError(
                f"catalog name {name!r} must be non-empty and contain "
                "no '/', '\\\\', '@', or leading '.'"
            )
        return name

    def _vdir(self, name, version):
        return os.path.join(self.catalog_dir, name, str(int(version)))

    def _version_dirs(self, name):
        """Every numeric version directory on disk (valid or torn)."""
        base = os.path.join(self.catalog_dir, str(name))
        try:
            entries = os.listdir(base)
        except (FileNotFoundError, NotADirectoryError):
            return []
        out = []
        for ent in entries:
            try:
                out.append(int(ent))
            except ValueError:
                continue
        return out

    @staticmethod
    def _load_manifest(path):
        """The torn-state gate: any failure to read/parse/validate the
        manifest makes the version invisible (``None``), never an
        exception — crash debris must not take the catalog down."""
        try:
            with open(os.path.join(path, _MANIFEST)) as f:
                man = json.load(f)
            if not isinstance(man, dict):
                return None
            if int(man.get("format", -1)) > MANIFEST_FORMAT:
                return None  # from a future writer we cannot trust
            if not os.path.exists(os.path.join(path, _MODEL)):
                return None
            return man
        except (OSError, ValueError):
            return None
