"""
skdist_tpu.catalog: the tenant-lifecycle plane.

Owns the loop the serving tier (PR-13/16) deliberately left out:
train → publish → roll out → refresh → supersede, at catalog scale.

- :mod:`~skdist_tpu.catalog.store` — :class:`CatalogStore`, the
  durable, restart-survivable versioned model store (atomic
  dir-per-version publishes, lineage manifests, torn-state tolerance,
  pin/gc retention).
- :mod:`~skdist_tpu.catalog.refresh` — :class:`RefreshJob`,
  warm-started refits from fresh traffic published behind a quality
  gate (a regressed refit is stored ``rejected``, never rolled out).
- :mod:`~skdist_tpu.catalog.rollout` — :func:`cold_load` /
  :func:`rollout_records`, bulk placement onto engines and fleets
  (one bank generation per group, prewarm-before-swap, bank-aware
  sharded routing on fleets).

Lifecycle state machine (DESIGN.md "The living catalog"):
``trained → gated → published → rolled-out → superseded``, with
``rejected`` the gate's terminal siding.
"""

from .refresh import RefreshJob, RefreshResult
from .rollout import cold_load, rollout_records
from .store import CatalogRecord, CatalogStore

__all__ = [
    "CatalogStore",
    "CatalogRecord",
    "RefreshJob",
    "RefreshResult",
    "cold_load",
    "rollout_records",
]
