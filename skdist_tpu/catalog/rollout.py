"""
Catalog → serving: bulk cold-load and mid-traffic rollout.

The final leg of the lifecycle: published catalog versions become
served tenants. Two entry points, both built on the serving tier's
bulk staging (``register_many`` — K tenants behind ONE bank
generation per bank group, prewarm-before-swap, atomic cutover):

- :func:`cold_load` — bring a whole catalog (or a named subset) up on
  an empty engine or fleet in one bulk placement per precision tier.
  This is the restart path: a serving host reboots, the catalog
  replays, ``serve.bank_rebuilds`` grows by the number of bank
  GROUPS, not the number of tenants.

- :func:`rollout_records` — push refreshed versions
  (:class:`~skdist_tpu.catalog.refresh.RefreshResult` records, or raw
  :class:`~skdist_tpu.catalog.store.CatalogRecord`) onto a serving
  target mid-traffic. Rejected records are refused here AND invisible
  to :meth:`CatalogStore.latest` — belt and braces: a gate-rejected
  version cannot reach serving through any path in this module.

Targets duck-type: a fleet exposing ``rollout_many`` (bank-aware
sharded placement — ``ReplicaSet`` / ``ProcessReplicaSet``) or an
engine/registry exposing ``register_many``. ``rollout_swap`` spans
wrap every placement; ``catalog.bank_stagings`` counts the bulk
stagings performed.
"""

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

__all__ = ["cold_load", "rollout_records"]


def _stagings_counter():
    return obs_metrics.registry().counter(
        "catalog.bank_stagings",
        help="bulk bank stagings performed by catalog rollouts (one "
             "per serve_dtype group per target placement — vs one per "
             "TENANT on the per-model register path)",
    )


def cold_load(target, store, names=None, methods=("predict",),
              serve_dtype=None, **rollout_kwargs):
    """Bulk-load the newest published version of every catalog tenant
    (or the ``names`` subset) onto ``target``. Models group by their
    manifest's precision tier (``serve_dtype`` overrides it fleet-wide)
    and each tier stages as ONE bulk placement. Extra keyword
    arguments (``n_shards=``, ``replication=``) pass through to a
    fleet's ``rollout_many``. Returns ``{name: result}`` where result
    is the target's per-model handle (entry or version)."""
    models = store.load_models(names=names)
    if not models:
        return {}
    tiers = {}
    for name, model in models:
        tier = serve_dtype
        if tier is None:
            rec = store.latest(name)
            tier = (rec.manifest.get("serve_dtype", "float32")
                    if rec is not None else "float32")
        tiers.setdefault(tier, []).append((name, model))
    out = {}
    for tier, group in sorted(tiers.items()):
        out.update(_stage(target, group, methods, tier,
                          **rollout_kwargs))
    return out


def rollout_records(target, store, records, methods=("predict",),
                    **rollout_kwargs):
    """Roll explicit catalog records (refresh results included) onto
    ``target`` mid-traffic. Records whose status is not ``published``
    are skipped — the gate already stored them as rejected, and this
    is the second lock on the door. Returns ``{spec: result}`` for
    the records actually rolled out."""
    recs = []
    for r in records:
        rec = getattr(r, "record", r)   # RefreshResult -> its record
        if rec is None or isinstance(rec, Exception):
            continue
        if rec.status != "published":
            continue
        recs.append(rec)
    if not recs:
        return {}
    tiers = {}
    for rec in recs:
        model, _ = store.get(rec.name, rec.version)
        tiers.setdefault(
            rec.manifest.get("serve_dtype", "float32"), []
        ).append((rec.name, model))
    out = {}
    for tier, group in sorted(tiers.items()):
        staged = _stage(target, group, methods, tier, **rollout_kwargs)
        for name, result in staged.items():
            out[name] = result
    return out


def _stage(target, models, methods, serve_dtype, **rollout_kwargs):
    """One bulk placement of ``[(name, model), ...]`` on ``target``,
    dispatching on its surface; returns ``{name: result}``."""
    rollout_many = getattr(target, "rollout_many", None)
    if callable(rollout_many):
        # fleets emit their own rollout_swap span (it wraps the
        # per-replica placements individually)
        results = rollout_many(models, methods=methods,
                               serve_dtype=serve_dtype,
                               **rollout_kwargs)
        _stagings_counter().inc()
        return {name: res for (name, _), res in zip(models, results)}
    register_many = getattr(target, "register_many", None)
    if callable(register_many):
        if rollout_kwargs:
            raise TypeError(
                f"{type(target).__name__}.register_many takes no "
                f"placement options {sorted(rollout_kwargs)} — those "
                "are fleet (rollout_many) arguments"
            )
        with obs_trace.span(
            "rollout_swap",
            {"models": len(models), "serve_dtype": str(serve_dtype)}
            if obs_trace.enabled() else None,
        ):
            entries = register_many(models, methods=methods,
                                    serve_dtype=serve_dtype)
        _stagings_counter().inc()
        return {name: e for (name, _), e in zip(models, entries)}
    raise TypeError(
        f"{type(target).__name__} exposes neither rollout_many nor "
        "register_many — pass a ServingEngine, ModelRegistry, "
        "ReplicaSet, or ProcessReplicaSet"
    )
