"""
Continuous refresh: warm-started refits published behind a quality
gate.

The reference sk-dist deployments retrained their tenant fleets on a
scheduler — every model refit from scratch on yesterday's data, every
coefficient recomputed from zero, every result pushed to serving on
faith. :class:`RefreshJob` replaces that loop with three invariants:

- **warm-start from the parent**: a refresh loads the tenant's newest
  published version from the :class:`~skdist_tpu.catalog.store.
  CatalogStore` and seeds the refit with its coefficients
  (``fit(..., coef_init=, intercept_init=)``). Fresh traffic rarely
  moves a tenant's decision boundary far, so the L-BFGS/SGD solvers
  converge in a fraction of the cold iterations — the difference
  between "refresh the 100k-tenant catalog nightly" and "refresh what
  fits in the window". Streamed refits (``ChunkedDataset``) thread the
  same seed through the streaming drivers, so a tenant whose traffic
  outgrew host memory warm-starts identically.

- **gate before publish**: the refit scores on a holdout (explicit, or
  carved from the refresh data) against the PARENT's score on the same
  rows. A refit within ``gate_tol`` of its parent publishes; one that
  regresses further is still stored — ``status="rejected"``, full
  provenance, for forensics — but :meth:`CatalogStore.latest` never
  resolves it, so the rollout path cannot ship it. A bad data day
  demotes to "no-op refresh", never to "worse model in serving".

- **linear families first**: GBDT/forest tenants have no coefficient
  vector to seed, and their streamed refit is a different machine
  (ROADMAP item 4). Refreshing one raises immediately with the
  remedy, rather than silently cold-refitting at 10x the budget.

Counters (``/metrics``): ``catalog.refits``, ``catalog.publishes``,
``catalog.gate_rejects``.
"""

import numpy as np

from ..data import ChunkedDataset
from ..obs import metrics as obs_metrics

__all__ = ["RefreshJob", "RefreshResult"]


def _counter(name, help):
    return obs_metrics.registry().counter(name, help=help)


class RefreshResult:
    """One tenant's refresh verdict: the stored record plus the gate's
    arithmetic."""

    __slots__ = ("record", "parent_version", "parent_score",
                 "new_score", "published")

    def __init__(self, record, parent_version, parent_score, new_score,
                 published):
        self.record = record
        self.parent_version = parent_version
        self.parent_score = parent_score
        self.new_score = new_score
        self.published = published

    def __repr__(self):
        verdict = "published" if self.published else "rejected"
        return (f"RefreshResult({self.record.spec!r}, {verdict}, "
                f"score {self.new_score:.4f} vs parent "
                f"{self.parent_score:.4f})")


class RefreshJob:
    """Refit a tenant cohort from fresh traffic and publish behind the
    parity gate (module docstring).

    ``gate_tol`` is the allowed holdout-score regression vs the parent
    (``new >= parent - gate_tol`` publishes). ``holdout_frac`` carves
    the gate's holdout from the TAIL of the refresh data when the
    caller does not pass one explicitly — the newest rows, which is
    what the refreshed model will actually face. ``serve_dtype=None``
    inherits each parent's manifest tier."""

    def __init__(self, store, gate_tol=0.01, holdout_frac=0.2,
                 serve_dtype=None):
        self.store = store
        self.gate_tol = float(gate_tol)
        self.holdout_frac = float(holdout_frac)
        self.serve_dtype = serve_dtype
        if not (0.0 < self.holdout_frac < 1.0):
            raise ValueError(
                f"holdout_frac must be in (0, 1); got {holdout_frac}"
            )

    # ------------------------------------------------------------------
    def refresh(self, name, data, y=None, sample_weight=None,
                holdout=None):
        """Warm-refit one tenant from ``data`` (a
        :class:`~skdist_tpu.data.ChunkedDataset` for streamed refits,
        or an array with ``y``), gate, store, and return the
        :class:`RefreshResult`. The parent is the newest PUBLISHED
        version; a tenant with none raises ``KeyError`` (seed it with
        ``store.put`` first)."""
        parent, parent_rec = self.store.get(name)
        _counter(
            "catalog.refits",
            help="tenant refresh refits attempted by RefreshJob",
        ).inc()
        if not hasattr(parent, "coef_"):
            raise TypeError(
                f"{type(parent).__name__} has no coefficient vector to "
                "warm-start from — the catalog refresh loop covers the "
                "linear families (LogisticRegression, LinearSVC, "
                "SGDClassifier, Ridge, LinearRegression) today. For "
                "tree/GBDT tenants, refit cold with fit() and publish "
                "the result via CatalogStore.put(parent_version=...) "
                "until streamed GBDT refit lands (ROADMAP item 4)."
            )
        est = _clone_unfitted(parent)
        fit_data, fit_y, fit_sw, hold_X, hold_y = self._split(
            data, y, sample_weight, holdout
        )
        est.fit(fit_data, fit_y, sample_weight=fit_sw,
                coef_init=np.asarray(parent.coef_),
                intercept_init=np.asarray(parent.intercept_))
        new_score = float(est.score(hold_X, hold_y))
        parent_score = float(parent.score(hold_X, hold_y))
        published = new_score >= parent_score - self.gate_tol
        serve_dtype = (parent_rec.manifest.get("serve_dtype", "float32")
                       if self.serve_dtype is None else self.serve_dtype)
        record = self.store.put(
            name, est,
            parent_version=parent_rec.version,
            serve_dtype=serve_dtype,
            status="published" if published else "rejected",
            provenance={
                "refresh": True,
                "parent_version": parent_rec.version,
                "parent_score": parent_score,
                "new_score": new_score,
                "gate_tol": self.gate_tol,
                "n_holdout_rows": int(np.asarray(hold_y).shape[0]),
                "warm_started": True,
                "n_iter": int(getattr(est, "n_iter_", -1)),
            },
        )
        if published:
            _counter(
                "catalog.publishes",
                help="refreshed versions that passed the quality gate "
                     "and published to the catalog",
            ).inc()
        else:
            _counter(
                "catalog.gate_rejects",
                help="refreshed versions rejected by the quality gate "
                     "(stored with status=rejected, never rolled out)",
            ).inc()
        return RefreshResult(record, parent_rec.version, parent_score,
                             new_score, published)

    def refresh_cohort(self, items):
        """Refresh many tenants; ``items`` is an iterable of
        ``(name, data)`` / ``(name, data, y)`` tuples or kwargs dicts
        for :meth:`refresh`. Tenants fail independently — one bad
        tenant must not strand the rest of the cohort — and failures
        come back as the exception object in that tenant's slot."""
        out = []
        for item in items:
            kwargs = dict(item) if isinstance(item, dict) else None
            if kwargs is None:
                name, data = item[0], item[1]
                kwargs = {"name": name, "data": data}
                if len(item) > 2:
                    kwargs["y"] = item[2]
            try:
                out.append(self.refresh(**kwargs))
            except Exception as exc:
                out.append(exc)
        return out

    # ------------------------------------------------------------------
    def _split(self, data, y, sample_weight, holdout):
        """Resolve (fit-data, fit-y, fit-sw, holdout-X, holdout-y).

        With an explicit ``holdout=(X, y)`` the refit consumes ALL of
        ``data``. Otherwise the holdout is the TAIL fraction: for
        arrays a row split; for a ChunkedDataset the last block(s) are
        loaded as holdout while the refit streams the leading blocks
        (re-chunked view over the same on-disk/ in-memory blocks)."""
        if holdout is not None:
            hold_X, hold_y = holdout
            return data, y, sample_weight, np.asarray(hold_X), \
                np.asarray(hold_y)
        if isinstance(data, ChunkedDataset):
            n_blocks = data.n_blocks
            n_hold = max(1, int(round(n_blocks * self.holdout_frac)))
            if n_hold >= n_blocks:
                raise ValueError(
                    f"cannot carve a {self.holdout_frac:.0%} holdout "
                    f"from a {n_blocks}-block dataset; pass "
                    "holdout=(X, y) explicitly"
                )
            parts = [data.read_block(i, pad=False)
                     for i in range(n_blocks - n_hold, n_blocks)]
            hold_X = np.concatenate([p.X for p in parts])
            if parts[0].y is None:
                raise ValueError(
                    "refresh data has no labels; the gate needs y"
                )
            hold_y = np.concatenate([p.y for p in parts])
            head = [data.read_block(i, pad=False)
                    for i in range(n_blocks - n_hold)]
            fit = ChunkedDataset.from_arrays(
                np.concatenate([p.X for p in head]),
                y=np.concatenate([p.y for p in head]),
                sample_weight=(
                    np.concatenate([p.sw for p in head])
                    if head[0].sw is not None else None
                ),
                block_rows=data.block_rows,
            )
            return fit, None, None, hold_X, hold_y
        X = np.asarray(data)
        y = np.asarray(y)
        n = X.shape[0]
        n_hold = max(1, int(round(n * self.holdout_frac)))
        if n_hold >= n:
            raise ValueError(
                f"cannot carve a {self.holdout_frac:.0%} holdout from "
                f"{n} rows; pass holdout=(X, y) explicitly"
            )
        cut = n - n_hold
        sw = None if sample_weight is None \
            else np.asarray(sample_weight)[:cut]
        return X[:cut], y[:cut], sw, X[cut:], y[cut:]


def _clone_unfitted(est):
    """A fresh estimator with the parent's hyperparameters and none of
    its fitted state (sklearn ``clone`` semantics, without importing
    it at module level for the no-sklearn serving path)."""
    from sklearn.base import clone

    return clone(est)
