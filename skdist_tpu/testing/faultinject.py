"""
Deterministic fault injection for the fan-out data plane.

A :class:`FaultInjector` is installed into the ``parallel.faults``
seam (``with FaultInjector().at_round(2): ...`` or :func:`inject`) and
is consulted by the round loop at exactly two points:

- **dispatch** of every round (``round_dispatched``): planned
  transient / preemption / OOM / fatal faults RAISE here, where a real
  device failure would surface; ``hang`` sleeps (watchdog fodder);
  ``kill`` SIGKILLs the process (checkpoint-resume scenarios).
- **gather** of every round (``transform_output``): planned ``nan``
  injections poison chosen lanes of the gathered outputs — the
  observable signature of a numerically diverged task, exercising the
  lane-quarantine guard end to end.

Rounds are numbered by a process-wide DISPATCH ordinal starting at 0
when the injector is installed — retries consume ordinals too (the
re-dispatch of a failed round is the next ordinal), which is what
makes "this round fails once, then succeeds" expressible: a rule
fires at most ``times`` times, so the retried dispatch sails through.
Everything is host-side and deterministic: no randomness, no clocks in
the decision path, so an injected run's task outputs are bitwise
reproducible.

Two TARGETED scenarios ride the same ordinals for the elastic layer:
:meth:`FaultInjector.on_host` preempts a SPECIFIC mesh participant
(the raise at its ordinal plus a loss mark the
``ElasticMeshManager``'s probe reads until capacity "returns" N
dispatches later), and :meth:`FaultInjector.kill_replica` kills a
SPECIFIC serving replica when the ``ReplicaSet`` router dispatches a
chosen request ordinal — so "host 1 dies at round 2 and comes back
2 rounds later" and "replica 1 dies at request 40 under load" are
exact, replayable sentences rather than races.
"""

import os
import signal
import threading
import time

import numpy as np

from ..parallel import faults

__all__ = ["FaultInjector", "inject"]

#: fault kinds a rule may carry and the message each raises with —
#: phrased so ``faults.classify`` maps them exactly like the real thing
_RAISE_MESSAGES = {
    "transient": "UNAVAILABLE: injected transient fault (skdist faultinject)",
    "preempt": "injected fault: worker preempted (skdist faultinject)",
    "oom": "RESOURCE_EXHAUSTED: injected allocation failure "
           "(skdist faultinject)",
    "fatal": "injected fatal fault (skdist faultinject)",
}
_KINDS = set(_RAISE_MESSAGES) | {"hang", "kill", "nan"}


class _Rule:
    __slots__ = ("kind", "lanes", "sleep_s", "times", "message")

    def __init__(self, kind, lanes, sleep_s, times, message):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(have: {sorted(_KINDS)})")
        self.kind = kind
        self.lanes = tuple(int(i) for i in (lanes or (0,)))
        self.sleep_s = float(sleep_s)
        self.times = int(times)
        self.message = message or _RAISE_MESSAGES.get(kind, "")


class FaultInjector:
    """Deterministic per-round fault plan (see module docstring).

    Build a plan with :meth:`at_round` / :meth:`every` (chainable),
    then install it as a context manager. ``fired`` records every
    injection that actually happened as ``(ordinal, kind)`` — the
    assertion surface for tests and the smoke gate.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._exact = {}    # ordinal -> [_Rule, ...]
        self._every = []    # (period, _Rule)
        self.fired = []
        # elastic-mesh scenarios: ordinal -> [(participant,
        # restore_after_rounds or None)] armed when that ordinal
        # dispatches; participant -> restore_at ordinal (None = never)
        self._loss_plan = {}
        self._lost = {}
        # replica scenarios: request ordinal -> [replica indices] the
        # router must kill BEFORE dispatching that request
        self._replica_kills = {}
        # process-fleet scenarios (ProcessReplicaSet): request ordinal
        # -> [(replica, sig)] killed / [(replica, resume_after_s)]
        # SIGSTOPped before that request routes
        self._replica_proc_kills = {}
        self._replica_proc_stalls = {}
        # optional heartbeat probe driving lost_participants()
        self._hb_probe = None

    # ------------------------------------------------------------------
    # plan construction
    # ------------------------------------------------------------------
    def at_round(self, ordinal, kind="transient", lanes=None, sleep_s=0.0,
                 times=1, message=None):
        """Fire ``kind`` at dispatch ordinal ``ordinal`` (at most
        ``times`` times — with retries in play an exact ordinal fires
        once and the re-dispatch lands on a later ordinal)."""
        rule = _Rule(kind, lanes, sleep_s, times, message)
        self._exact.setdefault(int(ordinal), []).append(rule)
        return self

    def every(self, period, kind="transient", lanes=None, sleep_s=0.0,
              times=1, start=None, message=None):
        """Fire ``kind`` on every ``period``-th dispatch (ordinals
        ``period-1, 2*period-1, ...``, or ``start, start+period, ...``
        when ``start`` is given), at most ``times`` times per matching
        ordinal — the "fault on X% of rounds" knob."""
        rule = _Rule(kind, lanes, sleep_s, times, message)
        self._every.append((int(period), int(period) - 1 if start is None
                            else int(start), rule))
        return self

    def on_host(self, participant, at_round, restore_after=None,
                times=1):
        """Preempt a SPECIFIC mesh participant: at dispatch ordinal
        ``at_round`` a preemption raises (exactly like ``at_round(...,
        kind="preempt")``) AND participant ``participant`` is marked
        LOST — :meth:`lost_participants` (the
        ``ElasticMeshManager``'s default probe) reports it until
        capacity "returns" after ``restore_after`` further dispatch
        ordinals (None = never within this plan). This is what makes
        "host 1 is preempted at round k and comes back m rounds later"
        deterministically expressible — the round-ordinal preempt alone
        could not say WHICH participant died, so an elastic mesh had
        nothing concrete to shrink around."""
        self.at_round(int(at_round), kind="preempt", times=times)
        self._loss_plan.setdefault(int(at_round), []).append(
            (int(participant),
             None if restore_after is None else int(restore_after))
        )
        return self

    def kill_replica(self, replica, at_request, times=1):
        """Kill a SPECIFIC serving replica: when the ``ReplicaSet``
        router dispatches its ``at_request``-th request (0-based, the
        router's own deterministic ordinal), replica ``replica`` is
        killed abruptly (``close(drain=False)`` — queued futures fail,
        exactly like a process death) BEFORE the request routes. The
        router consults :meth:`replica_kills_due` on every request;
        ``times`` caps how many requests at that ordinal trigger it
        (>1 only matters with retries consuming request ordinals)."""
        del times  # one ordinal routes one request; kept for symmetry
        self._replica_kills.setdefault(int(at_request), []).append(
            int(replica)
        )
        return self

    def kill_replica_proc(self, replica, at_request, sig=signal.SIGKILL):
        """Kill a SPECIFIC serving replica PROCESS: when a
        ``ProcessReplicaSet`` router dispatches its ``at_request``-th
        request (0-based), replica ``replica``'s process group gets
        ``sig`` (default SIGKILL — the abrupt-death scenario a
        supervised fleet must absorb: queued futures on that replica
        fail, failover re-routes, the supervisor respawns). The
        process-boundary rendition of :meth:`kill_replica`."""
        self._replica_proc_kills.setdefault(int(at_request), []).append(
            (int(replica), int(sig))
        )
        return self

    def stall_replica_proc(self, replica, at_request,
                           resume_after_s=None):
        """SIGSTOP a replica process at request ordinal ``at_request``
        — the heartbeat-stall scenario: the process exists but answers
        nothing, so the supervisor must declare it dead on missed
        beats and SIGKILL+respawn it. ``resume_after_s`` schedules a
        SIGCONT (a stopped process dies to SIGKILL regardless)."""
        self._replica_proc_stalls.setdefault(int(at_request), []).append(
            (int(replica),
             None if resume_after_s is None else float(resume_after_s))
        )
        return self

    def with_heartbeat_probe(self, probe):
        """Drive :meth:`lost_participants` from a heartbeat probe (e.g.
        :class:`~skdist_tpu.parallel.mesh.HeartbeatFileProbe`): the
        probe's stale participants report lost IN ADDITION to any
        :meth:`on_host` plan — so elastic tests can express participant
        loss purely as "its heartbeat file went stale", the same signal
        production probes read."""
        self._hb_probe = probe
        return self

    # ------------------------------------------------------------------
    # runtime hooks (called by the round loop through the faults seam)
    # ------------------------------------------------------------------
    def _rules_for(self, ordinal):
        for rule in self._exact.get(ordinal, ()):
            yield rule
        for period, start, rule in self._every:
            if ordinal >= start and (ordinal - start) % period == 0:
                yield rule

    def round_dispatched(self):
        """Assign this dispatch its ordinal; raise/sleep/kill per plan.
        Returns the ordinal (the round loop tags the round with it so
        gather-side poisoning hits the right outputs)."""
        with self._lock:
            ordinal = self._count
            self._count += 1
            todo = [r for r in self._rules_for(ordinal) if r.times > 0]
            for rule in todo:
                rule.times -= 1
                self.fired.append((ordinal, rule.kind))
            for participant, restore_after in self._loss_plan.pop(
                    ordinal, ()):
                self._lost[participant] = (
                    None if restore_after is None
                    else self._count + restore_after
                )
                self.fired.append((ordinal, f"lost:{participant}"))
        for rule in todo:
            if rule.kind == "hang":
                time.sleep(rule.sleep_s)
            elif rule.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif rule.kind != "nan":  # nan fires at gather instead
                raise RuntimeError(rule.message)
        return ordinal

    def transform_output(self, ordinal, out):
        """Poison planned lanes of a gathered round's float leaves with
        NaN. ``ordinal`` is the tag ``round_dispatched`` returned for
        this round; non-``nan`` rules are a no-op here."""
        import jax

        nan_rules = [
            r for r in self._rules_for_fired(ordinal) if r.kind == "nan"
        ]
        if not nan_rules:
            return out
        lanes = sorted({i for r in nan_rules for i in r.lanes})

        def poison(leaf):
            arr = np.array(leaf)
            if not np.issubdtype(arr.dtype, np.floating):
                return arr
            for i in lanes:
                if i < arr.shape[0]:
                    arr[i] = np.nan
            return arr

        return jax.tree_util.tree_map(poison, out)

    def _rules_for_fired(self, ordinal):
        """nan rules consume their budget at DISPATCH (so ``times``
        means dispatches, consistently across kinds) — at gather we
        match the fired log, not the live budget."""
        with self._lock:
            fired_here = {k for o, k in self.fired if o == ordinal}
            if "nan" not in fired_here:
                return []
            return [r for r in self._rules_for(ordinal) if r.kind == "nan"]

    # ------------------------------------------------------------------
    # elastic-mesh / replica scenario hooks
    # ------------------------------------------------------------------
    def lost_participants(self):
        """Currently-lost mesh participants (the ``ElasticMeshManager``
        probe): a participant marked by :meth:`on_host` stays lost
        until the dispatch count reaches its restore ordinal — rounds
        are the clock, so "capacity returns after N more rounds" is
        exact and replayable."""
        with self._lock:
            lost = {
                p for p, restore_at in self._lost.items()
                if restore_at is None or self._count < restore_at
            }
            probe = self._hb_probe
        if probe is not None:
            lost = lost | set(probe())
        return lost

    def replica_kills_due(self, request_ordinal):
        """Replica indices the router must kill before dispatching its
        ``request_ordinal``-th request (consumed: each plan entry fires
        once). Records ``(request_ordinal, "kill_replica:<i>")`` in
        :attr:`fired`."""
        with self._lock:
            due = self._replica_kills.pop(int(request_ordinal), [])
            for i in due:
                self.fired.append(
                    (int(request_ordinal), f"kill_replica:{i}")
                )
            return due

    def replica_proc_kills_due(self, request_ordinal):
        """``(replica, sig)`` pairs the ``ProcessReplicaSet`` router
        must signal before dispatching its ``request_ordinal``-th
        request (consumed; fired as ``kill_replica_proc:<i>``)."""
        with self._lock:
            due = self._replica_proc_kills.pop(int(request_ordinal), [])
            for i, _sig in due:
                self.fired.append(
                    (int(request_ordinal), f"kill_replica_proc:{i}")
                )
            return due

    def replica_proc_stalls_due(self, request_ordinal):
        """``(replica, resume_after_s)`` pairs to SIGSTOP before
        dispatching that request (consumed; fired as
        ``stall_replica_proc:<i>``)."""
        with self._lock:
            due = self._replica_proc_stalls.pop(int(request_ordinal), [])
            for i, _resume in due:
                self.fired.append(
                    (int(request_ordinal), f"stall_replica_proc:{i}")
                )
            return due

    # ------------------------------------------------------------------
    @property
    def dispatch_count(self):
        with self._lock:
            return self._count

    def fired_kinds(self):
        with self._lock:
            return [k for _o, k in self.fired]

    def __enter__(self):
        self._prev = faults.set_injector(self)
        return self

    def __exit__(self, *exc):
        faults.set_injector(self._prev)
        return False


def inject(**kwargs):
    """One-rule convenience: ``with inject(ordinal=3, kind="nan",
    lanes=[1]): ...`` — sugar over ``FaultInjector().at_round``."""
    ordinal = kwargs.pop("ordinal", 0)
    return FaultInjector().at_round(ordinal, **kwargs)
