"""
Test-support utilities for skdist_tpu.

``skdist_tpu.testing.faultinject`` is the deterministic fault-injection
harness the fault-tolerance layer is certified with (unit tests +
``build_tools/fault_smoke.py``). Nothing here is imported by library
code paths except through the ``parallel.faults`` injector seam, which
is a single ``None`` check per round when no injector is installed.
"""

from .faultinject import FaultInjector, inject

__all__ = ["FaultInjector", "inject"]
