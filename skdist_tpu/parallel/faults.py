"""
Fault tolerance for the fan-out data plane.

The reference sk-dist inherited fault tolerance from Spark: a failed
inner fit was re-executed on another executor by lineage (RDD,
NSDI'12), so a transient device error cost one task, not the search.
The fan-out backend has no scheduler underneath it — this module is
that layer, in four parts shared by the round loop, the CV search, and
the serving engine:

1. **Taxonomy + retry** (:func:`classify`, :class:`RetryPolicy`): a
   typed classification of what a failed round means — transient XLA
   runtime errors and preemptions are retryable at round granularity
   (the round's inputs are immutable host slices, so a re-dispatch is
   bitwise identical); RESOURCE_EXHAUSTED keeps its dedicated
   shrink-and-resume machinery; everything else stays fail-loud.
   ``SKDIST_ROUND_RETRIES`` / ``SKDIST_RETRY_BACKOFF_MS`` are the
   knobs.

2. **Lane quarantine** (:func:`nonfinite_lanes`): a non-finite guard
   over batched outputs. A numerically diverging task poisons only its
   own lane of the vmapped program; the guard maps poisoned lanes to
   sklearn ``error_score`` semantics (search) or a
   ``FitFailedWarning`` (OvR/OvO) instead of letting NaN rank.
   ``SKDIST_FAULT_GUARD=0`` is the kill switch.

3. **Durable search checkpoints** (:class:`SearchCheckpoint`):
   completed (candidate x fold) results journaled host-side, keyed by
   the structural grid signature, so a killed multi-hour search
   resumes past its finished work. ``SKDIST_CHECKPOINT_DIR`` or
   ``fit(..., checkpoint_dir=...)`` opt in.

4. **Injection seam** (:func:`set_injector`): the deterministic hook
   ``skdist_tpu.testing.faultinject`` installs to raise/poison/hang at
   chosen rounds. ``None`` (the default) costs one attribute read per
   ROUND — nothing per task.

Serving reuses the same taxonomy for its dispatch watchdog and
per-version :class:`CircuitBreaker` (``serve.engine``).
"""

import json
import logging
import os
import threading
import time

import numpy as np

__all__ = [
    "TRANSIENT",
    "PREEMPTED",
    "OOM",
    "WATCHDOG",
    "FATAL",
    "classify",
    "is_retryable",
    "RetryPolicy",
    "WatchdogTimeout",
    "CircuitBreaker",
    "nonfinite_lanes",
    "guard_enabled",
    "SearchCheckpoint",
    "grid_signature",
    "resolve_checkpoint_dir",
    "set_injector",
    "active_injector",
    "log_suppressed",
    "snapshot",
    "reset_stats",
]

logger = logging.getLogger("skdist_tpu.faults")

# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

#: retryable device/runtime hiccup (XLA UNAVAILABLE/INTERNAL/ABORTED,
#: broken transport): the round's host inputs are intact, re-dispatch
TRANSIENT = "transient"
#: a worker/device was preempted: retryable, but device state (placed
#: shared args) must be assumed lost and re-placed first
PREEMPTED = "preempted"
#: RESOURCE_EXHAUSTED: NOT retried here — the round loop's dedicated
#: shrink-and-resume machinery owns this kind
OOM = "oom"
#: a dispatch exceeded its watchdog budget (serving taxonomy; the
#: offline round loop treats a raised WatchdogTimeout as retryable)
WATCHDOG = "watchdog"
#: everything else: user/code errors — never retried, never swallowed
FATAL = "fatal"


class WatchdogTimeout(RuntimeError):
    """A dispatch ran past its watchdog budget."""


#: message fragments marking a transient runtime failure. XLA runtime
#: errors surface as jaxlib XlaRuntimeError whose str() carries the
#: absl status code; matching the code strings avoids importing jaxlib
#: internals and also covers transport-level errors raised as plain
#: RuntimeErrors by the tunnel.
_TRANSIENT_MARKS = (
    "UNAVAILABLE",
    "ABORTED",
    "INTERNAL",
    "DATA_LOSS",
    "connection reset",
    "socket closed",
    "failed to connect",
    "Broken pipe",
)
_PREEMPT_MARKS = ("preempt", "PREEMPT", "worker has been restarted")


def classify(exc):
    """Map an exception to its fault kind (module constants).

    Order matters: RESOURCE_EXHAUSTED is checked first so the OOM
    resume machinery always wins (some runtimes phrase it
    "INTERNAL: ... RESOURCE_EXHAUSTED"), then preemption (its messages
    often also carry UNAVAILABLE), then the transient marks.
    """
    if isinstance(exc, WatchdogTimeout):
        return WATCHDOG
    msg = str(exc)
    if "RESOURCE_EXHAUSTED" in msg:
        return OOM
    if any(m in msg for m in _PREEMPT_MARKS):
        return PREEMPTED
    if any(m in msg for m in _TRANSIENT_MARKS):
        return TRANSIENT
    return FATAL


def is_retryable(kind):
    """Whether the round loop may re-dispatch on this fault kind."""
    return kind in (TRANSIENT, PREEMPTED, WATCHDOG)


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Bounded exponential backoff for round-granular retries.

    ``max_retries`` bounds CONSECUTIVE re-dispatches of one round (the
    counter resets when the task offset advances — progress proves the
    fault was transient); ``backoff_ms`` is the first delay, doubling
    per consecutive attempt up to ``max_backoff_ms``. Defaults come
    from ``SKDIST_ROUND_RETRIES`` (2) and ``SKDIST_RETRY_BACKOFF_MS``
    (50). ``max_retries=0`` disables retrying (every classified fault
    re-raises), which is also the forced policy on multi-process
    meshes — a locally caught exception cannot be re-synchronised with
    peers already inside the next collective.

    Jitter is OPT-IN (``jitter_ms`` / ``SKDIST_RETRY_JITTER_MS``,
    default 0): one process re-dispatching onto its own mesh has no
    thundering-herd peer, and the jitter-free default keeps the
    fault-injection matrix bitwise-checkable. A FLEET of replicas or
    hosts retrying against one shared resource (coordinator, storage,
    the recovering device pool itself) is exactly where synchronized
    retry storms come from — there, a uniform extra delay in
    ``[0, jitter_ms)`` per attempt decorrelates the herd. The jitter
    rides ON TOP of :meth:`delay_s` (which stays deterministic — it is
    what tests and log lines reason about); only the actual sleep
    moves.
    """

    __slots__ = ("max_retries", "backoff_ms", "max_backoff_ms", "_sleep",
                 "jitter_ms", "_rng")

    def __init__(self, max_retries=None, backoff_ms=None,
                 max_backoff_ms=5000.0, sleep=time.sleep,
                 jitter_ms=None, rng=None):
        if max_retries is None:
            max_retries = _env_int("SKDIST_ROUND_RETRIES", 2)
        if backoff_ms is None:
            backoff_ms = _env_float("SKDIST_RETRY_BACKOFF_MS", 50.0)
        if jitter_ms is None:
            jitter_ms = _env_float("SKDIST_RETRY_JITTER_MS", 0.0)
        self.max_retries = max(0, int(max_retries))
        self.backoff_ms = max(0.0, float(backoff_ms))
        self.max_backoff_ms = float(max_backoff_ms)
        self.jitter_ms = max(0.0, float(jitter_ms))
        self._sleep = sleep
        self._rng = rng  # lazily a random.Random; injectable for tests

    def delay_s(self, attempt):
        """Deterministic backoff before consecutive attempt ``attempt``
        (1-based) — excludes jitter by design (class docstring)."""
        ms = min(self.backoff_ms * (2.0 ** (attempt - 1)),
                 self.max_backoff_ms)
        return ms / 1e3

    def jitter_s(self):
        """One draw of the opt-in decorrelation delay: uniform in
        ``[0, jitter_ms)`` seconds; exactly 0.0 when jitter is off (the
        default — no RNG is even constructed, so injection runs stay
        bitwise-checkable)."""
        if self.jitter_ms <= 0.0:
            return 0.0
        if self._rng is None:
            import random

            self._rng = random.Random()
        return self._rng.uniform(0.0, self.jitter_ms) / 1e3

    def backoff(self, attempt):
        d = self.delay_s(attempt) + self.jitter_s()
        if d > 0:
            self._sleep(d)
        return d


def _env_int(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", name, raw)
        return default


def _env_float(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default


# ---------------------------------------------------------------------------
# counters (test/smoke observability) — backed by the process-wide
# telemetry registry (``skdist_tpu.obs.metrics``): one labeled family,
# ``faults.events{kind=...}``. record/snapshot/reset_stats stay the
# module's API; snapshot() is now a VIEW over the registry, so the same
# numbers surface through the Prometheus/JSON exporters with no second
# bookkeeping path.
# ---------------------------------------------------------------------------

_LOCK = threading.RLock()

#: the taxonomy of fault-layer events; an unknown name in record() is
#: a bug and raises (the old dict's KeyError contract)
FAULT_COUNTERS = (
    "rounds_retried",       # re-dispatches after a retryable fault
    "retries_exhausted",    # faults that ran out of policy budget
    "shared_replacements",  # shared-arg re-placements (preemption)
    "lanes_quarantined",    # tasks mapped to error_score by the guard
    "lanes_rung_killed",    # tasks retired early by an adaptive rung
    "suppressed",           # exceptions logged instead of swallowed
    "checkpoint_hits",      # tasks skipped because a journal had them
    "watchdog_trips",       # dispatches past their watchdog budget
    "elastic_shrinks",      # mesh rebuilt over survivors (preemption)
    "elastic_regrows",      # mesh re-grown after capacity returned
    "elastic_tasks_salvaged",  # tasks NOT re-run across an elastic
                               # shrink (journaled/gathered prefix)
    "replica_failovers",    # requests re-routed off a sick replica
    "shard_restages",       # catalog shards re-staged on a new holder
                            # after every assigned holder went down
    "replica_respawns",     # serving replicas drained + respawned
    "replica_proc_restarts",  # replica CHILD PROCESSES respawned by
                              # the procfleet supervisor
    "heartbeat_misses",     # supervisor heartbeats a replica missed
    "crash_loop_parks",     # replicas parked after N deaths in window
    "elastic_epoch_agreements",  # coordinated multi-process resumes
                                 # agreed (epoch, prefix, roster)
)


_EVENTS = None


def _events():
    global _EVENTS
    if _EVENTS is None:
        from ..obs import metrics as obs_metrics

        _EVENTS = obs_metrics.counter(
            "faults.events", help="fault-layer events by kind"
        )
    return _EVENTS


def record(counter, n=1):
    if counter not in FAULT_COUNTERS:
        raise KeyError(f"unknown fault counter {counter!r}")
    _events().inc(int(n), kind=counter)
    # every fault-layer event also lands in the flight recorder's
    # bounded ring: an incident file's last-seconds story is mostly
    # made of these (fault events are rare by construction — this is
    # one dict append, never I/O)
    from ..obs import flightrec

    flightrec.note("fault", event=counter, n=int(n))
    if counter == "retries_exhausted":
        # the round loop is about to fail loud: freeze the story now,
        # while the raising stack still exists
        flightrec.dump_incident("retries_exhausted")


def snapshot():
    # one children() read = one lock acquisition, so the returned
    # counters are mutually consistent (the old single-dict guarantee)
    kids = _events().children()
    return {
        k: int(kids.get((("kind", k),), 0)) for k in FAULT_COUNTERS
    }


def reset_stats():
    _events().reset()


_SUPPRESSED_SEEN = set()


def log_suppressed(where, exc, level=logging.WARNING):
    """The replacement for a bare ``except Exception: pass``: count and
    log what was swallowed. First occurrence per (site, exception type)
    logs at ``level``; repeats drop to DEBUG so a flaky probe cannot
    flood the log at fleet scale."""
    record("suppressed")
    key = (where, type(exc).__name__)
    with _LOCK:
        first = key not in _SUPPRESSED_SEEN
        if first:
            _SUPPRESSED_SEEN.add(key)
    logger.log(
        level if first else logging.DEBUG,
        "suppressed %s in %s: %s", type(exc).__name__, where, exc,
    )


# ---------------------------------------------------------------------------
# lane quarantine
# ---------------------------------------------------------------------------

def guard_enabled():
    """The non-finite lane guard is ON by default;
    ``SKDIST_FAULT_GUARD=0`` is the kill switch (e.g. for workloads
    whose legitimate outputs contain inf)."""
    return os.environ.get("SKDIST_FAULT_GUARD", "").strip().lower() not in (
        "0", "false", "no",
    )


def nonfinite_lanes(tree):
    """Boolean mask over the leading (task) axis marking lanes with ANY
    non-finite value in ANY leaf, or None when everything is finite
    (the fast path: one ``np.isfinite().all()`` per leaf, no mask
    allocation). Host-side numpy on already-gathered outputs — adds no
    device work and no compiles."""
    import jax

    mask = None
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        finite = np.isfinite(arr)
        if finite.all():
            continue
        lane_bad = ~finite.reshape(arr.shape[0], -1).all(axis=1)
        mask = lane_bad if mask is None else (mask | lane_bad)
    return mask


# ---------------------------------------------------------------------------
# circuit breaker (serving: per model-version dispatch health)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Per-key consecutive-failure circuit breaker (serving taxonomy).

    A key (the serving engine uses ``name@version``) opens after
    ``threshold`` consecutive classified faults; while open,
    :meth:`allow` rejects immediately — the engine turns that into a
    typed ``CircuitOpen`` so callers shed load onto a healthy version
    instead of queueing against a sick one. After ``cooldown_s`` the
    breaker goes half-open: ONE probe request is admitted, and its
    outcome closes or re-opens the circuit. Thread-safe; fully
    in-memory.
    """

    def __init__(self, threshold=3, cooldown_s=30.0, clock=time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        # key -> [consecutive_failures, opened_at or None,
        #         probe_started_at or None]
        self._state = {}

    def _ent(self, key):
        ent = self._state.get(key)
        if ent is None:
            ent = self._state[key] = [0, None, None]
        return ent

    def allow(self, key):
        """True if a request for ``key`` may proceed (closed circuit,
        or the single half-open probe). A probe whose outcome was never
        reported (e.g. its request was shed for an unrelated reason
        before dispatch) expires after another cooldown, so an
        abandoned probe cannot latch the circuit open forever."""
        with self._lock:
            ent = self._ent(key)
            now = self._clock()
            if ent[1] is None:
                return True
            if now - ent[1] < self.cooldown_s:
                return False
            if ent[2] is not None and now - ent[2] < self.cooldown_s:
                return False  # a live probe is already in flight
            ent[2] = now
            return True

    def record_success(self, key):
        with self._lock:
            self._state[key] = [0, None, None]

    def record_failure(self, key, kind=FATAL):
        """Returns True when this failure OPENED the circuit."""
        with self._lock:
            ent = self._ent(key)
            ent[0] += 1
            ent[2] = None
            if ent[1] is not None:
                # failed half-open probe: stay open, restart cooldown
                ent[1] = self._clock()
                return False
            if ent[0] >= self.threshold:
                ent[1] = self._clock()
                return True
            return False

    def state(self, key):
        """'closed' | 'open' | 'half-open' for observability."""
        with self._lock:
            ent = self._state.get(key)
            if ent is None or ent[1] is None:
                return "closed"
            if self._clock() - ent[1] >= self.cooldown_s:
                return "half-open"
            return "open"

    def states(self):
        with self._lock:
            keys = list(self._state)
        return {k: self.state(k) for k in keys}


# ---------------------------------------------------------------------------
# durable search checkpoints
# ---------------------------------------------------------------------------

def resolve_checkpoint_dir(explicit=None):
    """The checkpoint directory: the explicit ``fit`` argument wins,
    else ``SKDIST_CHECKPOINT_DIR``, else None (checkpointing off)."""
    if explicit:
        return str(explicit)
    env = os.environ.get("SKDIST_CHECKPOINT_DIR", "").strip()
    return env or None


def _digest_update_array(h, arr):
    """Feed an array's identity into a hash: shape + dtype always, and
    a bounded byte sample (head + tail slabs) so signatures stay O(MB)
    even for multi-GB training sets. A sampled signature can collide
    only for arrays agreeing on shape, dtype, and both slabs — at
    which point resuming into the journal is the user mixing
    deliberately near-identical data, not an accident the full hash
    would catch either."""
    arr = np.ascontiguousarray(arr)
    h.update(repr((arr.shape, str(arr.dtype))).encode())
    raw = arr.view(np.uint8).reshape(-1)
    slab = 1 << 20
    if raw.nbytes <= 2 * slab:
        h.update(raw.tobytes())
    else:
        h.update(raw[:slab].tobytes())
        h.update(raw[-slab:].tobytes())


def data_digest(X):
    """Stable digest of a training input (dense, pandas, scipy sparse,
    or any object exposing ``content_digest()`` — e.g. a
    ``ChunkedDataset``, whose digest covers its meta + head/tail block
    samples without materialising the out-of-core matrix) for the grid
    signature."""
    import hashlib

    digest = getattr(X, "content_digest", None)
    if callable(digest):
        return str(digest())
    h = hashlib.blake2b(digest_size=16)
    if hasattr(X, "values") and not isinstance(X, np.ndarray):
        X = X.values
    if hasattr(X, "data") and hasattr(X, "indptr"):  # CSR/CSC
        h.update(repr((type(X).__name__, X.shape)).encode())
        _digest_update_array(h, np.asarray(X.data))
        _digest_update_array(h, np.asarray(X.indptr))
    else:
        arr = np.asarray(X)
        if arr.dtype == object:
            # same head+tail sampling contract as the dense slabs in
            # _digest_update_array: shape always, then both ends, so a
            # regenerated tail (or truncation) changes the signature
            h.update(repr((arr.shape,)).encode())
            flat = arr.reshape(-1)
            if flat.size <= 128:
                h.update(repr(flat.tolist()).encode())
            else:
                h.update(repr(flat[:64].tolist()).encode())
                h.update(repr(flat[-64:].tolist()).encode())
        else:
            _digest_update_array(h, arr)
    return h.hexdigest()


def grid_signature(*parts):
    """Hex digest of the STRUCTURAL identity of one search: estimator
    class, candidate params, CV geometry, scoring config, data digests
    — anything that changes the meaning of task id ``t``. Same recipe
    as the compile cache's structural keys (PR-1): canonical reprs,
    never object identities, so the signature survives a process
    restart."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()


class SearchCheckpoint:
    """Append-only journal of completed (candidate x fold) tasks.

    One JSONL file per grid signature under ``checkpoint_dir``; each
    line is ``{"t": task_id, "r": {score dict}}``. Opening loads every
    complete line (a half-written tail from a SIGKILL mid-append is
    dropped, not fatal) into :attr:`completed`; :meth:`record` appends
    + flushes, so what a killed process loses is bounded by one round.
    Floats ride JSON's shortest-round-trip repr — reloaded scores are
    bitwise what was journaled. Thread-safe (the host fan-out records
    from worker threads).
    """

    def __init__(self, checkpoint_dir, signature):
        self.signature = str(signature)
        self.path = os.path.join(
            checkpoint_dir, f"skdist-ckpt-{self.signature}.jsonl"
        )
        self._lock = threading.Lock()
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.completed = {}
        self._load()
        self._fh = open(self.path, "a", encoding="utf-8")

    def _load(self):
        if not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                    self.completed[int(row["t"])] = row["r"]
                except (ValueError, KeyError, TypeError):
                    # torn tail write from a kill mid-append: the task
                    # simply reruns
                    continue
        if self.completed:
            record("checkpoint_hits", len(self.completed))
            logger.info(
                "checkpoint %s: resuming past %d completed tasks",
                self.path, len(self.completed),
            )

    def record(self, task_id, scores):
        """Journal one completed task (scores: flat dict of floats)."""
        row = json.dumps(
            {"t": int(task_id), "r": {k: float(v) for k, v in scores.items()}}
        )
        with self._lock:
            self.completed[int(task_id)] = scores
            self._fh.write(row + "\n")
            self._fh.flush()

    def record_many(self, pairs):
        for task_id, scores in pairs:
            self.record(task_id, scores)

    def close(self):
        with self._lock:
            try:
                self._fh.close()
            except Exception as exc:
                log_suppressed("SearchCheckpoint.close", exc,
                               level=logging.DEBUG)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# injection seam
# ---------------------------------------------------------------------------

_INJECTOR = None


def set_injector(inj):
    """Install (or with None, remove) the process-wide fault injector
    consulted by the round loop. Test/harness API — see
    ``skdist_tpu.testing.faultinject``. Returns the previous one."""
    global _INJECTOR
    prev = _INJECTOR
    _INJECTOR = inj
    return prev


def active_injector():
    return _INJECTOR
