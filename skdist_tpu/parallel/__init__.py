"""
Fan-out runtime: the TPU-native replacement for Spark's
``sc.parallelize(...).map(fn).collect()`` + ``sc.broadcast`` idiom that
every reference estimator is built on (reference ``search.py:411-437``,
``multiclass.py:316-331``, ``ensemble.py:304-322``).
"""

from . import compile_cache
from .backend import (
    BatchedPlan,
    LocalBackend,
    TPUBackend,
    TaskBackend,
    get_value,
    parse_partitions,
    prefers_host_engine,
    resolve_backend,
    row_sharded_specs,
)
from .compile_cache import enable_disk_cache, structural_key

__all__ = [
    "TaskBackend",
    "LocalBackend",
    "TPUBackend",
    "BatchedPlan",
    "resolve_backend",
    "parse_partitions",
    "prefers_host_engine",
    "get_value",
    "row_sharded_specs",
    "compile_cache",
    "enable_disk_cache",
    "structural_key",
]
