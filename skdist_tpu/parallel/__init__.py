"""
Fan-out runtime: the TPU-native replacement for Spark's
``sc.parallelize(...).map(fn).collect()`` + ``sc.broadcast`` idiom that
every reference estimator is built on (reference ``search.py:411-437``,
``multiclass.py:316-331``, ``ensemble.py:304-322``).
"""

from . import compile_cache, faults
from .backend import (
    BatchedPlan,
    BlockFeeder,
    IterativeKernelSpec,
    IterativePlan,
    LocalBackend,
    RungController,
    StreamPlan,
    TPUBackend,
    TaskBackend,
    compaction_enabled,
    get_value,
    iterative_chunk_size,
    iterative_fit_supported,
    parse_partitions,
    prefers_host_engine,
    resolve_backend,
    resolve_slice_iters,
    row_sharded_specs,
    tree_nbytes,
)
from .compile_cache import enable_disk_cache, structural_key
from .mesh import (
    ElasticMeshManager,
    STREAM_BLOCK_RULES,
    match_partition_rules,
)

__all__ = [
    "TaskBackend",
    "LocalBackend",
    "TPUBackend",
    "ElasticMeshManager",
    "STREAM_BLOCK_RULES",
    "match_partition_rules",
    "BatchedPlan",
    "BlockFeeder",
    "StreamPlan",
    "IterativeKernelSpec",
    "IterativePlan",
    "RungController",
    "resolve_backend",
    "parse_partitions",
    "prefers_host_engine",
    "compaction_enabled",
    "resolve_slice_iters",
    "iterative_fit_supported",
    "iterative_chunk_size",
    "get_value",
    "row_sharded_specs",
    "tree_nbytes",
    "compile_cache",
    "enable_disk_cache",
    "structural_key",
    "faults",
]
