"""
Persistent cross-process compile cache + process-wide kernel caches.

Compilation is the dominant non-compute cost of the fan-out hot path
(BENCH_r05: quick shapes 3.99 s cold vs 0.42 s warm — ~90% of cold
wall is XLA compilation; the full 96×5 grid pays ~12 s of it). This
module concentrates every layer of compile reuse in one place:

1. **In-process memo caches** with *structural* keys. Kernel builders
   return fresh closures, and ``jax.jit`` keys its own cache on
   function identity — so a fresh closure per fit silently recompiles
   an identical program. Callers therefore pass a ``cache_key`` built
   from the estimator class qualname + static/meta signature (+ any
   shape constants the closure captures); two closures with the same
   structural key share one traced/compiled function. Three tiers:

   - kernel memo (``kernel_memo``): built Python closures
     (``models/linear._KERNEL_CACHE``, ``distribute/search``'s cv
     kernels) keyed on semantic signature;
   - jit memo (``jit_vmapped``): ``jit(vmap(kernel))`` per
     (structural key, static args, shardings);
   - AOT memo (``aot_executable``): ``fn.lower(...).compile()``
     executables per (jit entry, shared shape signature, chunk).

2. **On-disk XLA compilation cache** (``enable_disk_cache``): points
   ``jax_compilation_cache_dir`` at a directory so *repeated service
   processes* skip XLA compilation entirely — the cold-start killer
   for short-lived workers. Opt in per backend
   (``TPUBackend(compile_cache_dir=...)``) or process-wide via the
   ``SKDIST_COMPILE_CACHE_DIR`` environment variable. Entries key on
   the serialized HLO + compile flags + jaxlib version, so a cache
   directory is safe to share between processes and survives code
   edits that do not change the compiled program.

3. **Counters** (``snapshot()``): hits/misses per tier plus cumulative
   lowering/compile wall time, so benchmarks and tests can *see* the
   cold-vs-warm gap instead of inferring it from wall clock.

Thread safety: counters and memo insertion take a module lock; the
underlying dicts are plain (reads are GIL-atomic, and double-building
a cache entry is benign — last writer wins, both entries are correct).
"""

import os
import threading
import time
import warnings

__all__ = [
    "enable_disk_cache",
    "disk_cache_dir",
    "structural_key",
    "kernel_memo",
    "jit_vmapped",
    "aot_executable",
    "prewarm",
    "snapshot",
    "scoped_misses",
    "last_stats",
    "reset_stats",
    "clear_memos",
]

from ..obs import trace as _trace

#: environment opt-in for the on-disk XLA compilation cache
CACHE_DIR_ENV = "SKDIST_COMPILE_CACHE_DIR"

_LOCK = threading.RLock()

#: the counter kinds of the compile plane — billed into the telemetry
#: registry (``skdist_tpu.obs.metrics``) as ``compile.events{kind=...}``
#: plus a float ``compile.lower_time_s`` wall accumulator; snapshot()
#: below is a VIEW over the registry, so the same numbers surface in
#: the Prometheus/JSON exporters with no second bookkeeping path
_COUNTER_KINDS = (
    "kernel_hits",
    "kernel_misses",
    "jit_hits",
    "jit_misses",
    "aot_hits",
    "aot_misses",
    # the on-disk EXPORT layer (serialized AOT programs; skips Python
    # tracing in warm-disk processes): file served / file written
    "aot_export_hits",
    "aot_export_writes",
)

#: jit(vmap(kernel)) entries: (structural-or-identity key, static args,
#: shardings) -> jitted fn
_JIT_CACHE = {}
#: AOT executables: (jit fn, shared shape sig, chunk) -> compiled
_AOT_CACHE = {}
#: built kernel closures: namespaced semantic key -> closure
_KERNEL_MEMO = {}
#: jit fn -> (process-stable key string, donate) for entries built with
#: a structural cache_key — the export disk layer's filename basis
_JIT_EXPORT_KEY = {}

_DISK_DIR = None
_ENV_CHECKED = False


# ---------------------------------------------------------------------------
# on-disk XLA compilation cache
# ---------------------------------------------------------------------------

def enable_disk_cache(path=None):
    """Point JAX's persistent compilation cache at ``path`` (or the
    ``SKDIST_COMPILE_CACHE_DIR`` environment variable when ``path`` is
    None). Returns the active directory, or None when neither is set.

    Idempotent; the first caller wins for the lifetime of the process
    (JAX's cache config is global — re-pointing it mid-process would
    split warm state across directories, so a conflicting second path
    raises). Thresholds are dropped to cache-everything: a service
    process's cold start pays for EVERY kernel, not only the slow ones.
    """
    global _DISK_DIR
    with _LOCK:
        if path is None:
            path = os.environ.get(CACHE_DIR_ENV) or None
        if path is None:
            return _DISK_DIR
        path = os.path.abspath(path)
        if _DISK_DIR is not None:
            if _DISK_DIR != path:
                raise ValueError(
                    "the persistent compile cache is already at "
                    f"{_DISK_DIR!r}; JAX's cache config is process-global "
                    f"and cannot be re-pointed to {path!r}"
                )
            return _DISK_DIR
        import jax

        # the cache backend skips a directory it cannot open; create it
        # up front so the very first compile already writes through
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        for knob, value in (
            ("jax_persistent_cache_min_entry_size_bytes", -1),
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ):
            try:
                jax.config.update(knob, value)
            except Exception:  # pragma: no cover - older jax w/o the knob
                pass
        try:
            # the export layer will need it; importing now keeps its
            # ~0.3 s module-exec cost out of the first timed fit
            from jax import export as _export  # noqa: F401
        except Exception:  # pragma: no cover - jax without jax.export
            pass
        _DISK_DIR = path
        return _DISK_DIR


def maybe_enable_from_env():
    """Lazily honour ``SKDIST_COMPILE_CACHE_DIR`` once per process —
    called from the compile paths so a bare env var works without any
    backend constructor argument (service launchers set env, not code).
    """
    global _ENV_CHECKED
    if _ENV_CHECKED:
        return _DISK_DIR
    with _LOCK:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            if _DISK_DIR is None and os.environ.get(CACHE_DIR_ENV):
                enable_disk_cache()
    return _DISK_DIR


def disk_cache_dir():
    """The active on-disk cache directory, or None."""
    return _DISK_DIR


# ---------------------------------------------------------------------------
# structural keys + counters
# ---------------------------------------------------------------------------

_CLS_CODE_TOKENS = None


def _cls_code_token(cls):
    """Digest of a class's kernel-builder bytecode (inherited methods
    included). Part of every structural key: a module-qualified NAME
    alone would let an in-process class redefinition (REPL/notebook
    re-execution with edited kernel math, same qualname) silently
    serve the old class's compiled kernel. Bytecode is deterministic
    for identical source under one Python version, so the token stays
    process-stable for the export disk layer while distinguishing
    redefinitions. Memoised per class object (weakly — REPL classes
    must be collectable)."""
    global _CLS_CODE_TOKENS
    if _CLS_CODE_TOKENS is None:
        import weakref

        _CLS_CODE_TOKENS = weakref.WeakKeyDictionary()
    token = _CLS_CODE_TOKENS.get(cls)
    if token is None:
        import hashlib

        import types

        h = hashlib.sha256()

        def hash_code(code):
            h.update(code.co_code)
            for const in code.co_consts:
                if isinstance(const, types.CodeType):
                    # recurse into nested closures' bytecode: their
                    # repr() embeds per-process memory addresses, which
                    # would make the token differ in every process and
                    # silently defeat the cross-process export layer
                    hash_code(const)
                else:
                    h.update(repr(const).encode())

        for name in sorted(dir(cls)):
            # every _build_* method participates: kernel math also
            # lives in the shared _build_fit_problem /
            # _build_fit_slice_kernels builders the sliced-solver
            # variants are generated from
            if name.startswith("_build_"):
                fn = getattr(cls, name, None)
                code = getattr(getattr(fn, "__func__", fn), "__code__", None)
                if code is not None:
                    h.update(name.encode())
                    hash_code(code)
        token = h.hexdigest()[:12]
        _CLS_CODE_TOKENS[cls] = token
    return token


def structural_key(family, est_cls, *parts):
    """Stable cache key for a kernel closure's *semantics*.

    ``family`` names the fan-out call site ("cv", "ovr", "predict",
    ...); ``est_cls`` is the estimator class (stored as
    module-qualified name + kernel-builder bytecode token, so the key
    survives reload/re-import, is identical across processes, AND
    distinguishes an in-process redefinition with edited kernel math);
    ``parts`` must capture EVERYTHING the closure bakes in beyond its
    argument shapes — static config, meta signature, scorer names,
    captured shape constants. Two closures with equal structural keys
    are promised interchangeable.
    """
    if isinstance(est_cls, type):
        est_cls = (f"{est_cls.__module__}.{est_cls.__qualname__}",
                   _cls_code_token(est_cls))
    return (family, est_cls) + tuple(parts)


_FAMILIES = None


def _families():
    """(events counter, lower-time counter, scoped-miss counter) —
    registry handles, built once."""
    global _FAMILIES
    if _FAMILIES is None:
        from ..obs import metrics as obs_metrics

        _FAMILIES = (
            obs_metrics.counter(
                "compile.events", help="compile-cache tier hits/misses"
            ),
            obs_metrics.counter(
                "compile.lower_time_s",
                help="wall seconds building/lowering/compiling on misses",
            ),
            obs_metrics.counter(
                "compile.scoped_misses",
                help="compile-shaped misses attributed to an active "
                     "obs.metrics.compile_scope (serving engines)",
            ),
        )
    return _FAMILIES


def _record(counter, dt=0.0):
    events, lower, scoped = _families()
    events.inc(1, kind=counter)
    if dt:
        lower.inc(float(dt))
    if counter.endswith("_misses"):
        # scoped attribution: a serving engine's dispatch threads tag
        # themselves (obs.metrics.compile_scope) so compiles THEY cause
        # are separable from concurrent non-serving work — the basis of
        # ServingStats.compiles_after_warmup's per-engine delta
        from ..obs import metrics as obs_metrics

        tag = obs_metrics.current_scope()
        if tag is not None:
            scoped.inc(1, scope=tag)


def scoped_misses(tag):
    """Compile-shaped misses billed while ``compile_scope(tag)`` was
    active on the recording thread — the per-engine counter
    ``ServingStats.compiles_after_warmup`` snapshots."""
    return int(_families()[2].get(scope=str(tag)))


def snapshot():
    """Current counters (plus the disk cache dir), as a plain dict —
    a view over the telemetry registry's ``compile.*`` families. One
    ``children()`` read per family (single lock acquisition), so the
    event counters are mutually consistent within the snapshot."""
    events, lower, _scoped = _families()
    kids = events.children()
    out = {
        k: int(kids.get((("kind", k),), 0)) for k in _COUNTER_KINDS
    }
    out["lower_time_s"] = round(float(lower.get()), 4)
    out["disk_cache_dir"] = _DISK_DIR
    return out


def last_stats():
    """Alias of :func:`snapshot` — the name the compaction tests/smoke
    read when asserting "no recompile after warmup" (counter deltas
    between two snapshots around the flags-only slice loop)."""
    return snapshot()


def reset_stats():
    """Zero the counters (memo contents and disk config are kept).
    Scoped-miss attribution resets too — engines holding a warm mark
    across a reset re-baseline on their next ``mark_warm``."""
    for fam in _families():
        fam.reset()


def clear_memos():
    """Drop every in-process memo (tests; frees compiled executables)."""
    with _LOCK:
        _JIT_CACHE.clear()
        _AOT_CACHE.clear()
        _KERNEL_MEMO.clear()
        _JIT_EXPORT_KEY.clear()


# ---------------------------------------------------------------------------
# tier 1: kernel closures
# ---------------------------------------------------------------------------

def kernel_memo(key, build):
    """Return the memoised kernel closure for ``key``, building (and
    timing) it on first use. ``key`` must be namespaced by the caller
    (e.g. via :func:`structural_key`)."""
    fn = _KERNEL_MEMO.get(key)
    if fn is not None:
        _record("kernel_hits")
        return fn
    t0 = time.perf_counter()
    with _trace.span("compile", {"tier": "kernel"}
                     if _trace.enabled() else None):
        fn = build()
    _record("kernel_misses", time.perf_counter() - t0)
    with _LOCK:
        return _KERNEL_MEMO.setdefault(key, fn)


# ---------------------------------------------------------------------------
# tier 2: jit(vmap(kernel))
# ---------------------------------------------------------------------------

def jit_vmapped(kernel, static_args, task_sharding=None,
                shared_shardings=None, cache_key=None, donate_tasks=False):
    """jit(vmap(kernel)) with the task axis mapped; memoised.

    ``kernel(shared_args, one_task_args, **static)`` → pytree of arrays.
    ``shared_shardings`` may be a single sharding (replicated) or a
    pytree mirroring the shared args (row-sharded 'data' leaves).

    ``cache_key`` (see :func:`structural_key`) replaces closure
    identity in the memo key so per-call closures still reuse one
    traced program; without it the kernel object itself keys the entry
    (safe default — distinct closures never alias).

    ``donate_tasks=True`` donates the task-slice argument's buffers to
    the computation: each round's input chunk is freshly placed and
    never reused, so XLA may overwrite it in place — reclaiming one
    round's task-argument HBM for outputs/temps.
    """
    import jax

    maybe_enable_from_env()
    static_args = tuple(sorted((static_args or {}).items()))
    # NamedSharding hashes by (mesh, spec): distinct meshes/device sets
    # must never share a compiled fn. Sharding pytrees are flattened to
    # a hashable key.
    shared_leaves, shared_def = jax.tree_util.tree_flatten(shared_shardings)
    key = (cache_key or kernel, static_args, task_sharding,
           tuple(shared_leaves), shared_def, bool(donate_tasks))
    fn = _JIT_CACHE.get(key)
    if fn is not None:
        _record("jit_hits")
        return fn
    t0 = time.perf_counter()
    static = dict(static_args)

    def mapped(shared, tasks):
        return jax.vmap(lambda t: kernel(shared, t, **static))(tasks)

    jit_kwargs = {"donate_argnums": (1,)} if donate_tasks else {}
    with _trace.span("compile",
                     {"tier": "jit", "key": repr(cache_key)[:120]}
                     if _trace.enabled() else None):
        if task_sharding is not None:
            fn = jax.jit(
                mapped,
                in_shardings=(shared_shardings, task_sharding),
                out_shardings=task_sharding,
                **jit_kwargs,
            )
        else:
            fn = jax.jit(mapped, **jit_kwargs)
    _record("jit_misses", time.perf_counter() - t0)
    with _LOCK:
        fn = _JIT_CACHE.setdefault(key, fn)
        if cache_key is not None and fn not in _JIT_EXPORT_KEY:
            # a structural key makes the entry PROCESS-STABLE: record
            # the string form (+ mesh topology) the export disk layer
            # uses as its filename basis. Identity-keyed entries (no
            # cache_key) are not stable across processes and never
            # reach the export layer.
            _JIT_EXPORT_KEY[fn] = (
                repr((cache_key, static_args,
                      _sharding_desc(task_sharding),
                      tuple(_sharding_desc(s) for s in shared_leaves),
                      bool(donate_tasks))),
                bool(donate_tasks),
            )
        return fn


def _sharding_desc(s):
    """Process-stable description of a sharding (mesh topology + spec),
    NOT its object repr (which may embed per-process device ids)."""
    try:
        if s is None:
            return None
        mesh = s.mesh
        kinds = (
            str(mesh.devices.flat[0].device_kind)
            if mesh.devices.size else ""
        )
        return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
                kinds, repr(s.spec))
    except Exception:
        return repr(s)


# ---------------------------------------------------------------------------
# tier 3: AOT executables
# ---------------------------------------------------------------------------

def aot_executable(fn, shared_args, task_like, n_chunk, shared_sig=None):
    """AOT-compile ``fn`` for a task chunk of ``n_chunk`` (memoised).

    ``fn`` must be an AOT-capable jitted function (``.lower``);
    ``task_like`` supplies per-task leaf shapes/dtypes (its leading
    axis is replaced by ``n_chunk``). The memo keys on the jit entry
    itself — jitted fns are memoised structurally in tier 2, so this
    composes to the same lifetime jit's own compilation cache would
    have had, plus explicit counters and the on-disk write-through.
    The task leaves' TRAILING shapes are part of the key: one jit
    entry legitimately serves several task widths (jit re-traces by
    shape; e.g. sparse predict's packed nnz width), and an executable
    compiled for one width must never be served for another.
    """
    import jax

    if shared_sig is None:
        shared_sig = shape_sig(shared_args)
    task_sig = tuple(
        (tuple(l.shape[1:]), str(l.dtype))
        for l in jax.tree_util.tree_leaves(task_like)
    )
    key = (fn, shared_sig, task_sig, n_chunk)
    comp = _AOT_CACHE.get(key)
    if comp is not None:
        _record("aot_hits")
        return comp
    t0 = time.perf_counter()
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            (n_chunk,) + tuple(a.shape[1:]), a.dtype
        ),
        task_like,
    )
    with warnings.catch_warnings():
        # donated task leaves too small/oddly-shaped to alias an output
        # (scalar hypers, split ids) are expected and harmless — the
        # donation exists for the big leaves; don't warn per compile
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        with _trace.span("compile",
                         {"tier": "aot", "chunk": int(n_chunk)}
                         if _trace.enabled() else None):
            comp = _exported_executable(
                fn, shared_args, structs, shared_sig, task_sig, n_chunk
            )
            if comp is None:
                comp = fn.lower(shared_args, structs).compile()
    _record("aot_misses", time.perf_counter() - t0)
    with _LOCK:
        return _AOT_CACHE.setdefault(key, comp)


def prewarm(fn, shared_args, task_like, n_chunk=None, shared_sig=None):
    """AOT-prewarm ``fn`` for an explicit task shape, with NO task data.

    The public entry point for shape-driven warmup (the serving
    registry's bucket prewarm): ``task_like`` is a pytree whose leaves
    are arrays OR ``jax.ShapeDtypeStruct``s — only ``.shape``/``.dtype``
    are read — and whose leading axis is the chunk (overridable via
    ``n_chunk``). Compilation goes through the same memo + disk layers
    as live dispatch (:func:`aot_executable`), so a later real call of
    the same shape is a pure in-process cache hit, and a warm-disk
    process skips tracing and XLA compilation entirely. Returns the
    compiled executable.
    """
    import jax

    if n_chunk is None:
        leaves = jax.tree_util.tree_leaves(task_like)
        if not leaves:
            raise ValueError("prewarm needs at least one task leaf")
        n_chunk = int(leaves[0].shape[0])
    return aot_executable(
        fn, shared_args, task_like, n_chunk, shared_sig=shared_sig
    )


_SOURCE_DIGEST = None


def _source_digest():
    """Digest of every .py file in the skdist_tpu package (computed
    once per process, ~ms). Part of the export filename: structural
    keys name WHAT a kernel computes, not HOW — a source edit that
    changes kernel math under an unchanged structural key must
    invalidate the serialized program, or a warm cache directory would
    silently serve stale math across a package upgrade. (The XLA tier
    keys on HLO bytes and self-invalidates; this tier exists to skip
    producing the HLO, so it needs its own invalidation basis.)
    Over-invalidates on unrelated edits, which a cache may."""
    global _SOURCE_DIGEST
    if _SOURCE_DIGEST is None:
        import hashlib

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    path = os.path.join(dirpath, name)
                    h.update(path[len(root):].encode())
                    try:
                        with open(path, "rb") as f:
                            h.update(f.read())
                    except OSError:
                        h.update(b"?")
        _SOURCE_DIGEST = h.hexdigest()[:16]
    return _SOURCE_DIGEST


def _export_path(keystr, shared_sig, task_sig, n_chunk):
    import hashlib

    import jax

    payload = repr((keystr, shared_sig, task_sig, n_chunk,
                    jax.__version__, _source_digest()))
    h = hashlib.sha256(payload.encode()).hexdigest()[:32]
    return os.path.join(_DISK_DIR, "aot_exports", h + ".jaxexp")


def _exported_executable(fn, shared_args, structs, shared_sig, task_sig,
                         n_chunk):
    """The export disk layer: serialized AOT programs next to the XLA
    disk cache, so a warm-disk process skips PYTHON TRACING as well as
    XLA compilation — the two costs that dominate service cold-start.

    Active only when (a) the on-disk cache is enabled, (b) the jit
    entry carries a process-stable structural key, and (c) the run is
    single-process (exported device assignments don't transplant
    across multi-process topologies). First process: traces once via
    ``jax.export``, persists the serialized program, and compiles the
    EXPORTED form — both processes then execute byte-identical
    programs, and the exported form's XLA compile is what the disk
    cache holds, so the warm process's compile is a pure cache read.
    Any failure (un-exportable program — e.g. some Pallas custom
    calls — version skew, disk trouble) returns None and the caller
    falls back to the direct lower+compile path.
    """
    ent = _JIT_EXPORT_KEY.get(fn)
    if _DISK_DIR is None or ent is None:
        return None
    keystr, donate = ent
    try:
        import jax
        from jax import export as jexport

        if jax.process_count() > 1:
            return None
        path = _export_path(keystr, shared_sig, task_sig, n_chunk)
        if os.path.exists(path):
            with open(path, "rb") as f:
                exp = jexport.deserialize(bytearray(f.read()))
            _record("aot_export_hits")
        else:
            exp = jexport.export(fn)(shared_args, structs)
            blob = exp.serialize()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            _record("aot_export_writes")
        jit_kwargs = {"donate_argnums": (1,)} if donate else {}
        return (
            jax.jit(exp.call, **jit_kwargs)
            .lower(shared_args, structs).compile()
        )
    except Exception as exc:
        warnings.warn(
            f"compile_cache export layer disabled for this program "
            f"({type(exc).__name__}: {exc}); falling back to direct "
            "compilation"
        )
        return None


def shape_sig(tree):
    import jax

    return tuple(
        (tuple(l.shape), str(l.dtype)) for l in jax.tree_util.tree_leaves(tree)
    )
