"""
Mesh construction helpers: single-host, multi-host (DCN × ICI), and the
2D tasks × data layout the estimators use.

The reference's "cluster" was a Spark deployment reached through one
SparkContext. Here the cluster is a ``jax.sharding.Mesh``:

- single host: all local devices on one 'tasks' axis (optionally split
  with a 'data' axis for row-sharding big X);
- multi-host: ``jax.distributed.initialize`` (the driver's analogue of
  spark-submit) makes every host see the global device set; the same
  SPMD program then runs on each host with the mesh spanning hosts.
  Lay the 'data' axis along ICI (fast all-reduce of gram/gradient
  partials) and the 'tasks' axis across DCN (embarrassingly parallel —
  no cross-task traffic), which is exactly what
  ``create_hybrid_device_mesh`` produces.
"""

import itertools
import json
import logging
import os
import re
import time

import numpy as np

from . import faults
from ..obs import metrics as obs_metrics, trace as obs_trace

__all__ = [
    "initialize_cluster",
    "task_data_mesh",
    "multihost_task_mesh",
    "match_partition_rules",
    "STREAM_BLOCK_RULES",
    "ElasticMeshManager",
    "HeartbeatFileProbe",
    "KVStoreHeartbeatProbe",
    "MaintenanceEventProbe",
    "combine_probes",
]

logger = logging.getLogger("skdist_tpu.mesh")

#: per-process ordinal for elastic managers' registry gauge labels
_MESH_IDS = itertools.count()


def initialize_cluster(coordinator_address=None, num_processes=None,
                       process_id=None, **jax_kwargs):
    """Join this host to a multi-host JAX cluster (no-op if already
    initialised or single-host). Wrapper over jax.distributed.

    ``jax_kwargs`` pass through to ``jax.distributed.initialize`` —
    on ELASTIC fleets raise ``service_max_missing_heartbeats`` (and
    the client twin) well above the default: the coordination
    service's fail-fast otherwise ABORTS every surviving process
    ~100s after a peer dies, while the elastic layer's epoch
    agreement is the membership authority that actually handles the
    loss."""
    import jax

    if num_processes in (None, 0, 1):
        return
    # Multi-process collectives on the CPU backend need an explicit
    # cross-process transport (jax >= 0.4.34 ships gloo but defaults to
    # 'none', and the first cross-process device_put then fails with
    # "Multiprocess computations aren't implemented on the CPU
    # backend"). Harmless on TPU/GPU: the knob only shapes CPU client
    # construction. Must run before the backend is instantiated.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - jaxlib without the knob/gloo
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **jax_kwargs,
        )
    except TypeError:
        # jax's PUBLIC wrapper lags the internal surface: the heartbeat
        # tolerance knobs live on global_state.initialize (which the
        # wrapper forwards to verbatim after a backends-uninitialized
        # check we replicate here)
        from jax._src import distributed as _dist
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            raise RuntimeError(
                "initialize_cluster must run before any JAX computation"
            ) from None
        _dist.global_state.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **jax_kwargs,
        )


def task_data_mesh(devices=None, data_axis_size=1):
    """2D mesh ('tasks', 'data') over the given (default: all) devices.

    ``data_axis_size`` devices cooperate on each fit (row-sharded X,
    psum'd reductions); the remaining factor fans tasks out.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if data_axis_size < 1 or n % data_axis_size != 0:
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide device count {n}"
        )
    arr = np.array(devices).reshape(n // data_axis_size, data_axis_size)
    return Mesh(arr, ("tasks", "data"))


def multihost_task_mesh(data_axis_size=None):
    """Global 2D mesh for multi-host runs: 'data' along each host's
    local devices (ICI), 'tasks' across hosts × leftover local factor
    (DCN). On a single-host process this deterministically degenerates
    to :func:`task_data_mesh`; in a genuine multi-host run any
    construction failure propagates loudly instead of silently falling
    back to a single-host mesh (which would wedge the SPMD program the
    moment other hosts enter the collective).

    ``data_axis_size`` may exceed the local device count when it is a
    multiple of it: the 'data' axis then SPANS processes (e.g. 4 hosts
    × 2 devices with ``data_axis_size=4`` → each fit's row sharding
    crosses 2 hosts). Per-fit reductions (gram/gradient psums) then
    ride DCN for the cross-host hop — legitimate when X is too big for
    one host's devices, but prefer keeping 'data' within a host and
    fanning 'tasks' across hosts when the workload allows it.
    """
    import jax

    local = jax.local_device_count()
    if data_axis_size is None:
        data_axis_size = local
    n_hosts = jax.process_count()
    n_global = local * n_hosts
    within_host = data_axis_size >= 1 and local % data_axis_size == 0
    cross_host = (
        data_axis_size > local
        and data_axis_size % local == 0
        and n_global % data_axis_size == 0
    )
    if not (within_host or cross_host):
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide the local "
            f"device count {local}, or be a multiple of it that divides "
            f"the global device count {n_global}"
        )
    if n_hosts == 1:
        return task_data_mesh(data_axis_size=data_axis_size)
    from jax.sharding import Mesh

    # Deterministic construction (create_hybrid_device_mesh assumes
    # slice-granule topologies and rejects common pod slices): order
    # the global devices by (process, device id) so each contiguous
    # data_axis_size group covers whole processes — within-host groups
    # keep 'data'-axis collectives (gram/gradient psums) on ICI; a
    # cross-host group spans the minimal number of adjacent processes.
    # The 'tasks' axis spans processes over DCN, which is fine because
    # tasks never talk to each other.
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    arr = np.array(devices).reshape(-1, data_axis_size)
    return Mesh(arr, ("tasks", "data"))


# ---------------------------------------------------------------------------
# declarative named-axis partition rules
# ---------------------------------------------------------------------------

#: Default partition-rule table for streamed data blocks: the design
#: matrix (dense ``X`` or its packed-CSR children ``X/0``/``X/1``) and
#: the per-row vectors (labels, sample weights, fold ids) row-shard
#: onto the mesh 'data' axis; anything unmatched — and every scalar,
#: regardless of rules (the SGD epoch/block clocks) — replicates.
#: Ordered first-match-wins, same contract as the exemplar regex
#: partition tables over named param trees.
STREAM_BLOCK_RULES = (
    (r"(^|/)X($|/)", ("data",)),
    (r"(^|/)(y|sw|fold)($|/)", ("data",)),
    # streamed GBDT margin carry F is (lanes, rows, classes): lane axis
    # replicated (each task lane gathers its own slice), rows sharded
    # on 'data' alongside the binned X block they were computed from
    (r"(^|/)F($|/)", (None, "data")),
)


def _leaf_path_name(path):
    """'/'-joined human name of a pytree leaf path (dict keys, attr
    names, sequence/flattened indices)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):  # DictKey / FlattenedIndexKey
            parts.append(str(k.key))
        elif hasattr(k, "name"):  # GetAttrKey
            parts.append(str(k.name))
        elif hasattr(k, "idx"):  # SequenceKey
            parts.append(str(k.idx))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules, tree, default=()):
    """Declarative named-axis placement: map every leaf of ``tree`` to a
    ``PartitionSpec`` by regex-matching its '/'-joined tree path against
    ``rules`` — an ordered ``(pattern, spec)`` table, first match wins
    (``re.search`` semantics). Specs may be ``PartitionSpec`` instances
    or plain tuples of axis names (``("data",)``); scalar leaves always
    replicate regardless of rules (a scalar has no axis to shard).

    ``default`` is the spec for unmatched non-scalar leaves (replicate
    by default); pass ``default=None`` to make an unmatched leaf a
    loud ``ValueError`` naming the path — the strict mode for param
    trees where silent replication would hide a placement bug.

    Returns a tree of ``PartitionSpec`` with the same structure as
    ``tree`` — the declarative replacement for hand-plumbed per-leaf
    sharding decisions (consumed by ``prepare_streamed`` /
    ``_block_shardings`` on 2D (task × data) meshes).
    """
    import jax
    from jax.sharding import PartitionSpec

    def to_spec(s):
        return s if isinstance(s, PartitionSpec) else PartitionSpec(*s)

    compiled = [(re.compile(pat), to_spec(spec)) for pat, spec in rules]
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for path, leaf in flat:
        name = _leaf_path_name(path)
        if getattr(leaf, "ndim", 0) == 0:
            specs.append(PartitionSpec())
            continue
        for pat, spec in compiled:
            if pat.search(name):
                specs.append(spec)
                break
        else:
            if default is None:
                raise ValueError(
                    f"no partition rule matches tree path {name!r}"
                )
            specs.append(to_spec(default))
    return jax.tree_util.tree_unflatten(treedef, specs)


# ---------------------------------------------------------------------------
# elastic meshes (preemptible capacity)
# ---------------------------------------------------------------------------

class ElasticMeshManager:
    """Shrink / resume / re-grow policy for a mesh on preemptible
    capacity — the analogue of Spark dynamic allocation plus executor
    loss handling (the driver kept scheduling on the executors that
    remained, and took preempted ones back when the cluster returned
    them).

    The manager owns the FULL device roster and a partition of it into
    *participants* — the units that are preempted and restored together
    (a host's local devices on multi-process meshes; individual
    devices, or ``group_size`` blocks, on a single-controller mesh).
    Three calls drive the state machine, all invoked by the elastic
    backend (``TPUBackend(elastic=...)``), never by user code:

    - :meth:`on_preempted` — a round classified PREEMPTED: probe which
      participants are lost and rebuild the mesh over the survivors.
      Returns the new (shrunken) mesh, or None when the probe says the
      current mesh already matches (the caller still re-places device
      state either way — preemption presumes it lost).
    - :meth:`maybe_regrow` — called at round boundaries while degraded:
      when the probe reports capacity back, rebuild the larger (up to
      full) mesh. Returns the new mesh or None.
    - :attr:`degraded` — whether the current mesh is smaller than full.

    **Shrink geometry.** Largest-divisor re-layout on BOTH axes: the
    shrunken layout is the (task extent, data size) pair maximising
    devices used, with the task extent a divisor of the FULL task
    extent and the data size a divisor of the full 'data' axis. Ties
    prefer the larger data size, so the per-fit psum geometry — and
    with it bitwise parity against the full mesh — is preserved
    whenever the survivors allow it; only when fewer than
    ``data_axis_size`` devices survive per slot does the data axis
    itself shrink (previously a hard error). The divisor rule is what
    keeps every task axis laid out for the full mesh — padded carries,
    slot-aligned chunks, streamed task trees — placeable on the
    shrunken mesh without re-padding: anything divisible by the full
    extent is divisible by each of its divisors.

    **Probing.** ``probe`` is the seam to real preemption signals
    (plant notifications, heartbeat loss, device health): a callable
    returning the set of currently-LOST participant ids. The default
    consults the installed fault injector's ``lost_participants()``
    (deterministic tests/smokes) and reports nothing lost otherwise —
    on real clusters the PREEMPTED classification itself is the loss
    signal and the operator wires a probe.

    **Multi-host.** ``cluster`` (a dict of ``initialize_cluster``
    kwargs) is the re-init seam for meshes spanning processes: when
    capacity returns, :meth:`rebuild_cluster` tears down and re-joins
    the jax.distributed cluster before the mesh is rebuilt. Today's
    in-process elastic path covers single-controller meshes (a
    shrunken local device set); the multi-process round loop stays
    fail-loud (its collectives cannot be re-synchronised mid-dispatch)
    and resumes through durable checkpoints on restart.
    """

    def __init__(self, devices=None, axis_name="tasks", data_axis_size=1,
                 group_size=None, probe=None, cluster=None,
                 coordinate=None, agree_timeout_s=10.0,
                 kv_namespace="skdist-elastic", heartbeat=None):
        import jax

        if devices is None:
            devices = jax.devices()
        self.full_devices = list(devices)
        self.axis_name = axis_name
        self.data_axis_size = max(1, int(data_axis_size))
        if len(self.full_devices) % self.data_axis_size:
            raise ValueError(
                f"data_axis_size={self.data_axis_size} must divide the "
                f"device count {len(self.full_devices)}"
            )
        self.full_extent = len(self.full_devices) // self.data_axis_size
        self._probe = probe
        self.cluster = dict(cluster) if cluster else None
        # participant partition: by process on multi-process rosters,
        # else group_size blocks (default 1 device = 1 participant)
        n_proc = len({d.process_index for d in self.full_devices})
        self._by_process = group_size is None and n_proc > 1
        if self._by_process:
            self._pid_of = {
                id(d): d.process_index for d in self.full_devices
            }
        else:
            gs = max(1, int(group_size or 1))
            self._pid_of = {
                id(d): i // gs for i, d in enumerate(self.full_devices)
            }
        self.participant_ids = sorted(set(self._pid_of.values()))
        self.current_extent = self.full_extent
        self.current_data = self.data_axis_size
        #: epoch agreement (multi-process coordinated resume): on by
        #: default exactly when participants ARE processes — the only
        #: roster whose loss tears a jax.distributed collective
        self.coordinate = (self._by_process if coordinate is None
                           else bool(coordinate))
        self.agree_timeout_s = float(agree_timeout_s)
        self.kv_namespace = str(kv_namespace)
        self._epoch = 0
        #: participants an epoch agreement declared lost: they stay
        #: lost (no regrow into a dead process) until an operator
        #: ``probe=`` positively reports them back
        self._coordinated_lost = set()
        self._heartbeat = heartbeat
        #: shrink/regrow log: dicts with kind, lost, extents, wall time
        self.events = []
        #: the `mesh` label of this manager's registry gauge — two
        #: elastic backends in one process must not overwrite each
        #: other's extent readings last-writer-wins
        self._obs_id = f"mesh-{next(_MESH_IDS)}"

    # ------------------------------------------------------------------
    @property
    def degraded(self):
        return (self.current_extent < self.full_extent
                or self.current_data < self.data_axis_size)

    def _probe_lost(self):
        """Currently-lost participant ids (a frozenset). An operator
        ``probe=`` is authoritative — a participant it stops reporting
        is considered BACK, including one an epoch agreement declared
        lost. Without a probe, agreement verdicts persist (a dead
        process cannot rejoin a collective on its own) and the default
        consults the installed fault injector."""
        if self._probe is not None:
            lost = frozenset(self._probe())
            self._coordinated_lost &= set(lost)
            return lost
        inj = faults.active_injector()
        probe = getattr(inj, "lost_participants", None)
        lost = frozenset(probe()) if callable(probe) else frozenset()
        return lost | frozenset(self._coordinated_lost)

    def beat(self):
        """Stamp this process's participant heartbeat(s) (``heartbeat=``
        — typically the same :class:`HeartbeatFileProbe` /
        :class:`KVStoreHeartbeatProbe` instance other participants
        probe). Called by the elastic backend at dispatch boundaries;
        a no-op without a heartbeat sink."""
        hb = self._heartbeat
        if hb is None:
            return
        import jax

        try:
            if self._by_process:
                hb.beat(int(jax.process_index()))
            else:
                for p in self.participant_ids:
                    hb.beat(p)
        except Exception as exc:  # a flaky beat must not fail a round
            faults.log_suppressed("ElasticMeshManager.beat", exc,
                                  level=logging.DEBUG)

    def _survivors(self, lost):
        return [d for d in self.full_devices
                if self._pid_of[id(d)] not in lost]

    def _fit_layout(self, n_survivors):
        """Largest-divisor re-layout on BOTH axes (see class
        docstring): the ``(task extent, data size)`` pair maximising
        devices used, the extent a divisor of the full task extent and
        the data size a divisor of the full 'data' axis; ties prefer
        the larger data size (preserving the psum geometry and bitwise
        parity with the full mesh whenever survivors allow). Returns
        ``(0, 0)`` when even one task slot cannot be formed."""
        best = (0, 0)
        for d in range(1, self.data_axis_size + 1):
            if self.data_axis_size % d:
                continue
            for t in range(1, self.full_extent + 1):
                if self.full_extent % t or t * d > n_survivors:
                    continue
                if (t * d, d) > (best[0] * best[1], best[1]):
                    best = (t, d)
        return best

    def _build(self, extent, dsize, survivors):
        from jax.sharding import Mesh

        picked = survivors[: extent * dsize]
        if self.data_axis_size > 1:
            # keep the 2D axis names even at dsize == 1 so compiled
            # programs and PartitionSpecs referencing 'data' stay valid
            arr = np.array(picked).reshape(extent, dsize)
            return Mesh(arr, (self.axis_name, "data"))
        return Mesh(np.array(picked), (self.axis_name,))

    def _resize(self, kind, lost):
        survivors = self._survivors(lost)
        extent, dsize = self._fit_layout(len(survivors))
        if extent == 0:
            raise RuntimeError(
                f"elastic mesh cannot shrink below one task slot: "
                f"{len(survivors)} surviving device(s) for "
                f"data_axis_size={self.data_axis_size} (lost "
                f"participants: {sorted(lost)})"
            )
        if (extent, dsize) == (self.current_extent, self.current_data):
            return None
        mesh = self._build(extent, dsize, survivors)
        self.events.append({
            "kind": kind, "lost": sorted(lost),
            "from_extent": self.current_extent, "to_extent": extent,
            "from_data": self.current_data, "to_data": dsize,
            "t": time.time(),
        })
        logger.warning(
            "elastic mesh %s: task extent %d -> %d, data axis %d -> %d "
            "(lost participants: %s)", kind, self.current_extent, extent,
            self.current_data, dsize, sorted(lost) or "none",
        )
        self.current_extent = extent
        self.current_data = dsize
        faults.record(
            "elastic_shrinks" if kind == "shrink" else "elastic_regrows"
        )
        # the fleet timeline: an elastic resize is an instant on the
        # trace next to the rounds it interrupts, and the mesh extent
        # is a live gauge for the exporters
        obs_trace.instant(
            f"elastic_{kind}",
            {"from": self.events[-1]["from_extent"], "to": extent}
            if obs_trace.enabled() else None,
        )
        obs_metrics.gauge(
            "mesh.task_extent",
            help="current elastic task-axis extent per manager",
        ).set(extent, mesh=self._obs_id)
        obs_metrics.gauge(
            "mesh.data_axis",
            help="current elastic data-axis size per manager",
        ).set(dsize, mesh=self._obs_id)
        return mesh

    # ------------------------------------------------------------------
    def on_preempted(self):
        """A PREEMPTED round: rebuild over the survivors. Returns the
        shrunken mesh or None when the extent is unchanged (the caller
        re-places shared state either way)."""
        return self._resize("shrink", self._probe_lost())

    @property
    def can_coordinate(self):
        """Whether :meth:`coordinated_resume` is available: opted in,
        process-partitioned roster, and a live jax.distributed KV
        client to agree through."""
        return (self.coordinate and self._by_process
                and _kv_client() is not None)

    def coordinated_resume(self, local_prefix):
        """Epoch agreement for a PREEMPTED multi-process round: the
        survivors agree on **(epoch, gathered-task-prefix, survivor
        roster)** through the jax.distributed KV store, then the mesh
        re-forms over the survivors' devices — so a multi-process
        search resumes mid-round instead of failing loud to a durable
        checkpoint restart.

        Protocol (every surviving process runs it symmetrically):

        1. bump the per-manager epoch (survivors see the same fault
           sequence, so epochs advance in lockstep) and publish this
           process's contiguous gathered prefix under
           ``{ns}/e{epoch}/p{pid}``;
        2. blocking-get every other participant's key with the
           ``agree_timeout_s`` budget — a process that never publishes
           within it is DECLARED LOST (the KV silence doubles as the
           preemption probe; a configured ``probe=`` / injector signal
           merges in);
        3. the agreed resume prefix is the MIN over the survivors'
           prefixes (SPMD lockstep makes them equal in practice; min
           is the safe direction — re-running a gathered task is
           correct, skipping an ungathered one is not);
        4. the mesh rebuilds over the survivors at the
           largest-divisor task extent (the ordinary shrink
           geometry). New collectives then compile against the
           survivor mesh — the collective "re-forms" lazily through
           the same structural-cache path every elastic resize uses.

        Returns ``(agreed_prefix, mesh_or_None)`` (None: extent
        unchanged — a transient where everyone responded; the caller
        still re-places shared state).

        Caveats, documented honestly: the agreement rides the
        EXISTING distributed service, so it requires the coordinator
        process to survive (coordinator loss raises, and the caller
        falls back to the fail-loud checkpoint remedy); and a
        participant publishing within epsilon of a peer's timeout
        expiry can be declared lost by one survivor and seen by
        another — the timeout is the roster authority, size it well
        above the fleet's straggler spread. Lost participants stay
        lost (no regrow) until an operator ``probe=`` reports them
        back; re-admitting a RESTARTED process goes through the
        ``cluster=`` re-``initialize_cluster`` seam
        (:meth:`rebuild_cluster`) at regrow time."""
        import jax

        client = _kv_client()
        if client is None:
            raise RuntimeError(
                "coordinated elastic resume needs the jax.distributed "
                "KV store; initialize_cluster was never called (or the "
                "coordinator is gone)"
            )
        self._epoch += 1
        epoch = self._epoch
        me = int(jax.process_index())
        ns = f"{self.kv_namespace}/e{epoch}"
        # the trace context rides the SAME KV round trip as the prefix:
        # every survivor publishes its active context (or a fresh one),
        # and all adopt the minimum-id survivor's trace id — so the
        # stitched multi-process trace shows ONE epoch-agreement line
        # across every process's track instead of per-process orphans
        my_ctx = obs_trace.current_context() or (
            obs_trace.new_context() if obs_trace.enabled() else None
        )
        client.key_value_set(
            f"{ns}/p{me}",
            json.dumps({"prefix": int(local_prefix), "trace": my_ctx}),
            allow_overwrite=True,
        )
        prefixes = {me: int(local_prefix)}
        traces = {me: my_ctx}
        lost = set()
        timeout_ms = max(1, int(self.agree_timeout_s * 1e3))
        for pid in self.participant_ids:
            if pid == me:
                continue
            try:
                raw = client.blocking_key_value_get(
                    f"{ns}/p{pid}", timeout_ms
                )
                peer = json.loads(raw)
                prefixes[pid] = int(peer["prefix"])
                traces[pid] = peer.get("trace")
            except Exception:
                lost.add(pid)
        lost |= set(self._probe_lost())
        lost.discard(me)
        self._coordinated_lost |= lost
        survivors = sorted(set(prefixes) - lost)
        agreed = min(prefixes[pid] for pid in survivors)
        faults.record("elastic_epoch_agreements")
        self.events.append({
            "kind": "epoch_agreement", "epoch": epoch,
            "prefix": int(agreed), "survivors": survivors,
            "lost": sorted(lost), "t": time.time(),
        })
        logger.warning(
            "elastic epoch %d agreement: survivors=%s lost=%s -> resume "
            "from task prefix %d", epoch, survivors, sorted(lost), agreed,
        )
        agreed_ctx = next(
            (traces[pid] for pid in survivors if traces.get(pid)), None
        )
        with obs_trace.use_context(agreed_ctx):
            obs_trace.instant(
                "elastic_epoch_agreement",
                {"epoch": epoch, "prefix": int(agreed),
                 "survivors": len(survivors), "lost": len(lost)}
                if obs_trace.enabled() else None,
            )
        mesh = self._resize("shrink", frozenset(self._coordinated_lost)) \
            if lost else None
        return int(agreed), mesh

    def maybe_regrow(self):
        """Round-boundary check while degraded: when the probe reports
        capacity back, rebuild the larger mesh (re-joining the cluster
        first where configured). Returns the new mesh or None."""
        if not self.degraded:
            return None
        lost = self._probe_lost()
        survivors = self._survivors(lost)
        extent, dsize = self._fit_layout(len(survivors))
        if extent * dsize <= self.current_extent * self.current_data:
            return None
        if self.cluster is not None:
            self.rebuild_cluster()
        return self._resize("regrow", lost)

    def rebuild_cluster(self):
        """Re-join the jax.distributed cluster (the multi-host 'regrow'
        leg: restored hosts re-initialize into the global device set).
        A no-op failure is logged, not fatal — the local device roster
        still regrows."""
        import jax

        try:
            jax.distributed.shutdown()
        except Exception as exc:  # not initialised / already down
            faults.log_suppressed("ElasticMeshManager.shutdown", exc,
                                  level=logging.DEBUG)
        try:
            initialize_cluster(**self.cluster)
        except Exception as exc:
            faults.log_suppressed("ElasticMeshManager.reinit", exc)


def _kv_client():
    """The jax.distributed KV-store client, or None when the cluster
    was never initialized (single-controller runs)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax without the module
        return None


# ---------------------------------------------------------------------------
# production preemption probes (the manager's `probe=` seam)
# ---------------------------------------------------------------------------

class HeartbeatFileProbe:
    """Heartbeat-file liveness for process participants: every
    participant :meth:`beat`\\ s its file (an mtime touch on shared
    storage) at dispatch boundaries, and the probe reports any
    participant whose file is missing or staler than ``stale_s`` as
    LOST. The plainest production probe — no coordinator dependency,
    so it keeps working through the exact failures it detects. Pass
    the same instance as both ``heartbeat=`` (this process beats) and
    ``probe=`` (this process judges the others) of an
    :class:`ElasticMeshManager`. Beat once at startup: a participant
    that never wrote its file reads as lost, which is the right
    default for a worker that never came up."""

    def __init__(self, directory, participants, stale_s=30.0,
                 clock=time.time):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.participants = sorted(int(p) for p in participants)
        self.stale_s = float(stale_s)
        self._clock = clock

    def path(self, participant):
        return os.path.join(self.directory,
                            f"participant-{int(participant)}.hb")

    def beat(self, participant):
        p = self.path(participant)
        with open(p, "a", encoding="utf-8"):
            pass
        now = self._clock()
        os.utime(p, (now, now))

    def __call__(self):
        now = self._clock()
        lost = set()
        for p in self.participants:
            try:
                mtime = os.stat(self.path(p)).st_mtime
            except OSError:
                lost.add(p)
                continue
            if now - mtime > self.stale_s:
                lost.add(p)
        return lost


class KVStoreHeartbeatProbe:
    """Heartbeats through the jax.distributed KV store: each process
    :meth:`beat`\\ s a wall-clock stamp under its participant key;
    the probe reports missing/stale stamps as lost. The zero-extra-
    infrastructure variant of :class:`HeartbeatFileProbe` for fleets
    already running a coordinator — with the same caveat the epoch
    agreement carries: it shares fate with the coordinator process."""

    def __init__(self, participants, stale_s=30.0,
                 namespace="skdist-hb", clock=time.time):
        self.participants = sorted(int(p) for p in participants)
        self.stale_s = float(stale_s)
        self.namespace = str(namespace)
        self._clock = clock

    def _key(self, participant):
        return f"{self.namespace}/p{int(participant)}"

    def beat(self, participant):
        client = _kv_client()
        if client is None:
            raise RuntimeError(
                "KVStoreHeartbeatProbe needs an initialized "
                "jax.distributed cluster"
            )
        client.key_value_set(self._key(participant),
                             repr(float(self._clock())),
                             allow_overwrite=True)

    def __call__(self):
        client = _kv_client()
        if client is None:
            return set(self.participants)
        now = self._clock()
        lost = set()
        for p in self.participants:
            try:
                raw = client.blocking_key_value_get(self._key(p), 50)
                if now - float(raw) > self.stale_s:
                    lost.add(p)
            except Exception:
                lost.add(p)
        return lost


class MaintenanceEventProbe:
    """Pluggable maintenance-event hook: ``hook()`` returns the
    participant ids a platform notice says are being (or about to be)
    preempted — e.g. a poll of the cloud metadata maintenance-event
    endpoint, or a callback queue an operator daemon feeds. Each
    report is HELD for ``hold_s`` so a one-shot notice outlives the
    round that happens to read it; after the hold the participant is
    presumed back (pair with a heartbeat probe via
    :func:`combine_probes` when "gone" must be observed, not
    presumed)."""

    def __init__(self, hook, hold_s=120.0, clock=time.time):
        self.hook = hook
        self.hold_s = float(hold_s)
        self._clock = clock
        self._until = {}

    def __call__(self):
        now = self._clock()
        for p in (self.hook() or ()):
            self._until[int(p)] = now + self.hold_s
        return {p for p, t in self._until.items() if t > now}


def combine_probes(*probes):
    """One probe from many: the union of every probe's lost set (a
    participant is lost if ANY signal says so — heartbeat silence OR a
    maintenance notice)."""

    def combined():
        lost = set()
        for probe in probes:
            lost |= set(probe())
        return lost

    return combined
