"""
Mesh construction helpers: single-host, multi-host (DCN × ICI), and the
2D tasks × data layout the estimators use.

The reference's "cluster" was a Spark deployment reached through one
SparkContext. Here the cluster is a ``jax.sharding.Mesh``:

- single host: all local devices on one 'tasks' axis (optionally split
  with a 'data' axis for row-sharding big X);
- multi-host: ``jax.distributed.initialize`` (the driver's analogue of
  spark-submit) makes every host see the global device set; the same
  SPMD program then runs on each host with the mesh spanning hosts.
  Lay the 'data' axis along ICI (fast all-reduce of gram/gradient
  partials) and the 'tasks' axis across DCN (embarrassingly parallel —
  no cross-task traffic), which is exactly what
  ``create_hybrid_device_mesh`` produces.
"""

import numpy as np

__all__ = [
    "initialize_cluster",
    "task_data_mesh",
    "multihost_task_mesh",
]


def initialize_cluster(coordinator_address=None, num_processes=None,
                       process_id=None):
    """Join this host to a multi-host JAX cluster (no-op if already
    initialised or single-host). Wrapper over jax.distributed."""
    import jax

    if num_processes in (None, 0, 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def task_data_mesh(devices=None, data_axis_size=1):
    """2D mesh ('tasks', 'data') over the given (default: all) devices.

    ``data_axis_size`` devices cooperate on each fit (row-sharded X,
    psum'd reductions); the remaining factor fans tasks out.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if data_axis_size < 1 or n % data_axis_size != 0:
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide device count {n}"
        )
    arr = np.array(devices).reshape(n // data_axis_size, data_axis_size)
    return Mesh(arr, ("tasks", "data"))


def multihost_task_mesh(data_axis_size=None):
    """Global 2D mesh for multi-host runs: 'data' along each host's
    local devices (ICI), 'tasks' across hosts (DCN)."""
    import jax

    local = jax.local_device_count()
    if data_axis_size is None:
        data_axis_size = local
    try:
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        n_hosts = jax.device_count() // local
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, data_axis_size),
            dcn_mesh_shape=(n_hosts * (local // data_axis_size), 1),
        )
        return Mesh(arr.reshape(-1, data_axis_size), ("tasks", "data"))
    except Exception:
        return task_data_mesh(data_axis_size=data_axis_size)
