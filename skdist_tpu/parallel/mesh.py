"""
Mesh construction helpers: single-host, multi-host (DCN × ICI), and the
2D tasks × data layout the estimators use.

The reference's "cluster" was a Spark deployment reached through one
SparkContext. Here the cluster is a ``jax.sharding.Mesh``:

- single host: all local devices on one 'tasks' axis (optionally split
  with a 'data' axis for row-sharding big X);
- multi-host: ``jax.distributed.initialize`` (the driver's analogue of
  spark-submit) makes every host see the global device set; the same
  SPMD program then runs on each host with the mesh spanning hosts.
  Lay the 'data' axis along ICI (fast all-reduce of gram/gradient
  partials) and the 'tasks' axis across DCN (embarrassingly parallel —
  no cross-task traffic), which is exactly what
  ``create_hybrid_device_mesh`` produces.
"""

import numpy as np

__all__ = [
    "initialize_cluster",
    "task_data_mesh",
    "multihost_task_mesh",
]


def initialize_cluster(coordinator_address=None, num_processes=None,
                       process_id=None):
    """Join this host to a multi-host JAX cluster (no-op if already
    initialised or single-host). Wrapper over jax.distributed."""
    import jax

    if num_processes in (None, 0, 1):
        return
    # Multi-process collectives on the CPU backend need an explicit
    # cross-process transport (jax >= 0.4.34 ships gloo but defaults to
    # 'none', and the first cross-process device_put then fails with
    # "Multiprocess computations aren't implemented on the CPU
    # backend"). Harmless on TPU/GPU: the knob only shapes CPU client
    # construction. Must run before the backend is instantiated.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - jaxlib without the knob/gloo
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def task_data_mesh(devices=None, data_axis_size=1):
    """2D mesh ('tasks', 'data') over the given (default: all) devices.

    ``data_axis_size`` devices cooperate on each fit (row-sharded X,
    psum'd reductions); the remaining factor fans tasks out.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if data_axis_size < 1 or n % data_axis_size != 0:
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide device count {n}"
        )
    arr = np.array(devices).reshape(n // data_axis_size, data_axis_size)
    return Mesh(arr, ("tasks", "data"))


def multihost_task_mesh(data_axis_size=None):
    """Global 2D mesh for multi-host runs: 'data' along each host's
    local devices (ICI), 'tasks' across hosts × leftover local factor
    (DCN). On a single-host process this deterministically degenerates
    to :func:`task_data_mesh`; in a genuine multi-host run any
    construction failure propagates loudly instead of silently falling
    back to a single-host mesh (which would wedge the SPMD program the
    moment other hosts enter the collective).

    ``data_axis_size`` may exceed the local device count when it is a
    multiple of it: the 'data' axis then SPANS processes (e.g. 4 hosts
    × 2 devices with ``data_axis_size=4`` → each fit's row sharding
    crosses 2 hosts). Per-fit reductions (gram/gradient psums) then
    ride DCN for the cross-host hop — legitimate when X is too big for
    one host's devices, but prefer keeping 'data' within a host and
    fanning 'tasks' across hosts when the workload allows it.
    """
    import jax

    local = jax.local_device_count()
    if data_axis_size is None:
        data_axis_size = local
    n_hosts = jax.process_count()
    n_global = local * n_hosts
    within_host = data_axis_size >= 1 and local % data_axis_size == 0
    cross_host = (
        data_axis_size > local
        and data_axis_size % local == 0
        and n_global % data_axis_size == 0
    )
    if not (within_host or cross_host):
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide the local "
            f"device count {local}, or be a multiple of it that divides "
            f"the global device count {n_global}"
        )
    if n_hosts == 1:
        return task_data_mesh(data_axis_size=data_axis_size)
    from jax.sharding import Mesh

    # Deterministic construction (create_hybrid_device_mesh assumes
    # slice-granule topologies and rejects common pod slices): order
    # the global devices by (process, device id) so each contiguous
    # data_axis_size group covers whole processes — within-host groups
    # keep 'data'-axis collectives (gram/gradient psums) on ICI; a
    # cross-host group spans the minimal number of adjacent processes.
    # The 'tasks' axis spans processes over DCN, which is fine because
    # tasks never talk to each other.
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    arr = np.array(devices).reshape(-1, data_axis_size)
    return Mesh(arr, ("tasks", "data"))
