"""
Mesh construction helpers: single-host, multi-host (DCN × ICI), and the
2D tasks × data layout the estimators use.

The reference's "cluster" was a Spark deployment reached through one
SparkContext. Here the cluster is a ``jax.sharding.Mesh``:

- single host: all local devices on one 'tasks' axis (optionally split
  with a 'data' axis for row-sharding big X);
- multi-host: ``jax.distributed.initialize`` (the driver's analogue of
  spark-submit) makes every host see the global device set; the same
  SPMD program then runs on each host with the mesh spanning hosts.
  Lay the 'data' axis along ICI (fast all-reduce of gram/gradient
  partials) and the 'tasks' axis across DCN (embarrassingly parallel —
  no cross-task traffic), which is exactly what
  ``create_hybrid_device_mesh`` produces.
"""

import itertools
import logging
import time

import numpy as np

from . import faults
from ..obs import metrics as obs_metrics, trace as obs_trace

__all__ = [
    "initialize_cluster",
    "task_data_mesh",
    "multihost_task_mesh",
    "ElasticMeshManager",
]

logger = logging.getLogger("skdist_tpu.mesh")

#: per-process ordinal for elastic managers' registry gauge labels
_MESH_IDS = itertools.count()


def initialize_cluster(coordinator_address=None, num_processes=None,
                       process_id=None):
    """Join this host to a multi-host JAX cluster (no-op if already
    initialised or single-host). Wrapper over jax.distributed."""
    import jax

    if num_processes in (None, 0, 1):
        return
    # Multi-process collectives on the CPU backend need an explicit
    # cross-process transport (jax >= 0.4.34 ships gloo but defaults to
    # 'none', and the first cross-process device_put then fails with
    # "Multiprocess computations aren't implemented on the CPU
    # backend"). Harmless on TPU/GPU: the knob only shapes CPU client
    # construction. Must run before the backend is instantiated.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - jaxlib without the knob/gloo
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def task_data_mesh(devices=None, data_axis_size=1):
    """2D mesh ('tasks', 'data') over the given (default: all) devices.

    ``data_axis_size`` devices cooperate on each fit (row-sharded X,
    psum'd reductions); the remaining factor fans tasks out.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if data_axis_size < 1 or n % data_axis_size != 0:
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide device count {n}"
        )
    arr = np.array(devices).reshape(n // data_axis_size, data_axis_size)
    return Mesh(arr, ("tasks", "data"))


def multihost_task_mesh(data_axis_size=None):
    """Global 2D mesh for multi-host runs: 'data' along each host's
    local devices (ICI), 'tasks' across hosts × leftover local factor
    (DCN). On a single-host process this deterministically degenerates
    to :func:`task_data_mesh`; in a genuine multi-host run any
    construction failure propagates loudly instead of silently falling
    back to a single-host mesh (which would wedge the SPMD program the
    moment other hosts enter the collective).

    ``data_axis_size`` may exceed the local device count when it is a
    multiple of it: the 'data' axis then SPANS processes (e.g. 4 hosts
    × 2 devices with ``data_axis_size=4`` → each fit's row sharding
    crosses 2 hosts). Per-fit reductions (gram/gradient psums) then
    ride DCN for the cross-host hop — legitimate when X is too big for
    one host's devices, but prefer keeping 'data' within a host and
    fanning 'tasks' across hosts when the workload allows it.
    """
    import jax

    local = jax.local_device_count()
    if data_axis_size is None:
        data_axis_size = local
    n_hosts = jax.process_count()
    n_global = local * n_hosts
    within_host = data_axis_size >= 1 and local % data_axis_size == 0
    cross_host = (
        data_axis_size > local
        and data_axis_size % local == 0
        and n_global % data_axis_size == 0
    )
    if not (within_host or cross_host):
        raise ValueError(
            f"data_axis_size={data_axis_size} must divide the local "
            f"device count {local}, or be a multiple of it that divides "
            f"the global device count {n_global}"
        )
    if n_hosts == 1:
        return task_data_mesh(data_axis_size=data_axis_size)
    from jax.sharding import Mesh

    # Deterministic construction (create_hybrid_device_mesh assumes
    # slice-granule topologies and rejects common pod slices): order
    # the global devices by (process, device id) so each contiguous
    # data_axis_size group covers whole processes — within-host groups
    # keep 'data'-axis collectives (gram/gradient psums) on ICI; a
    # cross-host group spans the minimal number of adjacent processes.
    # The 'tasks' axis spans processes over DCN, which is fine because
    # tasks never talk to each other.
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    arr = np.array(devices).reshape(-1, data_axis_size)
    return Mesh(arr, ("tasks", "data"))


# ---------------------------------------------------------------------------
# elastic meshes (preemptible capacity)
# ---------------------------------------------------------------------------

class ElasticMeshManager:
    """Shrink / resume / re-grow policy for a mesh on preemptible
    capacity — the analogue of Spark dynamic allocation plus executor
    loss handling (the driver kept scheduling on the executors that
    remained, and took preempted ones back when the cluster returned
    them).

    The manager owns the FULL device roster and a partition of it into
    *participants* — the units that are preempted and restored together
    (a host's local devices on multi-process meshes; individual
    devices, or ``group_size`` blocks, on a single-controller mesh).
    Three calls drive the state machine, all invoked by the elastic
    backend (``TPUBackend(elastic=...)``), never by user code:

    - :meth:`on_preempted` — a round classified PREEMPTED: probe which
      participants are lost and rebuild the mesh over the survivors.
      Returns the new (shrunken) mesh, or None when the probe says the
      current mesh already matches (the caller still re-places device
      state either way — preemption presumes it lost).
    - :meth:`maybe_regrow` — called at round boundaries while degraded:
      when the probe reports capacity back, rebuild the larger (up to
      full) mesh. Returns the new mesh or None.
    - :attr:`degraded` — whether the current mesh is smaller than full.

    **Shrink geometry.** The shrunken task extent is the largest
    divisor of the FULL task extent that the survivors can still
    populate (times the unchanged 'data' axis). The divisor rule is
    what keeps every task axis laid out for the full mesh — padded
    carries, slot-aligned chunks, streamed task trees — placeable on
    the shrunken mesh without re-padding: anything divisible by the
    full extent is divisible by each of its divisors.

    **Probing.** ``probe`` is the seam to real preemption signals
    (plant notifications, heartbeat loss, device health): a callable
    returning the set of currently-LOST participant ids. The default
    consults the installed fault injector's ``lost_participants()``
    (deterministic tests/smokes) and reports nothing lost otherwise —
    on real clusters the PREEMPTED classification itself is the loss
    signal and the operator wires a probe.

    **Multi-host.** ``cluster`` (a dict of ``initialize_cluster``
    kwargs) is the re-init seam for meshes spanning processes: when
    capacity returns, :meth:`rebuild_cluster` tears down and re-joins
    the jax.distributed cluster before the mesh is rebuilt. Today's
    in-process elastic path covers single-controller meshes (a
    shrunken local device set); the multi-process round loop stays
    fail-loud (its collectives cannot be re-synchronised mid-dispatch)
    and resumes through durable checkpoints on restart.
    """

    def __init__(self, devices=None, axis_name="tasks", data_axis_size=1,
                 group_size=None, probe=None, cluster=None):
        import jax

        if devices is None:
            devices = jax.devices()
        self.full_devices = list(devices)
        self.axis_name = axis_name
        self.data_axis_size = max(1, int(data_axis_size))
        if len(self.full_devices) % self.data_axis_size:
            raise ValueError(
                f"data_axis_size={self.data_axis_size} must divide the "
                f"device count {len(self.full_devices)}"
            )
        self.full_extent = len(self.full_devices) // self.data_axis_size
        self._probe = probe
        self.cluster = dict(cluster) if cluster else None
        # participant partition: by process on multi-process rosters,
        # else group_size blocks (default 1 device = 1 participant)
        n_proc = len({d.process_index for d in self.full_devices})
        if group_size is None and n_proc > 1:
            self._pid_of = {
                id(d): d.process_index for d in self.full_devices
            }
        else:
            gs = max(1, int(group_size or 1))
            self._pid_of = {
                id(d): i // gs for i, d in enumerate(self.full_devices)
            }
        self.participant_ids = sorted(set(self._pid_of.values()))
        self.current_extent = self.full_extent
        #: shrink/regrow log: dicts with kind, lost, extents, wall time
        self.events = []
        #: the `mesh` label of this manager's registry gauge — two
        #: elastic backends in one process must not overwrite each
        #: other's extent readings last-writer-wins
        self._obs_id = f"mesh-{next(_MESH_IDS)}"

    # ------------------------------------------------------------------
    @property
    def degraded(self):
        return self.current_extent < self.full_extent

    def _probe_lost(self):
        """Currently-lost participant ids (a frozenset)."""
        if self._probe is not None:
            return frozenset(self._probe())
        inj = faults.active_injector()
        lost = getattr(inj, "lost_participants", None)
        if callable(lost):
            return frozenset(lost())
        return frozenset()

    def _survivors(self, lost):
        return [d for d in self.full_devices
                if self._pid_of[id(d)] not in lost]

    def _fit_extent(self, n_survivors):
        """Largest divisor of the full task extent the survivors can
        populate (see class docstring), or 0 when even one task slot
        cannot be formed."""
        best = 0
        for t in range(1, self.full_extent + 1):
            if self.full_extent % t == 0 and \
                    t * self.data_axis_size <= n_survivors:
                best = t
        return best

    def _build(self, extent, survivors):
        from jax.sharding import Mesh

        picked = survivors[: extent * self.data_axis_size]
        if self.data_axis_size > 1:
            arr = np.array(picked).reshape(extent, self.data_axis_size)
            return Mesh(arr, (self.axis_name, "data"))
        return Mesh(np.array(picked), (self.axis_name,))

    def _resize(self, kind, lost):
        survivors = self._survivors(lost)
        extent = self._fit_extent(len(survivors))
        if extent == 0:
            raise RuntimeError(
                f"elastic mesh cannot shrink below one task slot: "
                f"{len(survivors)} surviving device(s) for "
                f"data_axis_size={self.data_axis_size} (lost "
                f"participants: {sorted(lost)})"
            )
        if extent == self.current_extent:
            return None
        mesh = self._build(extent, survivors)
        self.events.append({
            "kind": kind, "lost": sorted(lost),
            "from_extent": self.current_extent, "to_extent": extent,
            "t": time.time(),
        })
        logger.warning(
            "elastic mesh %s: task extent %d -> %d (lost participants: "
            "%s)", kind, self.current_extent, extent, sorted(lost) or "none",
        )
        self.current_extent = extent
        faults.record(
            "elastic_shrinks" if kind == "shrink" else "elastic_regrows"
        )
        # the fleet timeline: an elastic resize is an instant on the
        # trace next to the rounds it interrupts, and the mesh extent
        # is a live gauge for the exporters
        obs_trace.instant(
            f"elastic_{kind}",
            {"from": self.events[-1]["from_extent"], "to": extent}
            if obs_trace.enabled() else None,
        )
        obs_metrics.gauge(
            "mesh.task_extent",
            help="current elastic task-axis extent per manager",
        ).set(extent, mesh=self._obs_id)
        return mesh

    # ------------------------------------------------------------------
    def on_preempted(self):
        """A PREEMPTED round: rebuild over the survivors. Returns the
        shrunken mesh or None when the extent is unchanged (the caller
        re-places shared state either way)."""
        return self._resize("shrink", self._probe_lost())

    def maybe_regrow(self):
        """Round-boundary check while degraded: when the probe reports
        capacity back, rebuild the larger mesh (re-joining the cluster
        first where configured). Returns the new mesh or None."""
        if not self.degraded:
            return None
        lost = self._probe_lost()
        survivors = self._survivors(lost)
        if self._fit_extent(len(survivors)) <= self.current_extent:
            return None
        if self.cluster is not None:
            self.rebuild_cluster()
        return self._resize("regrow", lost)

    def rebuild_cluster(self):
        """Re-join the jax.distributed cluster (the multi-host 'regrow'
        leg: restored hosts re-initialize into the global device set).
        A no-op failure is logged, not fatal — the local device roster
        still regrows."""
        import jax

        try:
            jax.distributed.shutdown()
        except Exception as exc:  # not initialised / already down
            faults.log_suppressed("ElasticMeshManager.shutdown", exc,
                                  level=logging.DEBUG)
        try:
            initialize_cluster(**self.cluster)
        except Exception as exc:
            faults.log_suppressed("ElasticMeshManager.reinit", exc)
